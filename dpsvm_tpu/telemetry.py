"""Unified run telemetry: the RunTrace recorder and the report renderer.

The reference left its per-phase instrumentation commented out
(``svmTrain.cu:218-293``) and its duality-gap probe dead
(``seq.cpp:352-376``); we resurrected both (utils/timing.py,
ops/diagnostics.py) but they were islands — no single artifact recorded
what a training run *did*. ``RunTrace`` is that artifact: one JSONL
file per run (schema in utils/trace.py, prose in docs/OBSERVABILITY.md)
holding the manifest, a record per host poll, solver events, and a
summary. Every signal in the per-chunk record rides the solvers'
existing packed-stats transfer (solver/driver.py "Poll economics"), so
a traced run performs ZERO additional device->host transfers.

Producers: the shared host driver (solver/driver.host_training_loop —
every path through it: single-device, fused, decomposition, and both
SPMD variants), the shrinking manager (solver/shrink.py), and the
benchmark harnesses (bench.py, bench_convergence.py via
``BENCH_TRACE_OUT``). Consumer: the ``dpsvm report`` CLI subcommand
(this module's ``render_report`` / ``summarize_trace``).

This module never touches a device: ``report`` and the schema
self-check (``python -m dpsvm_tpu.telemetry --selfcheck``) run without
initializing any backend. Callers pass device facts in via ``env``.
"""

from __future__ import annotations

import dataclasses
import math
import time
import weakref
from typing import Dict, List, Optional

from dpsvm_tpu.utils.trace import (TRACE_SCHEMA_VERSION, TraceWriter,
                                   read_trace, validate_trace)

# Every in-flight RunTrace, so emergency exit paths (the stall watchdog's
# os._exit) can stamp a terminal event record before the process dies —
# an abandoned trace with no terminal record is indistinguishable from a
# live run (docs/ROBUSTNESS.md). Weak: a dropped recorder unregisters
# itself.
_OPEN_TRACES: "weakref.WeakSet[RunTrace]" = weakref.WeakSet()


def flush_open_traces(event: str, **extra) -> int:
    """Best-effort: append ``event`` to every still-open trace and close
    it. Called from exit paths that bypass the driver's finally block
    (utils/watchdog.py expiry — a different thread, microseconds before
    os._exit, while the training thread is wedged in a device call, so
    a concurrent write is not a practical concern). Returns the number
    of traces flushed; never raises."""
    count = 0
    for tr in list(_OPEN_TRACES):
        try:
            tr.event(event, **extra)
            tr.close()
            count += 1
        except Exception:
            pass
    return count

# Carry-class -> human solver-path name (the driver keys the manifest on
# the carry type; one table so a new solver fails loudly in tests, not
# silently as its class name).
SOLVER_NAMES = {
    "SMOCarry": "smo",
    "DistCarry": "dist-smo",
    "DecompCarry": "decomp",
    "DistDecompCarry": "dist-decomp",
    "FusedCarry": "fused-pallas",
}


def _config_dict(config) -> dict:
    if config is None:
        return {}
    if dataclasses.is_dataclass(config):
        return dataclasses.asdict(config)
    return dict(config)


class RunTrace:
    """One training run's JSONL recorder.

    Construction writes the manifest; ``chunk``/``event`` append during
    the run; ``summary`` + ``close`` finish it. All record shapes are
    owned here so every producer (driver, shrink manager, benchmarks)
    emits the one schema utils/trace.validate_trace checks.
    """

    def __init__(self, path: str, *, config=None, n: int = 0, d: int = 0,
                 gamma: float = 0.0, solver: str = "unknown",
                 it0: int = 0, env: Optional[dict] = None):
        config_d = _config_dict(config)
        kernel = {
            "kind": config_d.get("kernel", "rbf"),
            "gamma": float(gamma),
            "coef0": float(config_d.get("coef0", 0.0)),
            "degree": int(config_d.get("degree", 3)),
        }
        mesh = {"shards": int(config_d.get("shards", 1)),
                "shard_x": bool(config_d.get("shard_x", True))}
        from dpsvm_tpu import __version__
        self._w = TraceWriter(path)
        self._t0 = time.perf_counter()
        self._it0 = int(it0)
        self._closed = False
        self._w.write({
            "kind": "manifest",
            "schema": TRACE_SCHEMA_VERSION,
            "version": __version__,
            "solver": solver,
            "n": int(n),
            "d": int(d),
            "gamma": float(gamma),
            "kernel": kernel,
            "mesh": mesh,
            "env": dict(env or {"backend": None, "device_kind": None,
                                "device_count": None}),
            "config": config_d,
            "it0": int(it0),
            "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        })
        _OPEN_TRACES.add(self)

    @property
    def path(self) -> str:
        return self._w.path

    def _t(self) -> float:
        return round(time.perf_counter() - self._t0, 6)

    def chunk(self, *, n_iter: int, b_lo: float, b_hi: float,
              n_sv: int = 0, cache_hits: int = 0, cache_misses: int = 0,
              rounds: int = 0,
              phases: Optional[Dict[str, float]] = None,
              **extra) -> None:
        """One host-poll record. Every argument is already on the host
        (the packed-stats read) — recording is file I/O only."""
        rec = {
            "kind": "chunk",
            "n_iter": int(n_iter),
            "b_lo": float(b_lo),
            "b_hi": float(b_hi),
            "gap": float(b_lo) - float(b_hi),
            "n_sv": int(n_sv),
            "cache_hits": int(cache_hits),
            "cache_misses": int(cache_misses),
            "rounds": int(rounds),
            "t": self._t(),
            "phases": {k: round(float(v), 6)
                       for k, v in (phases or {}).items()},
        }
        rec.update(extra)
        self._w.write(rec)

    def event(self, event: str, *, n_iter: int = 0, **extra) -> None:
        """Solver lifecycle marker: checkpoint, program_swap (working-set
        growth), wall_budget, shrink, unshrink."""
        rec = {"kind": "event", "event": str(event),
               "n_iter": int(n_iter), "t": self._t()}
        rec.update(extra)
        self._w.write(rec)

    def summary(self, *, converged: bool, n_iter: int, b: float,
                b_lo: float, b_hi: float, n_sv: int,
                train_seconds: float, cache_hits: int = 0,
                cache_misses: int = 0,
                phases: Optional[Dict[str, float]] = None,
                **extra) -> None:
        iters = int(n_iter) - self._it0
        lookups = int(cache_hits) + int(cache_misses)
        rec = {
            "kind": "summary",
            "converged": bool(converged),
            "n_iter": int(n_iter),
            "iters": iters,
            "iters_per_sec": round(iters / train_seconds, 3)
            if train_seconds > 0 else 0.0,
            "b": float(b),
            "b_lo": float(b_lo),
            "b_hi": float(b_hi),
            "gap": float(b_lo) - float(b_hi),
            "n_sv": int(n_sv),
            "cache_hits": int(cache_hits),
            "cache_misses": int(cache_misses),
            "cache_hit_rate": round(cache_hits / lookups, 6)
            if lookups else None,
            "train_seconds": round(float(train_seconds), 6),
            "phases": {k: round(float(v), 6)
                       for k, v in (phases or {}).items()},
            "t": self._t(),
        }
        rec.update(extra)
        self._w.write(rec)

    def close(self) -> None:
        self._closed = True
        _OPEN_TRACES.discard(self)
        self._w.close()


def load_trace(path: str) -> List[dict]:
    """read + validate; raises ValueError with every problem listed."""
    records = read_trace(path)
    errors = validate_trace(records)
    if errors:
        raise ValueError(f"invalid trace {path}: " + "; ".join(errors))
    return records


def summarize_trace(records: List[dict]) -> dict:
    """The machine-readable digest ``dpsvm report --json`` prints."""
    manifest = records[0] if records else {}
    chunks = [r for r in records if r.get("kind") == "chunk"]
    events = [r for r in records if r.get("kind") == "event"]
    summary = next((r for r in records if r.get("kind") == "summary"),
                   None)
    return {
        "manifest": manifest,
        "summary": summary,
        "n_chunks": len(chunks),
        "events": events,
        "curve": [{"n_iter": c["n_iter"], "gap": c["gap"],
                   "n_sv": c["n_sv"], "t": c["t"]} for c in chunks],
    }


def _fmt_si(v: float) -> str:
    return f"{v:,.0f}" if v >= 100 else f"{v:.3g}"


def _gap_curve(chunks: List[dict], width: int = 60,
               height: int = 10) -> List[str]:
    """ASCII iter-vs-gap plot (log-scale gap). Robust down to a single
    chunk record (the acceptance floor: manifest + >= 1 chunk +
    summary)."""
    pts = [(c["n_iter"], c["gap"]) for c in chunks if c["gap"] > 0]
    if not pts:
        return ["  (no open-gap chunk records to plot)"]
    its = [p[0] for p in pts]
    lgs = [math.log10(p[1]) for p in pts]
    it_lo, it_hi = min(its), max(its)
    lg_lo, lg_hi = min(lgs), max(lgs)
    it_span = max(it_hi - it_lo, 1)
    lg_span = max(lg_hi - lg_lo, 1e-9)
    grid = [[" "] * width for _ in range(height)]
    for it, lg in zip(its, lgs):
        col = min(int((it - it_lo) / it_span * (width - 1)), width - 1)
        row = min(int((lg_hi - lg) / lg_span * (height - 1)), height - 1)
        grid[row][col] = "*"
    lines = []
    for r in range(height):
        lg = lg_hi - r * lg_span / (height - 1 or 1)
        label = f"{10 ** lg:8.1e}" if r in (0, height - 1) else " " * 8
        lines.append(f"  {label} |" + "".join(grid[r]))
    lines.append("  " + " " * 8 + "+" + "-" * width)
    left = f"{it_lo:,}"
    right = f"{it_hi:,}"
    pad = max(width - len(left) - len(right), 1)
    lines.append("  " + " " * 9 + left + " " * pad + right)
    return lines


def _phase_bars(phases: Dict[str, float]) -> List[str]:
    total = sum(phases.values())
    if not phases or total <= 0:
        return ["  (no phase timings recorded)"]
    width = max(len(k) for k in phases)
    lines = []
    for name, sec in sorted(phases.items(), key=lambda kv: -kv[1]):
        frac = sec / total
        bar = "#" * max(int(round(frac * 30)), 1 if sec > 0 else 0)
        lines.append(f"  {name:<{width}}  {sec:8.3f} s  {frac:5.1%}  {bar}")
    return lines


def render_report(records: List[dict], width: int = 60) -> str:
    """The human rendering behind ``dpsvm report``."""
    m = records[0]
    chunks = [r for r in records if r.get("kind") == "chunk"]
    events = [r for r in records if r.get("kind") == "event"]
    s = next((r for r in records if r.get("kind") == "summary"), None)
    k = m["kernel"]
    env = m.get("env") or {}
    out = []
    kern = k["kind"]
    if kern in ("rbf", "poly", "sigmoid"):
        kern += f"(gamma={k['gamma']:g})"
    out.append(f"run: {m['solver']}  {m['n']}x{m['d']}  {kern}  "
               f"shards={m['mesh']['shards']}  "
               f"backend={env.get('backend')} "
               f"{env.get('device_kind') or ''}  "
               f"dpsvm_tpu {m['version']}")
    if s is not None:
        status = "converged" if s["converged"] else "NOT converged"
        out.append(f"result: {status} at iter {s['n_iter']:,} in "
                   f"{s['train_seconds']:.2f} s "
                   f"({_fmt_si(s['iters_per_sec'])} it/s)   "
                   f"gap {s['gap']:.3g}  b={s['b']:.6g}  "
                   f"n_sv={s['n_sv']:,}")
    else:
        out.append("result: (no summary record — run still in flight "
                   "or killed)")
    out.append("")
    out.append("convergence (gap vs iteration, log scale):")
    out.extend(_gap_curve(chunks, width=width))
    out.append("")
    phases = (s or {}).get("phases") or (
        chunks[-1]["phases"] if chunks else {})
    out.append("host-loop phase time:")
    out.extend(_phase_bars(phases))
    out.append("")
    src = s or (chunks[-1] if chunks else None)
    if src is not None:
        lookups = src["cache_hits"] + src["cache_misses"]
        if lookups:
            out.append(f"kernel-row cache: {lookups:,} lookups, hit rate "
                       f"{src['cache_hits'] / lookups:.1%} "
                       f"({src['cache_hits']:,} hits / "
                       f"{src['cache_misses']:,} misses)")
        else:
            out.append("kernel-row cache: off (cache_size=0)")
        if src.get("rounds"):
            out.append(f"decomposition outer rounds: {src['rounds']:,}")
    if events:
        out.append("events: " + ", ".join(
            f"{e['event']}@{e['n_iter']:,}" for e in events))
    out.append(f"chunk polls recorded: {len(chunks)}")
    return "\n".join(out)


def selfcheck(tmp_dir: Optional[str] = None) -> List[str]:
    """Produce a synthetic trace through the real writer, then run it
    through the real validator and renderer. Returns problems (empty =
    OK). Tier-1 (tests/test_telemetry.py) and
    ``python -m dpsvm_tpu.telemetry --selfcheck`` both call this, so a
    schema drift between producer and validator fails loudly in CI."""
    import os
    import tempfile

    with tempfile.TemporaryDirectory(dir=tmp_dir) as td:
        path = os.path.join(td, "selfcheck.jsonl")
        tr = RunTrace(path, config={"kernel": "rbf", "shards": 2,
                                    "shard_x": True, "coef0": 0.0,
                                    "degree": 3},
                      n=1000, d=32, gamma=0.5, solver="smo", it0=0,
                      env={"backend": "cpu", "device_kind": "host",
                           "device_count": 2})
        for i, gap in enumerate((1.5, 0.3, 0.0009)):
            tr.chunk(n_iter=(i + 1) * 512, b_lo=gap / 2, b_hi=-gap / 2,
                     n_sv=100 * (i + 1), cache_hits=i * 10,
                     cache_misses=i * 20, rounds=i,
                     phases={"dispatch": 0.1 * i, "poll": 0.2 * i})
        tr.event("checkpoint", n_iter=1024)
        tr.summary(converged=True, n_iter=1536, b=0.0, b_lo=0.00045,
                   b_hi=-0.00045, n_sv=300, train_seconds=1.5,
                   cache_hits=20, cache_misses=40,
                   phases={"dispatch": 0.3, "poll": 0.6})
        tr.close()
        try:
            records = load_trace(path)
        except ValueError as e:
            return [str(e)]
        problems = []
        digest = summarize_trace(records)
        if digest["n_chunks"] != 3 or digest["summary"] is None:
            problems.append(f"digest mismatch: {digest['n_chunks']} "
                            "chunks or missing summary")
        text = render_report(records)
        for needle in ("run: smo", "converged at iter 1,536",
                       "hit rate 33.3%", "checkpoint@1,024"):
            if needle not in text:
                problems.append(f"report rendering lost {needle!r}")
        return problems


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json
    import sys

    p = argparse.ArgumentParser(prog="python -m dpsvm_tpu.telemetry")
    p.add_argument("--selfcheck", action="store_true",
                   help="writer -> validator -> renderer round-trip on "
                        "a synthetic trace (the CI schema gate)")
    p.add_argument("--validate", default=None, metavar="TRACE",
                   help="validate an existing trace file")
    args = p.parse_args(argv)
    if args.selfcheck:
        problems = selfcheck()
        if problems:
            print("telemetry selfcheck FAILED:", file=sys.stderr)
            for pr in problems:
                print(f"  {pr}", file=sys.stderr)
            return 1
        print("telemetry selfcheck OK "
              f"(schema v{TRACE_SCHEMA_VERSION})")
        return 0
    if args.validate:
        try:
            records = load_trace(args.validate)
        except (OSError, ValueError) as e:
            print(f"INVALID: {e}", file=sys.stderr)
            return 1
        print(json.dumps({"valid": True, "records": len(records)}))
        return 0
    p.print_help()
    return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())

"""Back-compat shim: run telemetry grew into the
``dpsvm_tpu.observability`` package (PR 3 — compile accounting, HBM
watermarks, FLOP/s, live ``report --follow``, ``dpsvm compare``).

Everything PR 1 exported from here still imports from here, and
``python -m dpsvm_tpu.telemetry --selfcheck`` remains the documented
CI schema gate; new code should import ``dpsvm_tpu.observability``
directly (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

from dpsvm_tpu.observability import (SOLVER_NAMES,                # noqa: F401
                                     TRACE_SCHEMA_VERSION, RunTrace,
                                     compare_paths, compare_traces,
                                     flush_open_traces, follow_trace,
                                     host_lanes, load_trace,
                                     load_trace_auto, main,
                                     regressions, render_compare,
                                     render_report,
                                     resolve_trace_path, selfcheck,
                                     summarize_trace, trace_facts,
                                     validate_trace)

__all__ = [
    "TRACE_SCHEMA_VERSION", "RunTrace", "SOLVER_NAMES",
    "flush_open_traces", "load_trace", "load_trace_auto",
    "render_report", "summarize_trace", "trace_facts",
    "resolve_trace_path", "follow_trace", "host_lanes",
    "compare_traces", "compare_paths",
    "render_compare", "regressions", "selfcheck", "main",
    "validate_trace",
]

if __name__ == "__main__":
    import sys

    sys.exit(main())

"""The SMO alpha pair step, shared by the single-device and distributed
solvers.

Two clip rules:

* "independent" — the reference's (``svmTrainMain.cpp:289-295``):
  a_hi' computed from the UNCLIPPED a_lo', then both clipped to their
  boxes separately. Lets sum(alpha*y) drift off the dual manifold
  (documented in ops/diagnostics.py); reproduced bit-for-bit for parity.
* "pairwise" — the textbook/LIBSVM joint box: a_lo' clipped to the
  feasible segment of the equality-constraint line through the pair,
  a_hi' moved along it. Conserves sum(alpha*y) exactly; one-class
  training requires it (its constraint value nu*n is part of the
  model — models/oneclass.py).
"""

from __future__ import annotations

import jax.numpy as jnp


def alpha_pair_step(a_hi, a_lo, y_hi, y_lo, b_hi, b_lo_sel, eta,
                    c_hi, c_lo, pairwise: bool):
    """Returns (a_hi_new, a_lo_new). ``pairwise`` is static."""
    s = y_lo * y_hi
    a_lo_u = a_lo + y_lo * (b_hi - b_lo_sel) / eta
    if pairwise:
        # The I-set masks test alpha == 0 / alpha == C EXACTLY
        # (ops/selection.py, matching the reference's clip outputs), so
        # when the joint clip binds, the partner alpha must land on the
        # LITERAL corner value — computing it arithmetically as
        # a_hi + s*(a_lo - bound) leaves it 1 ulp off the box and the
        # pair freezes: it keeps being selected but cannot move
        # (observed: alpha = 0.99999994 stuck in I_up forever).
        pos = s > 0
        ssum = a_lo + a_hi                   # conserved when s > 0
        diff = a_hi - a_lo                   # conserved when s < 0
        lo_b = jnp.maximum(0.0, jnp.where(pos, ssum - c_hi, a_lo - a_hi))
        hi_b = jnp.minimum(c_lo, jnp.where(pos, ssum, a_lo + c_hi - a_hi))
        a_lo_n = jnp.clip(a_lo_u, lo_b, hi_b)
        hi_at_lo = jnp.where(pos,
                             jnp.where(lo_b > 0, c_hi, ssum),
                             jnp.where(lo_b > 0, 0.0, diff))
        hi_at_hi = jnp.where(pos,
                             jnp.where(hi_b < c_lo, 0.0, ssum - c_lo),
                             jnp.where(hi_b < c_lo, c_hi, diff + c_lo))
        a_hi_n = jnp.where(a_lo_u <= lo_b, hi_at_lo,
                           jnp.where(a_lo_u >= hi_b, hi_at_hi,
                                     a_hi + s * (a_lo - a_lo_u)))
    else:
        a_hi_u = a_hi + s * (a_lo - a_lo_u)      # uses UNCLIPPED a_lo'
        a_lo_n = jnp.clip(a_lo_u, 0.0, c_lo)
        a_hi_n = jnp.clip(a_hi_u, 0.0, c_hi)
    return a_hi_n, a_lo_n

"""Kernel math on the MXU: RBF (reference parity) + the LIBSVM family.

The reference computes kernel rows as one cuBLAS SGEMV per working-set
index on its own CUDA stream (``svmTrain.cu:216-249``) and then applies
exp(-gamma (|x_i|^2 + |x_a|^2 - 2 dot)) elementwise in a Thrust functor
(``svmTrain.cu:128-135``). Here both working rows go through a single
``(2, d) @ (d, n)`` matmul — on TPU the MXU wants one batched contraction,
not two streamed vector products — and XLA fuses the elementwise epilogue
into the same kernel.

The reference is RBF-only; this framework also offers LIBSVM's other
kernels (``-t 0..3``), all computable from the same dot products:

    linear   K = u.v
    poly     K = (gamma u.v + coef0)^degree
    rbf      K = exp(-gamma |u - v|^2)
    sigmoid  K = tanh(gamma u.v + coef0)

Every solver path consumes kernels through ``rows_from_dots`` /
``kdiag_from_norms`` with a static ``KernelSpec``, so the RBF expression
(and its bit-exact parity with the reference) is untouched when
``kind == "rbf"``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class KernelSpec(NamedTuple):
    """Static (hashable, jit-key-safe) kernel description."""

    kind: str = "rbf"        # linear | poly | rbf | sigmoid |
                             # precomputed (LIBSVM -t 4: X IS the
                             # kernel matrix; a "row fetch" is a gather
                             # and the x2 slot carries diag(K))
    gamma: float = 1.0       # unused by linear/precomputed
    coef0: float = 0.0       # poly / sigmoid only
    degree: int = 3          # poly only

    @property
    def is_rbf(self) -> bool:
        return self.kind == "rbf"

    @classmethod
    def coerce(cls, value) -> "KernelSpec":
        """A KernelSpec, or a bare gamma float as RBF shorthand (the
        original call convention, kept for the benchmark harnesses)."""
        if isinstance(value, cls):
            return value
        return cls(kind="rbf", gamma=float(value))


def row_norms_sq(x: jax.Array, precision=jax.lax.Precision.HIGHEST) -> jax.Array:
    """|x_i|^2 per row, one fused reduction.

    (The reference does this as n separate device-wide
    ``thrust::inner_product`` calls in a host loop, ``svmTrain.cu:361-364``.)
    """
    return jnp.einsum("ij,ij->i", x, x, precision=precision)


def host_row_stats(x, spec) -> "np.ndarray":
    """The per-row scalar the solvers thread through as ``x2``: squared
    row norms for the vector kernels, diag(K) for precomputed (where
    callers pass the kernel matrix as x). Keeping the diagonal in the
    same slot lets kdiag_from_norms and every solver path stay
    kernel-generic."""
    import numpy as np
    spec = KernelSpec.coerce(spec)
    if spec.kind == "precomputed":
        return np.ascontiguousarray(
            np.diagonal(np.asarray(x, np.float32))).astype(np.float32)
    return host_row_norms_sq(x)


def host_row_norms_sq(x) -> "np.ndarray":
    """|x_i|^2 per row on the HOST, with the oracle's exact expression
    (solver/oracle.py) — the single source of the bit-parity row norms
    both solver front-ends feed the device. Host-side on purpose: a
    device-side norm program is one more tiny XLA compile per process
    on the tunneled TPU (see solver/smo.init_carry)."""
    import numpy as np
    xf = np.ascontiguousarray(x, dtype=np.float32)
    return np.einsum("ij,ij->i", xf, xf).astype(np.float32)


def rbf_rows_from_dots(dots: jax.Array, w2: jax.Array, x2: jax.Array,
                       gamma) -> jax.Array:
    """K(a, i) = exp(-gamma (|x_i|^2 + |x_a|^2 - 2 x_a.x_i)).

    dots: (r, n) dot products of r working rows against all points;
    w2: (r,) squared norms of the working rows; x2: (n,).
    Exactly the ``update_functor`` expression (``svmTrain.cu:128-135``).
    """
    return jnp.exp(-gamma * (x2[None, :] + w2[:, None] - 2.0 * dots))


def rows_from_dots(dots: jax.Array, w2: jax.Array, x2: jax.Array,
                   spec: KernelSpec, gamma=None) -> jax.Array:
    """Kernel rows from dot products, dispatched statically on the kind.

    dots: (r, n); w2: (r,) squared norms of the working rows (consumed
    by RBF only); x2: (n,). The RBF branch is byte-identical to
    ``rbf_rows_from_dots`` — reference parity is untouched.

    ``gamma`` overrides ``spec.gamma`` with a traced value — a scalar,
    or an (r, 1) per-row array (the batched gamma-grid sweep: the dots
    are gamma-independent, so per-row gammas reuse one matmul). The
    expressions are unchanged; an array gamma merely broadcasts.
    """
    g = spec.gamma if gamma is None else gamma
    if spec.kind == "rbf":
        return rbf_rows_from_dots(dots, w2, x2, g)
    if spec.kind == "linear":
        return dots
    if spec.kind == "poly":
        return (g * dots + spec.coef0) ** spec.degree
    if spec.kind == "sigmoid":
        return jnp.tanh(g * dots + spec.coef0)
    raise ValueError(f"unknown kernel kind {spec.kind!r}")


def kdiag_from_norms(x2: jax.Array, spec: KernelSpec) -> jax.Array:
    """K(i, i) from squared row norms (WSS2's a_j and eta need the
    diagonal; for RBF it is identically 1 and callers keep the
    reference's literal ``2 - 2K`` form instead)."""
    if spec.kind == "rbf":
        return jnp.ones_like(x2)
    if spec.kind == "linear":
        return x2
    if spec.kind == "poly":
        return (spec.gamma * x2 + spec.coef0) ** spec.degree
    if spec.kind == "sigmoid":
        return jnp.tanh(spec.gamma * x2 + spec.coef0)
    if spec.kind == "precomputed":
        return x2       # x2 carries diag(K) by convention (host_row_stats)
    raise ValueError(f"unknown kernel kind {spec.kind!r}")


def kernel_rows(rows: jax.Array, w2: jax.Array, x: jax.Array, x2: jax.Array,
                spec, precision=jax.lax.Precision.HIGHEST) -> jax.Array:
    """Full kernel rows for the given working rows: (r, n).

    ``spec`` may be a KernelSpec or a bare gamma float (RBF shorthand,
    the original call convention).
    """
    spec = KernelSpec.coerce(spec)
    if spec.kind == "precomputed":
        # The gathered rows ARE the kernel rows (x is K); no matmul.
        return rows
    dots = jnp.matmul(rows, x.T, precision=precision)
    return rows_from_dots(dots, w2, x2, spec)

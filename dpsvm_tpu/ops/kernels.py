"""RBF kernel math on the MXU.

The reference computes kernel rows as one cuBLAS SGEMV per working-set
index on its own CUDA stream (``svmTrain.cu:216-249``) and then applies
exp(-gamma (|x_i|^2 + |x_a|^2 - 2 dot)) elementwise in a Thrust functor
(``svmTrain.cu:128-135``). Here both working rows go through a single
``(2, d) @ (d, n)`` matmul — on TPU the MXU wants one batched contraction,
not two streamed vector products — and XLA fuses the exp/scale elementwise
epilogue into the same kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def row_norms_sq(x: jax.Array, precision=jax.lax.Precision.HIGHEST) -> jax.Array:
    """|x_i|^2 per row, one fused reduction.

    (The reference does this as n separate device-wide
    ``thrust::inner_product`` calls in a host loop, ``svmTrain.cu:361-364``.)
    """
    return jnp.einsum("ij,ij->i", x, x, precision=precision)


def rbf_rows_from_dots(dots: jax.Array, w2: jax.Array, x2: jax.Array,
                       gamma) -> jax.Array:
    """K(a, i) = exp(-gamma (|x_i|^2 + |x_a|^2 - 2 x_a.x_i)).

    dots: (r, n) dot products of r working rows against all points;
    w2: (r,) squared norms of the working rows; x2: (n,).
    Exactly the ``update_functor`` expression (``svmTrain.cu:128-135``).
    """
    return jnp.exp(-gamma * (x2[None, :] + w2[:, None] - 2.0 * dots))


def kernel_rows(rows: jax.Array, w2: jax.Array, x: jax.Array, x2: jax.Array,
                gamma, precision=jax.lax.Precision.HIGHEST) -> jax.Array:
    """Full RBF kernel rows for the given working rows: (r, n)."""
    dots = jnp.matmul(rows, x.T, precision=precision)
    return rbf_rows_from_dots(dots, w2, x2, gamma)

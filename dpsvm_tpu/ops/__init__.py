"""Device-side primitives shared by the single-device and distributed solvers."""

from dpsvm_tpu.ops.kernels import (KernelSpec, kdiag_from_norms, kernel_rows,
                                   rbf_rows_from_dots, row_norms_sq,
                                   rows_from_dots)
from dpsvm_tpu.ops.selection import iup_ilow_masks, masked_extrema

__all__ = [
    "KernelSpec",
    "row_norms_sq",
    "rbf_rows_from_dots",
    "rows_from_dots",
    "kdiag_from_norms",
    "kernel_rows",
    "iup_ilow_masks",
    "masked_extrema",
]

"""Device-side primitives shared by the single-device and distributed solvers."""

from dpsvm_tpu.ops.kernels import row_norms_sq, rbf_rows_from_dots
from dpsvm_tpu.ops.selection import iup_ilow_masks, masked_extrema

__all__ = [
    "row_norms_sq",
    "rbf_rows_from_dots",
    "iup_ilow_masks",
    "masked_extrema",
]

"""Fixed-shape HBM kernel-row cache.

TPU-native equivalent of the reference's ``myCache`` (``cache.cu``): the
reference keeps ``max_size`` device vectors of per-shard kernel-row *dot
products*, a ``std::map`` key index and a ``std::list`` recency queue with
LRU eviction (``cache.cu:49-105``). Dynamic host-side containers cannot
exist inside a jitted loop, so here the cache is three fixed-shape arrays
carried through ``lax.while_loop``:

* ``rows``   (lines, n)  cached dot-product rows (same payload the
                         reference caches — RBF exp is always re-applied,
                         matching ``update_functor``),
* ``keys``   (lines,)    which working-set index each line holds (-1 empty),
* ``stamps`` (lines,)    last-use tick for LRU eviction,

plus a scalar ``tick``. A hit skips the matmul via ``lax.cond``; a miss
computes the row and overwrites the least-recently-used line. Unlike the
reference's ``order.remove(key)`` linear list scan per hit
(``cache.cu:68``), hit bookkeeping here is O(lines) vectorized compares.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax


class RowCache(NamedTuple):
    keys: jax.Array     # (lines,) int32, -1 = empty
    stamps: jax.Array   # (lines,) int32 last-use tick
    rows: jax.Array     # (lines, n) float32 dot products
    tick: jax.Array     # () int32
    # Lifetime outcome counters, accumulated on device so they ride the
    # driver's packed-stats transfer (zero extra D2H polls — the
    # reference only ever exposed its hit rate through a commented-out
    # printf, svmTrain.cu margins; see docs/OBSERVABILITY.md). One pair
    # fetch = 2 lookups, so hits + misses == 2 * fetches.
    hits: jax.Array     # () int32
    misses: jax.Array   # () int32


def cache_init(lines: int, n: int, dtype=None) -> RowCache:
    """Host-side NumPy init (no XLA programs — see solver/smo.init_carry;
    the arrays move to the device with the first runner call)."""
    import numpy as np
    return RowCache(
        keys=np.full((lines,), -1, dtype=np.int32),
        stamps=np.zeros((lines,), dtype=np.int32),
        rows=np.zeros((lines, n), dtype=np.dtype(dtype or np.float32)),
        tick=np.int32(0),
        hits=np.int32(0),
        misses=np.int32(0),
    )


def cache_fetch(cache: RowCache, key: jax.Array,
                compute: Callable[[], jax.Array]
                ) -> Tuple[jax.Array, RowCache]:
    """Return the dot-product row for ``key``, from cache or computed.

    ``compute`` is only executed on a miss (lax.cond), mirroring
    ``SvmTrain::lookup_cache`` -> hit / ``get_new_cache_line`` + SGEMV
    (``svmTrain.cu:203-222``, ``cache.cu:62-105``).
    """
    key = jnp.asarray(key, jnp.int32)
    cache = RowCache(*(jnp.asarray(v) for v in cache))   # see cache_fetch_pair
    hit_mask = cache.keys == key
    hit = jnp.any(hit_mask)
    line = jnp.where(hit, jnp.argmax(hit_mask), jnp.argmin(cache.stamps))
    row = lax.cond(hit, lambda: cache.rows[line], compute)
    tick = cache.tick + 1
    h = hit.astype(jnp.int32)
    return row, RowCache(
        keys=cache.keys.at[line].set(key),
        stamps=cache.stamps.at[line].set(tick),
        rows=cache.rows.at[line].set(row),
        tick=tick,
        hits=cache.hits + h,
        misses=cache.misses + (1 - h),
    )


def cache_fetch_pair(cache: RowCache, key_a: jax.Array, key_b: jax.Array,
                     compute_both: Callable[[], jax.Array]
                     ) -> Tuple[jax.Array, RowCache]:
    """Fetch the dot-product rows for BOTH working-set keys at once.

    The reference streams the two SGEMVs on separate CUDA streams
    (``svmTrain.cu:216-249``); on TPU each full pass over X is an HBM
    stream, so two sequential misses would cost two passes. Instead: if
    either key misses, ONE ``(2, d) @ (d, n)`` matmul recomputes both rows
    (a mixed hit/miss wastes one already-cached row's FLOPs but saves a
    second full pass over X); only a double hit skips the matmul entirely.

    ``compute_both`` returns the stacked (2, n) dot rows. Eviction is LRU
    over last-use ticks; the two lines are always distinct (key_a's line
    is patched out of key_b's eviction candidates).
    """
    key_a = jnp.asarray(key_a, jnp.int32)
    key_b = jnp.asarray(key_b, jnp.int32)
    # cache_init builds host NumPy arrays (no init-time XLA programs);
    # promote so eager (non-jit) callers get .at[] — a no-op under trace.
    cache = RowCache(*(jnp.asarray(v) for v in cache))
    intmax = jnp.iinfo(jnp.int32).max

    same = key_b == key_a          # i_hi == i_lo corner: share one line
    hit_mask_a = cache.keys == key_a
    hit_mask_b = cache.keys == key_b
    hit_a = jnp.any(hit_mask_a)
    hit_b = jnp.any(hit_mask_b) | same

    # a's eviction scan must not victimize b's hit line (and vice versa):
    # each side's scan masks out the other's resolved/hit line.
    line_b_hit = jnp.argmax(hit_mask_b)
    stamps_a = jnp.where(jnp.any(hit_mask_b) & ~same,
                         cache.stamps.at[line_b_hit].set(intmax),
                         cache.stamps)
    line_a = jnp.where(hit_a, jnp.argmax(hit_mask_a), jnp.argmin(stamps_a))

    stamps_b = cache.stamps.at[line_a].set(intmax)
    line_b = jnp.where(same, line_a,
                       jnp.where(jnp.any(hit_mask_b),
                                 line_b_hit,
                                 jnp.argmin(stamps_b)))

    def from_cache():
        return jnp.stack([cache.rows[line_a], cache.rows[line_b]])

    rows = lax.cond(hit_a & hit_b, from_cache, compute_both)     # (2, n)

    tick = cache.tick + 1
    keys = cache.keys.at[line_a].set(key_a).at[line_b].set(key_b)
    stamps = cache.stamps.at[line_a].set(tick).at[line_b].set(tick)
    new_rows = cache.rows.at[line_a].set(rows[0]).at[line_b].set(rows[1])
    # Per-key outcome counters: 2 lookups per pair fetch (the i_hi ==
    # i_lo corner counts b's shared line as a hit, like the reference's
    # second lookup_cache of the same key would).
    nh = hit_a.astype(jnp.int32) + hit_b.astype(jnp.int32)
    return rows, RowCache(keys=keys, stamps=stamps, rows=new_rows,
                          tick=tick, hits=cache.hits + nh,
                          misses=cache.misses + (2 - nh))

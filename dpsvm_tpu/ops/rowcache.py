"""Fixed-shape HBM kernel-row cache.

TPU-native equivalent of the reference's ``myCache`` (``cache.cu``): the
reference keeps ``max_size`` device vectors of per-shard kernel-row *dot
products*, a ``std::map`` key index and a ``std::list`` recency queue with
LRU eviction (``cache.cu:49-105``). Dynamic host-side containers cannot
exist inside a jitted loop, so here the cache is three fixed-shape arrays
carried through ``lax.while_loop``:

* ``rows``   (lines, n)  cached dot-product rows (same payload the
                         reference caches — RBF exp is always re-applied,
                         matching ``update_functor``),
* ``keys``   (lines,)    which working-set index each line holds (-1 empty),
* ``stamps`` (lines,)    last-use tick for LRU eviction,

plus a scalar ``tick``. A hit skips the matmul via ``lax.cond``; a miss
computes the row and overwrites the least-recently-used line. Unlike the
reference's ``order.remove(key)`` linear list scan per hit
(``cache.cu:68``), hit bookkeeping here is O(lines) vectorized compares.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax


class RowCache(NamedTuple):
    keys: jax.Array     # (lines,) int32, -1 = empty
    stamps: jax.Array   # (lines,) int32 last-use tick
    rows: jax.Array     # (lines, n) float32 dot products
    tick: jax.Array     # () int32


def cache_init(lines: int, n: int, dtype=jnp.float32) -> RowCache:
    return RowCache(
        keys=jnp.full((lines,), -1, dtype=jnp.int32),
        stamps=jnp.zeros((lines,), dtype=jnp.int32),
        rows=jnp.zeros((lines, n), dtype=dtype),
        tick=jnp.int32(0),
    )


def cache_fetch(cache: RowCache, key: jax.Array,
                compute: Callable[[], jax.Array]
                ) -> Tuple[jax.Array, RowCache]:
    """Return the dot-product row for ``key``, from cache or computed.

    ``compute`` is only executed on a miss (lax.cond), mirroring
    ``SvmTrain::lookup_cache`` -> hit / ``get_new_cache_line`` + SGEMV
    (``svmTrain.cu:203-222``, ``cache.cu:62-105``).
    """
    key = key.astype(jnp.int32)
    hit_mask = cache.keys == key
    hit = jnp.any(hit_mask)
    line = jnp.where(hit, jnp.argmax(hit_mask), jnp.argmin(cache.stamps))
    row = lax.cond(hit, lambda: cache.rows[line], compute)
    tick = cache.tick + 1
    return row, RowCache(
        keys=cache.keys.at[line].set(key),
        stamps=cache.stamps.at[line].set(tick),
        rows=cache.rows.at[line].set(row),
        tick=tick,
    )

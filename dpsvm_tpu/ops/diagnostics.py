"""Solver diagnostics: dual objective, duality gap, KKT violation.

The reference carries a ``get_duality_gap`` that is dead code — defined at
``seq.cpp:352-376`` but never called, and it reads an uninitialized
``duality_gap`` accumulator. This is the working, XLA-batched equivalent,
intended for validation and debugging (never the hot loop):

  dual objective  D(alpha) = sum(alpha) - 1/2 sum_ij alpha_i alpha_j
                              y_i y_j K(x_i, x_j)
  primal (at w implied by alpha, hinge loss):
                  P(alpha) = 1/2 |w|^2 + C sum_i max(0, 1 - y_i (f_w(x_i)))
  gap = P - D >= 0, -> 0 at the optimum.

The kernel matrix is never materialized: everything streams in row blocks
of a (block, d) @ (d, n) matmul, so memory stays O(block * n).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dpsvm_tpu.ops.kernels import kernel_rows, row_norms_sq


@jax.jit
def _block_terms(x_blk, x2_blk, coef_blk, x, x2, coef, y_blk, gamma):
    k = kernel_rows(x_blk, x2_blk, x, x2, gamma)        # (blk, n)
    kv = k @ coef                                       # (blk,) = (K alpha*y)_i
    quad = coef_blk @ kv                                # alpha_i y_i K alpha y
    hinge = jnp.sum(jnp.maximum(0.0, 1.0 - y_blk * kv))
    return quad, hinge


def dual_objective_and_gap(x: np.ndarray, y: np.ndarray, alpha: np.ndarray,
                           gamma: float, c: float,
                           block: int = 4096) -> Tuple[float, float, float]:
    """Returns (dual_objective, primal_objective, duality_gap).

    The primal uses the unbiased decision value f_w(x) = (K alpha*y)(x)
    (no intercept), consistent with the reference evaluators that drop b.
    """
    x = np.asarray(x, np.float32)
    n = x.shape[0]
    yf = jnp.asarray(y, jnp.float32)
    al = jnp.asarray(alpha, jnp.float32)
    coef = al * yf
    xd = jnp.asarray(x)
    x2 = row_norms_sq(xd)

    quad = 0.0
    hinge = 0.0
    for lo in range(0, n, block):
        hi = min(lo + block, n)
        q, h = _block_terms(xd[lo:hi], x2[lo:hi], coef[lo:hi], xd, x2, coef,
                            yf[lo:hi], jnp.float32(gamma))
        quad += float(q)
        hinge += float(h)

    dual = float(jnp.sum(al)) - 0.5 * quad
    primal = 0.5 * quad + float(c) * hinge
    return dual, primal, primal - dual


def kkt_violation(x: np.ndarray, y: np.ndarray, alpha: np.ndarray,
                  gamma: float, c: float) -> float:
    """max over (min_{I_up} f - max_{I_low} f) style optimality residual:
    b_lo - b_hi recomputed from scratch (f = K alpha*y - y), in contrast to
    the solver's incrementally-maintained f. Useful to bound f drift."""
    from dpsvm_tpu.solver.oracle import iup_ilow_masks

    x = np.asarray(x, np.float32)
    yf = np.asarray(y, np.float32)
    al = np.asarray(alpha, np.float32)
    coef = jnp.asarray(al * yf)
    xd = jnp.asarray(x)
    x2 = row_norms_sq(xd)
    f = np.empty((x.shape[0],), np.float32)
    block = 4096
    for lo in range(0, x.shape[0], block):
        hi = min(lo + block, x.shape[0])
        k = kernel_rows(xd[lo:hi], x2[lo:hi], xd, x2, jnp.float32(gamma))
        f[lo:hi] = np.asarray(k @ coef) - yf[lo:hi]
    in_up, in_low = iup_ilow_masks(al, yf, np.float32(c))
    b_hi = f[in_up].min() if in_up.any() else np.inf
    b_lo = f[in_low].max() if in_low.any() else -np.inf
    return float(b_lo - b_hi)

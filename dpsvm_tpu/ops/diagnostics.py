"""Solver diagnostics: dual objective, duality gap, KKT violation.

The reference carries a ``get_duality_gap`` that is dead code — defined at
``seq.cpp:352-376`` but never called, and it reads an uninitialized
``duality_gap`` accumulator. This is the working, XLA-batched equivalent,
intended for validation and debugging (never the hot loop):

  dual objective  D(alpha) = sum(alpha) - 1/2 sum_ij alpha_i alpha_j
                              y_i y_j K(x_i, x_j)
  primal (at w implied by alpha, hinge loss):
                  P(alpha) = 1/2 |w|^2 + sum_i C_i max(0, 1 - y_i (f_w(x_i) - b))
  gap = P - D >= 0, -> 0 at the optimum.

The kernel matrix is never materialized: everything streams in row blocks
of a (block, d) @ (d, n) matmul, so memory stays O(block * n). The
streamed ``kv = K @ (alpha*y)`` vector is computed ONCE and shared by
every metric (``optimality_report``); the standalone functions remain as
thin wrappers.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dpsvm_tpu.ops.kernels import KernelSpec, kernel_rows, row_norms_sq


@functools.partial(jax.jit, static_argnames=("spec",))
def _block_kv(x_blk, x2_blk, x, x2, coef, spec: KernelSpec):
    k = kernel_rows(x_blk, x2_blk, x, x2, spec)         # (blk, n)
    return k @ coef                                     # (blk,) = (K alpha*y)_i


def _stream_kv(x: np.ndarray, coef: np.ndarray, spec, block: int
               ) -> np.ndarray:
    """kv = K @ coef in row blocks; O(block * n) device memory."""
    spec = KernelSpec.coerce(spec)
    xd = jnp.asarray(x)
    x2 = row_norms_sq(xd)
    cf = jnp.asarray(coef)
    n = x.shape[0]
    kv = np.empty((n,), np.float32)
    for lo in range(0, n, block):
        hi = min(lo + block, n)
        kv[lo:hi] = np.asarray(_block_kv(xd[lo:hi], x2[lo:hi], xd, x2, cf,
                                         spec))
    return kv


@dataclasses.dataclass
class OptimalityReport:
    dual: float            # Lagrangian L(alpha, b) — see notes below
    primal: float          # P at (w(alpha), b)
    gap: float             # primal - dual
    kkt_residual: float    # b_lo - b_hi recomputed from fresh f
    eq_residual: float     # sum(alpha * y) — the independent-clip drift


def optimality_report(x: np.ndarray, y: np.ndarray, alpha: np.ndarray,
                      gamma, c, b: float = 0.0,
                      block: int = 4096) -> OptimalityReport:
    """All post-train optimality metrics from ONE streamed kernel pass.

    ``gamma`` is a bare float (RBF shorthand) or a KernelSpec for the
    other LIBSVM kernels.

    ``c`` may be a scalar or a per-example (n,) array (class-weighted
    costs: C_i = C * w(y_i)); the primal weights each hinge term by its
    example's box bound.

    The primal evaluates the hinge at f_w(x) - b. Pass the solver's
    intercept for a tight certificate: the bias is a free primal variable,
    so P(w, b*) = D(alpha*) at the optimum, while b=0 (the default, and
    what the reference evaluators use when they drop b, seq_test.cpp:197)
    systematically overstates the gap by up to C * sum_i |b| at large C.

    Equality-constraint correction: the reference clips the two updated
    alphas INDEPENDENTLY to their boxes (svmTrainMain.cpp:294-295 — not
    the textbook pairwise clip), so its iterates drift off the dual
    manifold sum_i alpha_i y_i = 0 (visibly so with class weights). The
    textbook dual value is then off by exactly b * sum(alpha*y) relative
    to the primal at the same KKT point, which is an artifact of the
    algorithm's parametrization, not suboptimality. When ``b`` is given,
    the reported dual is the Lagrangian value L(alpha, b) =
    sum(alpha) - 1/2 quad + b*sum(alpha*y), which removes that artifact
    and makes gap -> 0 at eps-KKT convergence regardless of the drift.

    ``kkt_residual`` is b_lo - b_hi with f = kv - y recomputed from
    scratch, in contrast to the solver's incrementally-maintained f —
    comparing the two bounds accumulated f drift.
    """
    from dpsvm_tpu.solver.oracle import iup_ilow_masks

    x = np.asarray(x, np.float32)
    yf = np.asarray(y, np.float32)
    al = np.asarray(alpha, np.float32)
    c_vec = np.asarray(c, np.float32)
    coef = al * yf

    kv = _stream_kv(x, coef, gamma, block)   # gamma may be a spec

    quad = float(coef @ kv)
    hinge = float(np.sum(np.broadcast_to(c_vec, yf.shape)
                         * np.maximum(0.0, 1.0 - yf * (kv - b))))
    eq_residual = float(np.sum(coef))
    dual = float(np.sum(al)) - 0.5 * quad + float(b) * eq_residual
    primal = 0.5 * quad + hinge

    f = kv - yf
    in_up, in_low = iup_ilow_masks(al, yf, c_vec)
    b_hi = f[in_up].min() if in_up.any() else np.inf
    b_lo = f[in_low].max() if in_low.any() else -np.inf

    return OptimalityReport(dual=dual, primal=primal, gap=primal - dual,
                            kkt_residual=float(b_lo - b_hi),
                            eq_residual=eq_residual)


def dual_objective_and_gap(x: np.ndarray, y: np.ndarray, alpha: np.ndarray,
                           gamma, c, b: float = 0.0,
                           block: int = 4096) -> Tuple[float, float, float]:
    """(dual_objective, primal_objective, duality_gap) — see
    ``optimality_report`` for the semantics of ``c`` and ``b``."""
    r = optimality_report(x, y, alpha, gamma, c, b, block)
    return r.dual, r.primal, r.gap


def kkt_violation(x: np.ndarray, y: np.ndarray, alpha: np.ndarray,
                  gamma, c) -> float:
    """b_lo - b_hi recomputed from fresh f — see ``optimality_report``."""
    return optimality_report(x, y, alpha, gamma, c).kkt_residual

"""Working-set selection: Keerthi index sets + first-order extrema.

XLA-native form of the reference's fused classify+reduce
(``arbitrary_functor`` ``svmTrain.cu:41-95`` + ``my_maxmin`` reduce
``svmTrain.cu:400-467``): membership masks become a ``jnp.where`` with the
same +/-1e9 sentinels, and the joint (argmin, argmax) is two fused XLA
reductions. Tie-break is first-index-wins (see oracle docstring).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from dpsvm_tpu.config import SENTINEL


def iup_ilow_masks(alpha: jax.Array, y: jax.Array, c
                   ) -> Tuple[jax.Array, jax.Array]:
    """Membership in I_up / I_low (svmTrain.cu:54-91 semantics).

    y is the float +/-1 label vector. Exact ==0 / ==C comparisons mirror
    the reference; clipping writes exactly 0.0 or C so they are well posed.
    """
    at0 = alpha == 0.0
    atc = alpha == c
    interior = ~at0 & ~atc
    pos = y > 0
    in_up = interior | (at0 & pos) | (atc & ~pos)
    in_low = interior | (at0 & ~pos) | (atc & pos)
    return in_up, in_low


def iup_ilow_masks_np(alpha, y, c):
    """NumPy twin of ``iup_ilow_masks`` for host-side consumers (the
    shrinking manager's shrink rule and unshrink optimality check) —
    ONE membership definition, two array libraries. Semantics must stay
    identical to the jnp version above."""
    import numpy as np

    at0 = alpha == 0.0
    atc = alpha == c
    interior = ~at0 & ~atc
    pos = np.asarray(y) > 0
    in_up = interior | (at0 & pos) | (atc & ~pos)
    in_low = interior | (at0 & ~pos) | (atc & pos)
    return in_up, in_low


def masked_scores_and_masks(alpha: jax.Array, y: jax.Array, f: jax.Array,
                            c, valid: Optional[jax.Array] = None
                            ) -> Tuple[jax.Array, jax.Array,
                                       jax.Array, jax.Array]:
    """(f_up, f_low, in_up, in_low): sentinel-masked scores plus the
    boolean membership masks themselves.

    Consumers that need membership (e.g. WSS2's violator filter) must use
    the returned masks, NOT a ``f_low > -SENTINEL/2`` style test on the
    scores — a genuine violator with f < -SENTINEL/2 (reachable with
    extreme but legal C*weight and n, since |f| <= n*C_max + 1) would be
    misclassified by the sentinel inference.
    """
    in_up, in_low = iup_ilow_masks(alpha, y, c)
    if valid is not None:
        in_up = in_up & valid
        in_low = in_low & valid
    f_up = jnp.where(in_up, f, jnp.float32(SENTINEL))
    f_low = jnp.where(in_low, f, jnp.float32(-SENTINEL))
    return f_up, f_low, in_up, in_low


def masked_scores(alpha: jax.Array, y: jax.Array, f: jax.Array, c,
                  valid: Optional[jax.Array] = None
                  ) -> Tuple[jax.Array, jax.Array]:
    """(f_up, f_low): f with non-members pushed to +/-SENTINEL.

    ``valid`` masks out padding rows (used when n is padded to a multiple
    of the mesh size); padded rows belong to neither set.
    """
    return masked_scores_and_masks(alpha, y, f, c, valid)[:2]


def masked_extrema(alpha: jax.Array, y: jax.Array, f: jax.Array, c,
                   valid: Optional[jax.Array] = None):
    """(i_hi, b_hi, i_lo, b_lo): first-order working set over this block."""
    f_up, f_low = masked_scores(alpha, y, f, c, valid)
    i_hi = jnp.argmin(f_up)
    i_lo = jnp.argmax(f_low)
    return i_hi, f_up[i_hi], i_lo, f_low[i_lo]


def masked_extrema_packed(alpha: jax.Array, y: jax.Array, f: jax.Array, c,
                          valid: Optional[jax.Array] = None):
    """Same contract as ``masked_extrema`` via ONE variadic lax.reduce.

    The reference fuses I-set classification and the joint (argmin,
    argmax) into a single Thrust reduce pass (``my_maxmin``,
    ``svmTrain.cu:400-467,476``). The default implementation leaves the
    fusion of its two argmin/argmax reductions + two gathers to XLA;
    this variant expresses the whole selection as one 4-operand
    ``lax.reduce`` carrying (f_up, idx, f_low, idx) with explicit
    first-index tie-breaks — the SURVEY §7(b) packed value-index
    reduction. Bit-identical results; which lowers faster is measured by
    benchmarks/selection_ab.py, not assumed.
    """
    f_up, f_low = masked_scores(alpha, y, f, c, valid)
    n = f.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)

    def comp(acc, val):
        au, ai, al, aj = acc
        bu, bi, bl, bj = val
        # strict-compare + lower-index wins, matching jnp.argmin/argmax's
        # first-occurrence rule whatever order XLA reduces in
        up_b = (bu < au) | ((bu == au) & (bi < ai))
        lo_b = (bl > al) | ((bl == al) & (bj < aj))
        return (jnp.where(up_b, bu, au), jnp.where(up_b, bi, ai),
                jnp.where(lo_b, bl, al), jnp.where(lo_b, bj, aj))

    b_hi, i_hi, b_lo, i_lo = jax.lax.reduce(
        (f_up, idx, f_low, idx),
        (jnp.float32(SENTINEL), jnp.int32(jnp.iinfo(jnp.int32).max),
         jnp.float32(-SENTINEL), jnp.int32(jnp.iinfo(jnp.int32).max)),
        comp, (0,))
    return i_hi, b_hi, i_lo, b_lo

"""Solver configuration and result types.

Replaces the reference's global mutable ``state_model`` singleton
(``svmTrainMain.hpp:4-19``, read ambiently from deep inside the solver at
``svmTrain.cu:309,349,361``) with one explicit, immutable dataclass shared by
the library API and both CLIs. Field names / defaults mirror the reference
flags (``svmTrainMain.cpp:62-71,22-44``):

    -c cost (default 1.0)     -> ``c``
    -g gamma (default 1/d)    -> ``gamma`` (None => 1.0/num_attributes; the
                                 reference's int-division bug that yields
                                 gamma=0 for d>1, ``svmTrainMain.cpp:133``,
                                 is deliberately FIXED here — see SURVEY §2d)
    -e epsilon (default 1e-3) -> ``epsilon``
    -n max-iter (default 150000) -> ``max_iter``
    -s cache-size (default 10 lines) -> ``cache_size`` (0 disables — the
                                 default here. Works on the single-device
                                 AND distributed first-order paths (per
                                 shard, like the reference's per-rank
                                 myCache). Whether it pays on TPU is
                                 shape-dependent and measured by
                                 benchmarks/cache_ab.py, not assumed.)

Shapes (`-a` / `-x`, which the reference REQUIRES on the command line) are
inferred from the data and never part of the config.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

# Sentinel used by the reference for masked I-set scores
# (svmTrain.cu:59,66 use +/-1e9); kept identical for parity.
SENTINEL = 1.0e9

_SOLVERS = ("exact", "approx-rff", "approx-nystrom", "cascade")

# Default cascade screening band (SVMConfig.screen_margin): one name
# so the field default, the capability table's "is the knob set" test
# and the cascade's stage sub-config resets can never drift apart.
SCREEN_MARGIN_DEFAULT = 0.35

# Per-solver knob capability table. One row per solver-path knob that
# only SOME solver families implement: (field label, is-set predicate,
# solvers that accept it, why the others reject it). validate() walks
# it once, and a rejection names the solver(s) that WOULD accept the
# knob — a misplaced flag is a redirect, not a dead end. The cascade
# accepts BOTH families' knobs: its stage 1 is an approx primal train
# (approx_dim/approx_seed), its stage 3 an exact dual polish
# (selection/working_set/shrinking/... pass through to the subproblem
# solve — solver/cascade.py).
_DUAL = ("exact", "cascade")
_CASCADE = ("cascade",)
_KNOB_TABLE = (
    ("backend", lambda c: c.backend == "numpy", ("exact",),
     "the golden oracle is the dual SMO reference; the primal path "
     "has its own convergence test and the cascade orchestrates "
     "compiled stages"),
    ("selection", lambda c: c.selection != "first-order", _DUAL,
     "there is no working-set selection in the primal solver"),
    ("select_impl", lambda c: c.select_impl != "argminmax", _DUAL,
     "there is no extrema selection to lower"),
    ("working_set", lambda c: c.working_set not in (0, 2), _DUAL,
     "there is no dual working set; the minibatch size is chosen by "
     "the primal solver"),
    ("inner_iters", lambda c: bool(c.inner_iters), _DUAL,
     "there is no decomposition subsolve"),
    ("grow_working_set", lambda c: c.grow_working_set, _DUAL,
     "there is no working set to grow"),
    ("shrinking", lambda c: c.shrinking is True, _DUAL,
     "there is no active set; every row rides the feature matmul"),
    ("cache_size", lambda c: c.cache_size > 0, _DUAL,
     "there are no kernel rows to cache"),
    ("use_pallas", lambda c: c.use_pallas == "on", _DUAL,
     "the Pallas kernels implement the dual iteration"),
    ("polish", lambda c: c.polish, ("exact",),
     "the two-phase precision schedule refines a dual trajectory — "
     "and the cascade is itself a screen-and-polish schedule; set "
     "matmul_precision directly"),
    ("screen_margin",
     lambda c: c.screen_margin != SCREEN_MARGIN_DEFAULT, _CASCADE,
     "margin-band SV screening is the cascade's stage-2 knob"),
    ("screen_cap", lambda c: c.screen_cap != 0, _CASCADE,
     "the screened-subproblem row cap is the cascade's stage-2 knob"),
)


@dataclasses.dataclass(frozen=True)
class SVMConfig:
    """Hyperparameters + execution options for the SMO solver."""

    # --- algorithm (reference-parity) ---
    c: float = 1.0                      # box constraint C
    gamma: Optional[float] = None       # kernel gamma; None => 1.0 / d
    kernel: str = "rbf"                 # LIBSVM -t family: "linear" (u.v),
                                        # "poly" ((g u.v + r)^deg), "rbf"
                                        # (the reference's only kernel,
                                        # exact parity path), "sigmoid"
                                        # (tanh(g u.v + r))
    degree: int = 3                     # poly degree (LIBSVM -d)
    coef0: float = 0.0                  # poly/sigmoid coef0 (LIBSVM -r)
    epsilon: float = 0.001              # convergence tolerance
    svr_epsilon: float = 0.1            # epsilon-SVR tube half-width
                                        # (LIBSVM -p; regression only)
    max_iter: int = 150_000             # iteration cap
    cache_size: int = 0                 # kernel-row cache lines (0 = off)
    weight_pos: float = 1.0             # class-weighted costs: the box
    weight_neg: float = 1.0             # bound is C*weight_pos for y=+1
                                        # examples, C*weight_neg for y=-1
                                        # (LIBSVM -wi; imbalanced data).
                                        # STRONGLY asymmetric weights
                                        # under the default independent
                                        # clip let sum(alpha*y) drift
                                        # far (measured: drift -252.9,
                                        # b -226.9 vs libsvm's 2.0 at
                                        # w=(0.3, 2) on a wine pair) —
                                        # prefer clip="pairwise" (what
                                        # LIBSVM's solver does; the
                                        # multiclass class_weight path
                                        # forces it)
    selection: str = "first-order"      # working-set rule: "first-order"
                                        # (reference parity, svmTrain.cu:
                                        # 476-481) or "second-order" (the
                                        # LIBSVM WSS2 rule — usually far
                                        # fewer iterations to convergence)
    working_set: int = 2                # violators optimized per kernel
                                        # fetch: 2 = the reference's SMO
                                        # pair (parity path); q > 2 (even)
                                        # = large-working-set
                                        # decomposition — top-q/2
                                        # violators per side, one
                                        # (q,d)@(d,n) MXU pass, an inner
                                        # SMO subsolve on the (q,q) block
                                        # (solver/decomp.py; the
                                        # ThunderSVM-style MXU path);
                                        # 0 = auto — resolved per
                                        # problem shape by resolved()
                                        # before any solver runs
    inner_iters: int = 0                # decomposition inner-step cap per
                                        # outer round (0 = auto: q/4).
                                        # The subsolve also exits early
                                        # when its own gap closes.
    grow_working_set: bool = False      # adaptive decomposition: start
                                        # at working_set=q and GROW the
                                        # block (recompile, same carry)
                                        # when the SV count approaches
                                        # it — the measured q-selection
                                        # rule (q >= ~1.3x n_sv or
                                        # updates blow up 2.5-3x)
                                        # applied without knowing n_sv
                                        # a priori. XLA decomposition
                                        # paths (single-device AND
                                        # distributed).
    shrinking: object = False           # LIBSVM -h: active-set training
                                        # (solver/shrink.py) — compact
                                        # the problem to the rows that
                                        # can still move, validate on
                                        # the full problem at the end.
                                        # True | False | "auto" (shape-
                                        # resolved by resolved()). Off
                                        # by default (the reference has
                                        # no shrinking; the unshrunk
                                        # path is the parity path).
                                        # Composes with working_set.
    clip: str = "independent"           # alpha-step clip rule:
                                        # "independent" (the reference's,
                                        # svmTrainMain.cpp:294-295 — both
                                        # alphas clipped separately, lets
                                        # sum(alpha*y) drift) or
                                        # "pairwise" (textbook/LIBSVM
                                        # joint box — conserves the
                                        # equality constraint exactly;
                                        # required by one-class, where
                                        # the constraint value nu*n is
                                        # part of the model)
    solver: str = "exact"               # "exact" = the dual SMO /
                                        # decomposition paths (the paper's
                                        # solver; everything above applies).
                                        # "approx-rff" / "approx-nystrom" =
                                        # explicit feature map + primal
                                        # linear solver (dpsvm_tpu/approx/):
                                        # O(n*D) matmul pipeline instead of
                                        # O(n^2) kernel work — the
                                        # million-row path (docs/APPROX.md).
                                        # Approx models have no support
                                        # vectors; api.fit returns an
                                        # ApproxSVMModel.
                                        # "cascade" = approx warm-start ->
                                        # margin-band SV screening -> exact
                                        # dual polish on the screened
                                        # subproblem + KKT re-admission
                                        # repair (solver/cascade.py,
                                        # docs/APPROX.md "Cascade"):
                                        # exact-quality decisions at a
                                        # fraction of the exact cost.
                                        # api.fit returns an ordinary
                                        # SVMModel.
    approx_dim: int = 1024              # feature-map dimension D (approx
                                        # solvers only): RFF uses D/2
                                        # frequency pairs (D must be even);
                                        # Nystrom uses up to D landmarks
                                        # (capped by n, rank-truncated)
    approx_seed: int = 0                # feature-map seed: RFF frequencies
                                        # / Nystrom landmark subsample are
                                        # deterministic in (seed, shape) —
                                        # persisted with the model so
                                        # serving rebuilds the identical map
    screen_margin: float = SCREEN_MARGIN_DEFAULT
                                        # cascade stage 2: the margin-band
                                        # safety delta — a row survives
                                        # screening when its CALIBRATED
                                        # approx margin y*f(x) <= 1 +
                                        # screen_margin (every confident
                                        # non-SV is screened out; the KKT
                                        # repair loop re-admits any the
                                        # band missed). Bigger = safer
                                        # band, bigger exact subproblem.
    screen_cap: int = 0                 # cascade stage 2: hard cap on the
                                        # screened subproblem's row count
                                        # (0 = auto: derived from
                                        # mem_budget_mb when set, else
                                        # uncapped). Over-cap rows are
                                        # dropped worst-margin-first, i.e.
                                        # the rows most likely to be SVs
                                        # are kept.
    select_impl: str = "argminmax"      # first-order selection lowering:
                                        # "argminmax" (two jnp.arg* +
                                        # gathers, XLA fuses) or "packed"
                                        # (one 4-operand lax.reduce, the
                                        # reference's my_maxmin shape —
                                        # bit-identical; relative speed is
                                        # measured by benchmarks/
                                        # selection_ab.py)

    # --- execution ---
    backend: str = "xla"                # "xla" (compiled) or "numpy" (the
                                        # golden-reference solver, the
                                        # seq.cpp-equivalent path)
    shards: int = 1                     # mesh size along the data axis
    shard_x: bool = True                # shard X rows over the mesh (v2);
                                        # False replicates X (reference
                                        # parity: every rank holds full X,
                                        # svmTrainMain.cpp:180)
    chunk_iters: int = 512              # host polls convergence every chunk
    use_pallas: str = "auto"            # fused Pallas iteration kernel:
                                        # "on" = force (interpret mode
                                        # off-TPU); "auto"/"off" = plain
                                        # XLA path (faster on measured
                                        # hardware — see fused.use_fused)
    matmul_precision: str = "highest"   # jax.lax precision for kernel rows
                                        # (solver dtype is float32 for
                                        # reference parity, not configurable)
    polish: bool = False                # two-phase precision schedule
                                        # ("polishing", the fast-SVM
                                        # recipe of arXiv:2207.01016):
                                        # bulk-solve fast — at the
                                        # configured precision, or bf16
                                        # "default" when that is
                                        # "highest" — then warm-start
                                        # refine at exact f32 to the
                                        # same epsilon. Final KKT holds
                                        # in exact arithmetic at near-
                                        # bf16 wall-clock.
    mem_budget_mb: Optional[float] = None   # host-memory admission
                                        # guard (docs/DATA.md): a load
                                        # or streaming block that would
                                        # exceed this many MiB refuses
                                        # UP FRONT with the shard-count
                                        # math instead of OOMing an
                                        # hour in (CLI --mem-budget-mb;
                                        # None = no guard)
    on_bad_shard: str = "raise"         # streaming-ingest policy when a
                                        # shard fails its manifest CRC
                                        # or finiteness check
                                        # (data/stream.py): "raise"
                                        # fails fast; "quarantine"
                                        # drops the shard (trace event
                                        # naming shard + reason),
                                        # bounded by the bad-fraction
                                        # abort
    live: bool = False                  # treat a shard-directory
                                        # dataset as a LIVE append log
                                        # (data/live.py, docs/DATA.md
                                        # "Live shard logs"): streaming
                                        # training polls the manifest
                                        # at sweep boundaries and
                                        # admits newly durable shards
                                        # mid-run (traced as
                                        # append_admitted/ingest_grow;
                                        # checkpoints carry the
                                        # consumed generation). Only
                                        # the approx streaming path
                                        # (train -f DIR --live)
    verbose: bool = False
    log_every: int = 0                  # 0 = no per-chunk logging
    wall_budget_s: float = 0.0          # stop dispatching chunks once this
                                        # much wall-clock has elapsed in the
                                        # training loop (0 = no budget). The
                                        # run returns the usual TrainResult,
                                        # converged=False if the gap was
                                        # still open — a time-budgeted train
                                        # for measurement windows and
                                        # best-effort-within-deadline use;
                                        # enforced at chunk-poll granularity
                                        # (~chunk_iters iterations)

    # --- persistence / observability (reference has none — SURVEY §5) ---
    checkpoint_path: Optional[str] = None   # .npz solver-state file
    checkpoint_every: int = 0               # iterations between saves (0=off)
    checkpoint_keep: int = 2                # rotation slots kept (state.npz,
                                            # state.1.npz, ...): the newest
                                            # write can never destroy the
                                            # only intact state; 1 = no
                                            # rotation (docs/ROBUSTNESS.md)
    resume_from: Optional[str] = None       # checkpoint to resume from
                                            # (corrupt file -> automatic
                                            # fallback to the newest intact
                                            # rotation slot, traced as a
                                            # `rollback` event)
    on_divergence: str = "raise"            # HealthMonitor policy when the
                                            # poll-loop stats look sick
                                            # (non-finite gap, stagnation,
                                            # SV collapse): "raise" fails
                                            # fast, "rollback" restores the
                                            # newest intact checkpoint and
                                            # halves chunk_iters, "ignore"
                                            # records a trace event only
    health_window: int = 0                  # iterations without best-gap
                                            # improvement before the
                                            # stagnation guard trips; > 0
                                            # also arms the SV-collapse
                                            # heuristic. 0 (default) =
                                            # heuristic guards off; the
                                            # non-finite-gap guard is
                                            # ALWAYS armed (a NaN gap is
                                            # never legitimate)
    profile_dir: Optional[str] = None       # jax.profiler trace output dir:
                                            # auto-windowed capture (skip
                                            # warmup compiles, K steady-state
                                            # polls) with TraceAnnotation
                                            # spans named after the PhaseTimer
                                            # phases + a profile_summary.json
                                            # sidecar `dpsvm profile
                                            # summarize` renders
                                            # (observability/profiler.py)
    metrics_port: Optional[int] = None      # opt-in read-only metrics
                                            # sidecar: serve the process
                                            # metric registry on this port
                                            # (0 = OS-assigned) as
                                            # /metricsz JSON +
                                            # /metricsz?format=prometheus,
                                            # torn down at run end — zero
                                            # extra D2H transfers (the
                                            # registry is fed from the same
                                            # packed-stats polls tracing
                                            # rides)
    metrics_out: Optional[str] = None       # scrape-less CI: rewrite this
                                            # file with the Prometheus text
                                            # exposition at every poll
                                            # (atomic tmp+rename)
    trace_out: Optional[str] = None         # run-telemetry JSONL path:
                                            # manifest + per-chunk records
                                            # (gap, SV count, cache
                                            # counters — all riding the
                                            # one packed-stats transfer,
                                            # zero extra D2H) + summary;
                                            # render with `dpsvm report`
                                            # (docs/OBSERVABILITY.md)
    watch_rules: Optional[str] = None       # alert-rules JSON for the
                                            # driver's continuous watch
                                            # (observability/slo.py);
                                            # None with bundle_dir set =
                                            # the default training rules
                                            # (docs/OBSERVABILITY.md
                                            # "Watch & alerts")
    bundle_dir: Optional[str] = None        # incident bundles land here
                                            # when a watch rule fires or
                                            # a divergence guard trips —
                                            # arms the black-box flight
                                            # recorder (zero extra D2H:
                                            # fed from the same packed-
                                            # stats polls tracing rides)
    debug_nans: bool = False                # jax_debug_nans during training

    def fused_incompatibility(self) -> Optional[str]:
        """Why the fused Pallas kernel cannot run this config (None if it
        can). Single source of truth for validate() and the dispatch
        policy in experimental/fused.use_fused."""
        if self.backend != "xla":
            return f"backend {self.backend!r}"
        if self.shards > 1:
            return "shards > 1"
        if self.kernel != "rbf":
            return f"kernel {self.kernel!r} (RBF only)"
        if self.clip != "independent":
            return f"clip {self.clip!r} (reference clip only)"
        if self.cache_size > 0:
            return "the kernel-row cache (cache_size > 0)"
        if self.selection != "first-order":
            return f"selection {self.selection!r}"
        if self.working_set != 2:
            return "working_set > 2 (decomposition)"
        if self.weight_pos != 1.0 or self.weight_neg != 1.0:
            return "class-weighted costs"
        return None

    def box_bound(self, y):
        """Per-example box bound C_i = C * w(y_i), or the scalar C when
        unweighted. The single host-side source of the float32 rounding
        that the solver's exact ``alpha == C`` membership tests rely on
        (the in-trace solvers compute the same jnp.float32(c * w)
        values — solver/smo.py, parallel/dist_smo.py)."""
        import numpy as np
        if self.weight_pos == 1.0 and self.weight_neg == 1.0:
            return self.c
        return np.where(np.asarray(y) > 0,
                        np.float32(self.c * self.weight_pos),
                        np.float32(self.c * self.weight_neg))

    def resolve_gamma(self, num_attributes: int) -> float:
        if self.gamma is not None:
            return float(self.gamma)
        return 1.0 / float(num_attributes)

    def kernel_spec(self, num_attributes: int):
        """The static KernelSpec every solver path consumes."""
        from dpsvm_tpu.ops.kernels import KernelSpec
        return KernelSpec(kind=self.kernel,
                          gamma=self.resolve_gamma(num_attributes),
                          coef0=float(self.coef0),
                          degree=int(self.degree))

    def resolved(self, n: int, d: int) -> "SVMConfig":
        """Concretize the auto solver-path sentinels for an (n, d)
        problem: ``shrinking="auto"`` and ``working_set=0`` become
        shape-chosen values (everything downstream of api.train only
        ever sees concrete configs). No-op when nothing is "auto".

        The shape policy lives in ``_auto_solver_plan`` so flipping the
        framework's default path is a table edit backed by measured
        chip rows, the way ``use_pallas="auto"`` already dispatches.
        """
        if self.shrinking != "auto" and self.working_set != 0:
            return self
        cfg = dataclasses.replace(
            self, **_auto_solver_plan(int(n), int(d), self))
        cfg.validate()
        return cfg

    def validate(self) -> None:
        if self.c <= 0:
            raise ValueError(f"cost must be > 0, got {self.c}")
        if self.epsilon <= 0:
            raise ValueError(f"epsilon must be > 0, got {self.epsilon}")
        if self.max_iter <= 0:
            raise ValueError(f"max_iter must be > 0, got {self.max_iter}")
        if self.shards < 1:
            raise ValueError(f"shards must be >= 1, got {self.shards}")
        if self.cache_size < 0:
            raise ValueError(f"cache_size must be >= 0, got {self.cache_size}")
        if self.chunk_iters <= 0:
            raise ValueError(
                f"chunk_iters must be > 0, got {self.chunk_iters}")
        if self.checkpoint_every < 0:
            raise ValueError(
                f"checkpoint_every must be >= 0, got {self.checkpoint_every}")
        if self.checkpoint_every and not self.checkpoint_path:
            raise ValueError("checkpoint_every set without checkpoint_path")
        if self.checkpoint_keep < 1:
            raise ValueError(
                f"checkpoint_keep must be >= 1, got {self.checkpoint_keep}")
        if self.on_divergence not in ("raise", "rollback", "ignore"):
            raise ValueError("on_divergence must be 'raise', 'rollback' "
                             f"or 'ignore', got {self.on_divergence!r}")
        if self.health_window < 0:
            raise ValueError(
                f"health_window must be >= 0, got {self.health_window}")
        if self.on_divergence == "rollback" and not self.checkpoint_path:
            raise ValueError(
                "on_divergence='rollback' restores the newest intact "
                "checkpoint; set checkpoint_path (and checkpoint_every) "
                "so one exists")
        if self.wall_budget_s < 0:
            raise ValueError(
                f"wall_budget_s must be >= 0, got {self.wall_budget_s}")
        if self.mem_budget_mb is not None and self.mem_budget_mb <= 0:
            raise ValueError(
                f"mem_budget_mb must be > 0, got {self.mem_budget_mb}")
        if self.on_bad_shard not in ("raise", "quarantine"):
            raise ValueError("on_bad_shard must be 'raise' or "
                             f"'quarantine', got {self.on_bad_shard!r}")
        if self.live and self.solver not in ("approx-rff",
                                             "approx-nystrom"):
            raise ValueError(
                "live=True is the streaming approx path's knob "
                "(fit_approx_stream admits appended shards at sweep "
                f"boundaries); solver {self.solver!r} trains a frozen "
                "view — docs/DATA.md 'Live shard logs'")
        if self.metrics_port is not None and not (
                0 <= int(self.metrics_port) <= 65535):
            raise ValueError(
                f"metrics_port must be in [0, 65535], got "
                f"{self.metrics_port}")
        # Finite AND positive: `w <= 0` alone lets NaN through (every
        # NaN comparison is False) and inf past the positivity check —
        # either would poison the box bound silently (ADVICE r5).
        if not (math.isfinite(self.weight_pos) and self.weight_pos > 0
                and math.isfinite(self.weight_neg)
                and self.weight_neg > 0):
            raise ValueError("class weights must be > 0 and finite, got "
                             f"({self.weight_pos}, {self.weight_neg})")
        if self.svr_epsilon < 0:
            raise ValueError(
                f"svr_epsilon must be >= 0, got {self.svr_epsilon}")
        if self.clip not in ("independent", "pairwise"):
            raise ValueError(f"clip must be 'independent' or 'pairwise', "
                             f"got {self.clip!r}")
        if self.kernel not in ("linear", "poly", "rbf", "sigmoid",
                               "precomputed"):
            raise ValueError(f"kernel must be 'linear', 'poly', 'rbf', "
                             f"'sigmoid' or 'precomputed', got "
                             f"{self.kernel!r}")
        if self.kernel == "precomputed":
            # LIBSVM -t 4: x IS the (n, n) kernel matrix. Paths that
            # must re-EVALUATE kernel values between row subsets (not
            # just gather stored ones) cannot, and say so.
            if self.shrinking is True:
                raise ValueError(
                    "precomputed kernel does not support shrinking: the "
                    "unshrink f reconstruction evaluates kernels between "
                    "row subsets, which a gathered K cannot provide")
            if self.backend == "numpy":
                raise ValueError(
                    "precomputed kernel is not implemented on the numpy "
                    "golden-reference backend; use the xla backend")
            if self.cache_size > 0:
                raise ValueError(
                    "precomputed kernel has nothing to cache: the row "
                    "fetch is already a 2-row gather of the stored K")
            if self.use_pallas == "on":
                raise ValueError(
                    "the Pallas kernels are built around the vector-"
                    "kernel row fetch; precomputed uses the plain XLA "
                    "gather path")
        if self.kernel == "poly" and self.degree < 1:
            raise ValueError(f"poly degree must be >= 1, got {self.degree}")
        if self.solver not in _SOLVERS:
            raise ValueError(f"solver must be one of {_SOLVERS}, got "
                             f"{self.solver!r}")
        if self.approx_dim < 2:
            raise ValueError(
                f"approx_dim must be >= 2, got {self.approx_dim}")
        # No-silent-ignore, per solver family (the select_impl /
        # working_set policy): a knob only SOME solver paths implement
        # is rejected by the others, and the error names the solver(s)
        # that WOULD accept it (_KNOB_TABLE below).
        for field, is_set, accepted, what in _KNOB_TABLE:
            if self.solver not in accepted and is_set(self):
                raise ValueError(
                    f"solver={self.solver!r} does not support {field}: "
                    f"{what} (accepted by solver "
                    f"{', '.join(repr(s) for s in accepted)})")
        if self.solver != "exact":
            if self.solver == "approx-rff" and self.kernel != "rbf":
                raise ValueError(
                    "approx-rff is the RBF spectral feature map "
                    "(Rahimi-Recht); for other kernels use "
                    "approx-nystrom or the exact solver")
            if (self.approx_dim % 2
                    and (self.solver == "approx-rff"
                         or (self.solver == "cascade"
                             and self.kernel == "rbf"))):
                raise ValueError(
                    "approx-rff pairs cos/sin features, so "
                    f"approx_dim must be even, got {self.approx_dim}"
                    + (" (the cascade's RBF warm-start stage is "
                       "approx-rff)" if self.solver == "cascade" else ""))
            if self.kernel == "precomputed":
                raise ValueError(
                    "approx solvers evaluate kernels between new rows "
                    "and landmarks/frequencies; a precomputed K has no "
                    "row vectors to featurize"
                    + (" (the cascade's warm-start stage is an approx "
                       "train)" if self.solver == "cascade" else ""))
        if self.solver == "cascade":
            if not (math.isfinite(self.screen_margin)
                    and self.screen_margin > 0):
                raise ValueError("screen_margin must be finite and > 0, "
                                 f"got {self.screen_margin}")
            if self.screen_cap < 0:
                raise ValueError(
                    f"screen_cap must be >= 0, got {self.screen_cap}")
            # Stage state lives UNDER checkpoint_path (stage-boundary
            # files, auto-resumed — solver/cascade.py); the periodic /
            # explicit-resume machinery is a single-trajectory contract
            # the three-stage cascade does not have.
            if self.resume_from:
                raise ValueError(
                    "cascade does not support resume_from: it "
                    "auto-resumes from its stage-boundary state files "
                    "under checkpoint_path (delete them to restart)")
            if self.checkpoint_every:
                raise ValueError(
                    "cascade does not support checkpoint_every: stage "
                    "boundaries are its checkpoint cadence — set "
                    "checkpoint_path alone to name where stage state "
                    "lives")
            if self.profile_dir:
                raise ValueError(
                    "cascade does not support profile_dir: the "
                    "auto-windowed capture profiles ONE chunk-runner "
                    "steady state and the cascade is three runs — "
                    "profile a stage's solver directly")
        if self.selection not in ("first-order", "second-order"):
            raise ValueError(f"selection must be 'first-order' or "
                             f"'second-order', got {self.selection!r}")
        if self.select_impl not in ("argminmax", "packed"):
            raise ValueError(f"select_impl must be 'argminmax' or "
                             f"'packed', got {self.select_impl!r}")
        if self.select_impl != "argminmax":
            # Reject every path that would silently ignore the flag, so
            # an A/B run can't attribute default-lowering numbers to it.
            # (working_set > 2 rejects 'packed' on its own below, with
            # its own message — use_pallas='on' means the inner-subsolve
            # kernel there, not the fused 2-violator one.)
            if self.use_pallas == "on" and self.working_set == 2:
                raise ValueError("the fused Pallas kernel has its own "
                                 "in-kernel selection; select_impl does "
                                 "not apply (use_pallas='on')")
            if self.backend == "numpy":
                raise ValueError("the numpy golden-reference backend has "
                                 "no XLA lowerings; select_impl does not "
                                 "apply")
        if self.selection == "second-order":
            if self.cache_size > 0:
                raise ValueError("second-order selection needs the hi row "
                                 "before the lo index is known; the pair "
                                 "row-cache does not apply (cache_size=0)")
            if self.use_pallas == "on" and self.working_set == 2:
                # (With working_set > 2 the combination is rejected by
                # the working_set guard table — selection must be
                # first-order there — with the right message.)
                raise ValueError("the fused Pallas kernel implements "
                                 "first-order selection only")
            if self.select_impl != "argminmax":
                raise ValueError("select_impl applies to first-order "
                                 "selection only (WSS2's argmax-over-"
                                 "objective has no packed lowering)")
        if self.polish:
            # Reject combinations that would make the two-phase schedule
            # meaningless or non-replayable, with the reason.
            for field, bad, what in (
                    ("backend", self.backend == "numpy",
                     "the numpy oracle already computes in exact "
                     "arithmetic — there is nothing to polish"),
                    ("resume_from", bool(self.resume_from),
                     "the two-phase schedule is not one replayable "
                     "trajectory; resume the fast phase, then polish"),
                    ("checkpoint_path", bool(self.checkpoint_path),
                     "the two-phase schedule is not one replayable "
                     "trajectory; checkpoint the fast phase, then "
                     "polish"),
                    ("trace_out", bool(self.trace_out),
                     "the two-phase schedule is two runs, not one "
                     "trajectory — one trace file would be overwritten "
                     "by the refinement phase; trace each phase "
                     "separately via warm_start")):
                if bad:
                    raise ValueError(f"polish does not support {field}: "
                                     f"{what}")
        # Identity checks, not equality: 1 == True and np.True_ == True
        # would pass a membership test yet skip every 'is True' guard
        # below while still truthy-dispatching to the shrinking path.
        if not (self.shrinking is True or self.shrinking is False
                or self.shrinking == "auto"):
            raise ValueError("shrinking must be True, False or 'auto', "
                             f"got {self.shrinking!r}")
        if self.working_set == 0:
            # The sentinel may resolve to either 2 or q > 2; knobs whose
            # meaning (or validity) depends on which one must be pinned
            # by an explicit working_set — no-silent-ignore.
            if self.inner_iters:
                raise ValueError(
                    "inner_iters requires an explicit working_set > 2 "
                    "(working_set=0 may resolve to the classic pair)")
            if self.use_pallas == "on":
                raise ValueError(
                    "use_pallas='on' pins a specific kernel (fused "
                    "iteration at working_set=2, inner subsolve at "
                    "q > 2); use an explicit working_set with it")
        if self.working_set not in (0, 2):
            # Upper bound sized so the decomposition state stays cheap
            # relative to HBM (K_WW is q^2 f32 — 1 GB at 16384) while
            # admitting the measured q-selection rule: q must exceed
            # the SV count by ~1.3x or subsolves grind on stale global
            # state (benchmarks/results/iteration_economy_r4.jsonl:
            # q<n_sv costs 2.5-3x the updates at both 8000x784 and
            # 20000x784), and the reference shapes run to ~8k SVs.
            if (self.working_set < 4 or self.working_set % 2
                    or self.working_set > 16384):
                raise ValueError("working_set must be 0 (auto), 2 "
                                 "(classic SMO pair) or an even value "
                                 f"in [4, 16384], got {self.working_set}")
            # Reject every path that would silently ignore q, so results
            # can't be misattributed (same policy as select_impl).
            # (use_pallas='on' IS meaningful here: it selects the
            # Pallas inner-subsolve kernel, experimental/subsolve_kernel.py.)
            for field, bad, what in (
                    ("selection", self.selection != "first-order",
                     "the decomposition subsolve is WSS2 internally"),
                    ("cache_size", self.cache_size > 0,
                     "the block fetch replaces the pair row-cache"),
                    ("use_pallas+shards",
                     self.use_pallas == "on" and self.shards > 1,
                     "the Pallas inner subsolve is single-device today"),
                    ("use_pallas+working_set",
                     self.use_pallas == "on" and self.working_set > 2048,
                     "the inner-subsolve kernel keeps the (q, q) f32 "
                     "block VMEM-resident; q caps at 2048 (16 MB)"),
                    ("select_impl", self.select_impl != "argminmax",
                     "outer selection is top_k, not packed extrema"),
                    ("backend", self.backend == "numpy",
                     "the golden oracle keeps the reference's pair "
                     "iteration")):
                if bad:
                    raise ValueError(
                        f"working_set > 2 does not support {field}: {what}")
        if self.grow_working_set:
            # Same no-silent-ignore policy: reject every path that
            # would ignore (or fight) the growth manager.
            for field, bad, what in (
                    ("working_set", self.working_set in (0, 2),
                     "growth needs an explicit starting q > 2 "
                     "(working_set=0 may resolve to the classic pair)"),
                    ("shrinking", self.shrinking is not False,
                     "two host-level rebuild managers (shrink compacts "
                     "n, growth raises q) are not composed yet"),
                    ("use_pallas", self.use_pallas == "on",
                     "the Pallas inner subsolve caps q at 2048, which "
                     "growth would cross"),
                    ("backend", self.backend == "numpy",
                     "the golden oracle keeps the reference's pair "
                     "iteration")):
                if bad:
                    raise ValueError(
                        f"grow_working_set does not support {field}: "
                        f"{what}")
        if self.shrinking is True:
            # Reject paths that would silently ignore or fight the
            # active-set manager (same no-silent-ignore policy).
            # ("auto" is exempt: the resolver never picks shrinking
            # when a conflicting field is set, then re-validates.)
            # For solver="cascade" the ORCHESTRATION fields (checkpoint
            # /resume/profile/metrics/divergence) belong to the cascade
            # driver and are stripped before the shrinking polish
            # sub-run ever sees them — only the solver-level conflicts
            # apply there.
            cascade = self.solver == "cascade"
            for field, bad, what in (
                    ("backend", self.backend == "numpy",
                     "the golden oracle keeps the reference's full-set "
                     "iteration"),
                    ("cache_size", self.cache_size > 0,
                     "cached row indices would dangle across "
                     "compactions"),
                    ("use_pallas",
                     self.use_pallas == "on" and self.working_set == 2,
                     "the 2-violator fused kernel hard-codes the "
                     "full-problem init (the decomposition's inner "
                     "kernel composes fine)"),
                    ("checkpoint_path",
                     bool(self.checkpoint_path) and not cascade,
                     "checkpoint/resume does not capture active-set "
                     "state"),
                    ("resume_from", bool(self.resume_from),
                     "checkpoint/resume does not capture active-set "
                     "state"),
                    ("profile_dir", bool(self.profile_dir),
                     "the shrinking loop manages its own dispatch; "
                     "profile the unshrunk path"),
                    ("metrics_port/metrics_out",
                     (self.metrics_port is not None
                      or bool(self.metrics_out)) and not cascade,
                     "the shrinking loop manages its own dispatch; "
                     "the metrics exporters ride the shared host "
                     "driver"),
                    ("watch_rules/bundle_dir",
                     (bool(self.watch_rules) or bool(self.bundle_dir))
                     and not cascade,
                     "the shrinking loop manages its own dispatch; "
                     "the continuous watch rides the shared host "
                     "driver"),
                    ("on_divergence",
                     self.on_divergence != "raise" and not cascade,
                     "the shrinking loop manages its own dispatch; "
                     "divergence guards ride the shared host driver"),
                    ("health_window",
                     bool(self.health_window) and not cascade,
                     "the shrinking loop manages its own dispatch; "
                     "divergence guards ride the shared host driver")):
                if bad:
                    raise ValueError(
                        f"shrinking does not support {field}: {what}")
        if self.inner_iters < 0:
            raise ValueError(
                f"inner_iters must be >= 0, got {self.inner_iters}")
        if self.inner_iters and self.working_set == 2:
            raise ValueError("inner_iters applies only to working_set > 2")
        if self.use_pallas not in ("auto", "on", "off"):
            raise ValueError(f"use_pallas must be 'auto', 'on' or 'off', "
                             f"got {self.use_pallas!r}")
        if (self.use_pallas == "on" and self.working_set == 2
                and self.fused_incompatibility()):
            # With working_set > 2, use_pallas='on' selects the
            # decomposition's inner-subsolve kernel instead (validated
            # by the working_set guard table above).
            raise ValueError("the fused Pallas kernel does not support "
                             f"{self.fused_incompatibility()}; use "
                             "use_pallas='auto' or 'off'")
        if self.backend not in ("xla", "numpy"):
            raise ValueError(f"backend must be 'xla' or 'numpy', "
                             f"got {self.backend!r}")
        if self.backend == "numpy":
            if self.shards > 1:
                raise ValueError("the numpy golden-reference backend is "
                                 "single-process only (shards must be 1)")
            unsupported = [name for name, v in (
                ("checkpoint_path", self.checkpoint_path),
                ("checkpoint_every", self.checkpoint_every),
                ("resume_from", self.resume_from),
                ("profile_dir", self.profile_dir),
                ("metrics_port", self.metrics_port is not None),
                ("metrics_out", self.metrics_out),
                ("trace_out", self.trace_out),
                ("watch_rules", self.watch_rules),
                ("bundle_dir", self.bundle_dir),
                ("wall_budget_s", self.wall_budget_s),
                ("on_divergence", self.on_divergence != "raise"),
                ("health_window", self.health_window)) if v]
            if unsupported:
                raise ValueError(
                    f"the numpy backend does not support: {unsupported}")


def _shape_class(n: int, d: int) -> str:
    """Problem-shape class for the auto-dispatch table. Boundaries come
    from the measured d-regimes of the CPU iteration-economy scan
    (docs/PERF.md "Solver-path iteration economics"): decomposition's
    update cut improves with d (0.90x at d=128 -> 0.66x at d=784) and
    fails at small-d/small-gamma (30000x54: DNF at the 600k cap), and
    past ~VMEM scale the 2-violator step becomes HBM-stream-bound."""
    if n >= 200_000:
        return "hbm"        # covtype/epsilon: X streams from HBM
    if d >= 512:
        return "highd"      # mnist-like: the decomposition candidate
    if d <= 32:
        return "lowd"       # ijcnn1-like
    return "mid"            # adult-like


# (want_shrink, want_q, want_cap) per shape class — THE table that
# cashes measured chip economics into default behavior (round-3
# verdict #2). Every non-parity entry must cite a measured chip row in
# docs/PERF.md; parity entries say why they stand. want_cap is the
# decomposition inner-step cap that ships with a flipped want_q
# (0 = the solver's auto q/4).
_PLAN_TABLE = {
    # highd shrinking: SETTLED NEGATIVE on chip — conv_shrink 74.36 s
    # vs 19.09 s base at 60000x784 [sweep conv_shrink, r4 window];
    # shrinking's cheaper steps cannot pay for its host round-trips
    # when the row fetch is one fused MXU pass. want_q: pending the
    # conv_decomp12288_cap* arms (q-selection rule says q >= ~1.3x
    # n_sv; the CPU cut at d=784 is 0.66-0.70x updates).
    "highd": (False, 2, 0),
    # lowd: pending conv_ijcnn1_* arms; CPU scan shows WSS2's cut
    # (0.59x) but no decomposition case (long subsolves on stale
    # state at small d).
    "lowd": (False, 2, 0),
    # mid: pending conv_adult_1m; CPU wall win for shrinking (2.6-3.3x
    # at d<=128) is deliberately NOT cashed — the shrink trade depends
    # on the hardware's round cost (see the highd chip negative).
    "mid": (False, 2, 0),
    # hbm: decomposition denied on measured CPU evidence at the
    # covtype d-regime (both 30000x54 q arms DNF at the 600k cap —
    # auto must never pick it there); the q2048 chip arms decide
    # whether measured-rate evidence overturns this.
    "hbm": (False, 2, 0),
}


def _auto_solver_plan(n: int, d: int, config: "SVMConfig") -> dict:
    """Shape-based solver-path choice for the "auto" sentinels.

    Policy lives in ``_PLAN_TABLE`` (per shape class); this function
    applies it without ever choosing a path a conflicting explicit
    field rules out — the guard tables in validate() stay the
    no-silent-ignore authority for EXPLICIT combinations, while auto
    simply declines the fast path. Current table resolves to the
    classic 2-violator unshrunk path at every class (exactly the
    framework's explicit defaults): CPU wall-clock evidence is
    deliberately not cashed into TPU defaults (round-3 verdict weak
    #4), and the chip rows that would flip the slots are the armed
    sweep backlog (`benchmarks/burst_runner.py`).
    """
    want_shrink, want_q, want_cap = _PLAN_TABLE[_shape_class(n, d)]
    plan = {}
    if config.shrinking == "auto":
        shrink_supported = (config.kernel != "precomputed"
                            and config.backend != "numpy"
                            and config.cache_size == 0
                            and not config.checkpoint_path
                            and not config.resume_from
                            and not config.profile_dir
                            and config.metrics_port is None
                            and not config.metrics_out
                            and not config.watch_rules
                            and not config.bundle_dir
                            and config.on_divergence == "raise"
                            and not config.health_window
                            and not (config.use_pallas == "on"
                                     and config.working_set == 2))
        plan["shrinking"] = bool(want_shrink and shrink_supported)
    if config.working_set == 0:
        decomp_supported = (config.selection == "first-order"
                            and config.cache_size == 0
                            and config.select_impl == "argminmax"
                            and config.backend != "numpy")
        if want_q > 2 and decomp_supported:
            plan["working_set"] = want_q
            if want_cap and config.inner_iters == 0:
                plan["inner_iters"] = want_cap
        else:
            plan["working_set"] = 2
    return plan


@dataclasses.dataclass
class TrainResult:
    """Outcome of a training run.

    Mirrors what the reference prints/writes at the end of training
    (``svmTrainMain.cpp:313-348``): intercept b, iteration count,
    convergence status, wall time, plus the full solver state needed to
    build a model (alpha) and diagnostics (final optimality gap).
    """

    alpha: "object"                     # (n,) float array
    b: float
    n_iter: int
    converged: bool
    b_lo: float
    b_hi: float
    train_seconds: float
    gamma: float
    n_sv: int
    kernel: str = "rbf"                 # LIBSVM -t family (see SVMConfig)
    coef0: float = 0.0
    degree: int = 3
    learned_epsilon: "Optional[float]" = None   # nu-SVR only: the tube
                                        # half-width the optimization
                                        # found ((r1+r2)/2 — LIBSVM -s 4
                                        # prints it as "epsilon = ...")

    @property
    def gap(self) -> float:
        return self.b_lo - self.b_hi

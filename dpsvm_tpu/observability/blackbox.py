"""Black-box flight recorder + one-command incident bundles.

The postmortem problem this closes: when a production run degrades —
a 504 storm, a stagnant gap, a watchdog stall — the rich artifacts
(traces, metrics, ledger rows) either were not armed or describe the
whole run, not the minutes that mattered. The BENCH_r03–r05 burned
rounds are the canonical failure: incidents that left NO artifact.

``FlightRecorder`` is a bounded, in-process ring of the most recent
trace-shaped records (chunk/event/compile/span), metrics snapshots and
its own manifest — fed for free from the paths that already hold every
fact on the host (the driver's packed-stats polls, the serving
server's event/span emission), so recording costs ZERO additional
device->host transfers and bounded memory regardless of run length.

When an alert rule fires (observability/slo.py), a divergence guard
trips, or an emergency exit path runs, ``dump_bundle`` writes a
self-contained incident directory:

    incident-<stamp>-<rule>/
      incident.json        manifest: rule, severity, window, reason,
                           fired-at time, git sha, file inventory
      trace.jsonl          the ring contents as a VALID schema-v3
                           trace (manifest + records + synthesized
                           summary) — `dpsvm report` renders it,
                           `validate_trace` accepts it
      metrics.prom         Prometheus text exposition at dump time
      metrics.json         the JSON snapshot twin
      doctor.txt           host-side environment facts (never inits a
                           backend: device facts only when jax is
                           already imported)
      tuned_profile.json   the active tuned-profile entry (when one
                           resolves — docs/PERF.md "Autotuning")
      perf_ledger.jsonl    the relevant perf-ledger context rows
                           (tail), when a ledger is configured

``validate_bundle``/``render_bundle`` back the ``dpsvm bundle`` CLI;
``python -m dpsvm_tpu.observability --selfcheck`` round-trips a
planted burn through dump -> re-validate (docs/OBSERVABILITY.md
"Incident bundles").

Like the schema module this file is dependency-free (stdlib only) and
never initializes a backend.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from dpsvm_tpu.observability.schema import (MANIFEST_KEYS, SUMMARY_KEYS,
                                            TRACE_SCHEMA_VERSION,
                                            read_trace, validate_trace)

#: incident.json schema version
BUNDLE_SCHEMA = 1

#: files every bundle must carry (tuned_profile / perf_ledger are
#: best-effort context: present when the source exists)
BUNDLE_REQUIRED_FILES = ("incident.json", "trace.jsonl",
                         "metrics.prom", "metrics.json", "doctor.txt")

INCIDENT_KEYS = ("schema", "rule", "severity", "window", "reason",
                 "time", "t", "git_sha", "files")

_DUMP_LOCK = threading.Lock()
_DUMP_SEQ = 0

# Emergency registry: (recorder, bundle_dir, registry) tuples armed by
# the driver / serving server so exit paths that bypass their finally
# blocks (the stall watchdog's os._exit) can still land a bundle —
# record.flush_open_traces calls dump_emergency right before dying.
_EMERGENCY: List[tuple] = []
_EMERGENCY_LOCK = threading.Lock()


def make_manifest(*, solver: str, n: int = 0, d: int = 0,
                  gamma: float = 0.0, config: Optional[dict] = None,
                  env: Optional[dict] = None) -> dict:
    """A schema-v3 trace manifest for the ring (same shape the
    RunTrace recorder writes — observability/record.py — so the dumped
    trace validates and renders through the ordinary tooling)."""
    config = dict(config or {})
    try:
        from dpsvm_tpu import __version__
    except Exception:               # pragma: no cover — import cycle
        __version__ = "0"
    man = {
        "kind": "manifest",
        "schema": TRACE_SCHEMA_VERSION,
        "version": __version__,
        "solver": str(solver),
        "n": int(n), "d": int(d), "gamma": float(gamma),
        "kernel": {"kind": config.get("kernel", "rbf"),
                   "gamma": float(gamma),
                   "coef0": float(config.get("coef0", 0.0)),
                   "degree": int(config.get("degree", 3))},
        "mesh": {"shards": int(config.get("shards", 1)),
                 "shard_x": bool(config.get("shard_x", True))},
        "env": dict(env or {"backend": None, "device_kind": None,
                            "device_count": None}),
        "config": config,
        "it0": 0,
        "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
    }
    missing = [k for k in MANIFEST_KEYS if k not in man]
    assert not missing, f"manifest shape drifted: missing {missing}"
    return man


class FlightRecorder:
    """Bounded ring of recent trace-shaped records + metrics
    snapshots. The record methods mirror RunTrace's signatures
    (observability/record.py) so ``TeeTrace`` can forward one call to
    both sinks; every append is host-side dict work under one lock."""

    def __init__(self, manifest: dict, *, capacity: int = 512,
                 snapshot_capacity: int = 8):
        self.manifest = dict(manifest)
        self._ring: deque = deque(maxlen=int(capacity))
        self._snapshots: deque = deque(maxlen=int(snapshot_capacity))
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._last_t = 0.0
        self._closed = False
        self._final_summary: Optional[dict] = None

    # -- clock --------------------------------------------------------

    def _t(self) -> float:
        # monotone even across clock hiccups: the schema's t-ordering
        # rule is part of the dumped trace's validity
        t = round(time.perf_counter() - self._t0, 6)
        with self._lock:
            t = max(t, self._last_t)
            self._last_t = t
            return t

    def _append(self, rec: dict) -> None:
        with self._lock:
            self._ring.append(rec)

    # -- RunTrace-shaped producers ------------------------------------

    def chunk(self, *, n_iter: int, b_lo: float, b_hi: float,
              n_sv: int = 0, cache_hits: int = 0, cache_misses: int = 0,
              rounds: int = 0, phases: Optional[Dict] = None,
              phase_counts: Optional[Dict] = None,
              hbm: Optional[dict] = None, **extra) -> None:
        rec = {"kind": "chunk", "n_iter": int(n_iter),
               "b_lo": float(b_lo), "b_hi": float(b_hi),
               "gap": float(b_lo) - float(b_hi), "n_sv": int(n_sv),
               "cache_hits": int(cache_hits),
               "cache_misses": int(cache_misses), "rounds": int(rounds),
               "t": self._t(),
               "phases": {k: round(float(v), 6)
                          for k, v in (phases or {}).items()},
               "phase_counts": {k: int(v)
                                for k, v in (phase_counts or {}).items()},
               "hbm": dict(hbm) if hbm else {"in_use": None,
                                             "peak": None,
                                             "limit": None}}
        rec.update(extra)
        self._append(rec)

    def event(self, event: str, *, n_iter: int = 0, **extra) -> None:
        rec = {"kind": "event", "event": str(event),
               "n_iter": int(n_iter), "t": self._t()}
        rec.update(extra)
        self._append(rec)

    def compile(self, *, program: str, seconds: float,
                signature=None, flops=None, bytes=None,
                n_iter: int = 0, **extra) -> None:
        rec = {"kind": "compile", "program": str(program),
               "seconds": round(float(seconds), 6),
               "signature": signature,
               "flops": float(flops) if flops is not None else None,
               "bytes": float(bytes) if bytes is not None else None,
               "n_iter": int(n_iter), "t": self._t()}
        rec.update(extra)
        self._append(rec)

    def span(self, *, trace_id, span_id: int, parent, name: str,
             t_start: float, t_end: float, **extra) -> None:
        # same rebase the RunTrace recorder does: absolute
        # perf_counter readings onto the recorder's clock
        rel0 = round(float(t_start) - self._t0, 6)
        rel1 = round(float(t_end) - self._t0, 6)
        rec = {"kind": "span", "trace_id": trace_id,
               "span_id": int(span_id),
               "parent": int(parent) if parent is not None else None,
               "name": str(name), "t_start": rel0, "t_end": rel1,
               "t": self._t()}
        rec.update(extra)
        self._append(rec)

    def summary(self, **kw) -> None:
        # a live recorder never holds a summary (the dump synthesizes
        # one); the final summary of a finished run is kept as the
        # dump's source of truth instead of a ring record, so a bundle
        # dumped mid-run stays valid
        with self._lock:
            self._final_summary = dict(kw)

    def snapshot_metrics(self, registry) -> None:
        """Park one metrics snapshot (JSON dict + text exposition) in
        the snapshot ring — called at alert transitions and dump time;
        never raises into the caller."""
        try:
            snap = {"t": self._t(),
                    "json": registry.snapshot(),
                    "prometheus": registry.render_prometheus()}
            with self._lock:
                self._snapshots.append(snap)
        except Exception:
            pass

    # -- ring views ---------------------------------------------------

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def last_snapshot(self) -> Optional[dict]:
        with self._lock:
            return self._snapshots[-1] if self._snapshots else None

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        self._closed = True

    # -- the dumped trace ---------------------------------------------

    def trace_records(self) -> List[dict]:
        """Manifest + ring contents + a synthesized summary — a
        self-contained, schema-valid v3 trace of the recent past.

        A ring is a LEFT-truncated slice of the run's record stream,
        so anything ordering-sensitive whose opening record fell off
        the edge is dropped rather than emitted invalid: span groups
        whose root (or a parent) was truncated away, and cascade
        stage events whose predecessor stage is gone (``polish``
        before any ``screen`` in the slice, ``readmit`` before any
        ``polish``). Chunk n_iter monotonicity survives truncation by
        construction — the ring drops oldest-first, and every rewind
        event rides between the chunks it separates."""
        recs = _sanitize_slice(self.records())
        last_t = max([r.get("t", 0.0) for r in recs] + [0.0])
        last_chunk = None
        for r in recs:
            if r.get("kind") == "chunk":
                last_chunk = r
        summary = {
            "kind": "summary", "converged": False,
            "n_iter": int((last_chunk or {}).get("n_iter", 0)),
            "iters": int((last_chunk or {}).get("n_iter", 0)),
            "iters_per_sec": 0.0,
            "b": 0.0,
            "b_lo": float((last_chunk or {}).get("b_lo", 0.0)),
            "b_hi": float((last_chunk or {}).get("b_hi", 0.0)),
            "gap": float((last_chunk or {}).get("gap", 0.0)),
            "n_sv": int((last_chunk or {}).get("n_sv", 0)),
            "cache_hits": int((last_chunk or {}).get("cache_hits", 0)),
            "cache_misses": int((last_chunk or {})
                                .get("cache_misses", 0)),
            "cache_hit_rate": None,
            "train_seconds": round(last_t, 6),
            "phases": dict((last_chunk or {}).get("phases", {})),
            "phase_counts": dict((last_chunk or {})
                                 .get("phase_counts", {})),
            "n_compiles": sum(1 for r in recs
                              if r.get("kind") == "compile"),
            "compile_seconds": round(
                sum(r.get("seconds", 0.0) for r in recs
                    if r.get("kind") == "compile"), 6),
            "hbm_peak": None,
            "est_flops": None,
            "est_bytes": None,
            "flight_recorder": True,    # honesty marker: a ring slice,
            "t": last_t,                # not a whole-run summary
        }
        missing = [k for k in SUMMARY_KEYS if k not in summary]
        assert not missing, f"summary shape drifted: missing {missing}"
        return [dict(self.manifest)] + recs + [summary]


def _sanitize_slice(recs: List[dict]) -> List[dict]:
    """Drop records a left-truncated ring cannot emit validly (see
    FlightRecorder.trace_records)."""
    # span groups: keep only requests whose root AND every referenced
    # parent survived the truncation
    by_trace: Dict[object, List[dict]] = {}
    for r in recs:
        if r.get("kind") == "span":
            by_trace.setdefault(r.get("trace_id"), []).append(r)
    bad_traces = set()
    for tid, group in by_trace.items():
        ids = {g.get("span_id") for g in group}
        roots = [g for g in group if g.get("parent") is None]
        if len(roots) != 1 or any(
                g.get("parent") is not None and g["parent"] not in ids
                for g in group):
            bad_traces.add(tid)
    out: List[dict] = []
    saw_screen = saw_polish = False
    for r in recs:
        kind = r.get("kind")
        if kind == "span" and r.get("trace_id") in bad_traces:
            continue
        if kind == "event":
            ev = r.get("event")
            if ev == "screen":
                saw_screen = True
            elif ev == "polish":
                if not saw_screen:
                    continue
                saw_polish = True
            elif ev == "readmit" and not saw_polish:
                continue
        out.append(r)
    return out


class TeeTrace:
    """Quacks like a RunTrace for the driver's call sites, forwarding
    every record to the file trace (when one is armed) AND the flight
    recorder — so watching a run records its black box without a
    second producer at any call site. ``file_trace`` may be None
    (watch armed, ``--trace-out`` not)."""

    def __init__(self, file_trace, flight: FlightRecorder):
        self._file = file_trace
        self._flight = flight

    def _both(self, method: str, *a, **kw):
        if self._file is not None:
            getattr(self._file, method)(*a, **kw)
        try:
            getattr(self._flight, method)(*a, **kw)
        except Exception:
            pass                # the black box must never kill the run

    def chunk(self, **kw):
        self._both("chunk", **kw)

    def event(self, event, **kw):
        self._both("event", event, **kw)

    def compile(self, **kw):
        self._both("compile", **kw)

    def span(self, **kw):
        self._both("span", **kw)

    def summary(self, **kw):
        self._both("summary", **kw)

    @property
    def path(self):
        return self._file.path if self._file is not None else None

    @property
    def closed(self) -> bool:
        return (self._file.closed if self._file is not None
                else self._flight.closed)

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
        self._flight.close()


# ---------------------------------------------------------------------
# bundle dump
# ---------------------------------------------------------------------

def _slug(s: str) -> str:
    out = "".join(c if c.isalnum() or c in "-_" else "-"
                  for c in str(s))
    return out.strip("-")[:48] or "incident"


def _doctor_text() -> str:
    """Host-side environment facts for the bundle — a bounded,
    never-blocking subset of ``dpsvm doctor``: this runs inside a
    degrading process, so it must not initialize a backend, touch a
    device, or wait on anything."""
    import platform

    lines = [f"dpsvm bundle doctor ("
             f"{time.strftime('%Y-%m-%dT%H:%M:%S%z')})"]
    try:
        from dpsvm_tpu import __version__
        lines.append(f"dpsvm: {__version__}")
    except Exception:
        pass
    lines.append(f"python: {platform.python_version()} "
                 f"({sys.platform})")
    lines.append(f"host: {platform.node()}")
    # device facts ONLY when the backend is already up in this
    # process (a dictionary read) — never an init from a bundle dump
    if "jax" in sys.modules:
        try:
            import jax
            devs = jax.devices()
            lines.append(f"backend: {devs[0].platform} x{len(devs)} "
                         f"({getattr(devs[0], 'device_kind', None)})")
        except Exception as e:
            lines.append(f"backend: unreadable ({e})")
    else:
        lines.append("backend: not initialized in this process")
    try:
        import shutil
        usage = shutil.disk_usage(os.getcwd())
        lines.append(f"disk: {usage.free / 1e9:.2f} GB free of "
                     f"{usage.total / 1e9:.2f} GB at {os.getcwd()}")
    except OSError:
        pass
    faults = sorted(k for k in os.environ
                    if k.startswith(("DPSVM_FAULT_", "BENCH_FAULT_")))
    if faults:
        lines.append("armed fault injections: " + ", ".join(
            f"{k}={os.environ[k]}" for k in faults))
    return "\n".join(lines) + "\n"


def _tuned_profile_entry() -> Optional[dict]:
    try:
        from dpsvm_tpu.tuning import profile as tuned_profile
        return tuned_profile.active_entry()
    except Exception:
        return None


def _ledger_tail(limit: int = 25) -> List[dict]:
    try:
        from dpsvm_tpu.observability import ledger
        path = ledger.ledger_path()
        if path is None or not os.path.exists(path):
            return []
        return ledger.read(path)[-limit:]
    except Exception:
        return []


def _git_sha() -> Optional[str]:
    try:
        from dpsvm_tpu.observability.ledger import git_sha
        return git_sha()
    except Exception:
        return None


def dump_bundle(out_dir: str, *, recorder: FlightRecorder,
                rule: str, severity: str, window: str, reason: str,
                registry=None, extra: Optional[dict] = None,
                host_artifacts: Optional[Dict[int, dict]] = None
                ) -> str:
    """Write one self-contained incident bundle; returns its
    directory. Never raises — a failed dump logs to stderr and
    returns "" (the incident response must not take the producer
    down with it).

    ``host_artifacts`` makes this a FLEET bundle (docs/OBSERVABILITY.md
    "Fleet"): host id -> ``{"heartbeat": dict, "trace_tail": [lines],
    "doctor": str}`` (observability/fleet.host_artifacts collects it),
    landing as ``host-<k>-heartbeat.json`` / ``host-<k>-trace-tail
    .jsonl`` / ``host-<k>-doctor.txt`` entries in the file inventory —
    so one bundle carries every group member's last words, not just
    the dumping process's own ring."""
    global _DUMP_SEQ
    try:
        with _DUMP_LOCK:
            _DUMP_SEQ += 1
            seq = _DUMP_SEQ
        stamp = time.strftime("%Y%m%d-%H%M%S")
        name = f"incident-{stamp}-{seq:03d}-{_slug(rule)}"
        path = os.path.join(out_dir, name)
        os.makedirs(path, exist_ok=True)

        # 1. the black-box trace
        trace_path = os.path.join(path, "trace.jsonl")
        with open(trace_path, "w") as fh:
            for rec in recorder.trace_records():
                fh.write(json.dumps(rec) + "\n")

        # 2. metrics at dump time (live registry preferred; the last
        # ring snapshot as fallback)
        snap_json, snap_prom = {}, ""
        if registry is not None:
            try:
                snap_json = registry.snapshot()
                snap_prom = registry.render_prometheus()
            except Exception:
                pass
        if not snap_prom:
            last = recorder.last_snapshot()
            if last is not None:
                snap_json = last["json"]
                snap_prom = last["prometheus"]
        with open(os.path.join(path, "metrics.json"), "w") as fh:
            json.dump(snap_json, fh, indent=1)
        with open(os.path.join(path, "metrics.prom"), "w") as fh:
            fh.write(snap_prom)

        # 3. doctor facts
        with open(os.path.join(path, "doctor.txt"), "w") as fh:
            fh.write(_doctor_text())

        files = {"trace": "trace.jsonl",
                 "metrics_prometheus": "metrics.prom",
                 "metrics_json": "metrics.json",
                 "doctor": "doctor.txt"}

        # 4. context: tuned profile + perf-ledger tail (best-effort)
        entry = _tuned_profile_entry()
        if entry is not None:
            with open(os.path.join(path, "tuned_profile.json"),
                      "w") as fh:
                json.dump(entry, fh, indent=1)
            files["tuned_profile"] = "tuned_profile.json"
        tail = _ledger_tail()
        if tail:
            with open(os.path.join(path, "perf_ledger.jsonl"),
                      "w") as fh:
                for rec in tail:
                    fh.write(json.dumps(rec) + "\n")
            files["perf_ledger"] = "perf_ledger.jsonl"

        # 4b. per-host artifacts (fleet bundles): written before the
        # manifest so a listed file always exists
        for hid in sorted(host_artifacts or {}):
            art = host_artifacts[hid]
            if not isinstance(art, dict):
                continue
            if art.get("heartbeat") is not None:
                fname = f"host-{hid}-heartbeat.json"
                with open(os.path.join(path, fname), "w") as fh:
                    json.dump(art["heartbeat"], fh, indent=1)
                files[f"host_{hid}_heartbeat"] = fname
            tail_lines = art.get("trace_tail")
            if tail_lines:
                fname = f"host-{hid}-trace-tail.jsonl"
                with open(os.path.join(path, fname), "w") as fh:
                    for line in tail_lines:
                        fh.write(line if line.endswith("\n")
                                 else line + "\n")
                files[f"host_{hid}_trace_tail"] = fname
            if art.get("doctor"):
                fname = f"host-{hid}-doctor.txt"
                with open(os.path.join(path, fname), "w") as fh:
                    fh.write(str(art["doctor"]))
                files[f"host_{hid}_doctor"] = fname

        # 5. the manifest, written LAST: an incident.json implies a
        # complete bundle
        incident = {
            "schema": BUNDLE_SCHEMA,
            "rule": str(rule),
            "severity": str(severity),
            "window": str(window),
            "reason": str(reason),
            "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "t": round(time.time(), 3),
            "git_sha": _git_sha(),
            "files": files,
        }
        if extra:
            incident.update(extra)
        with open(os.path.join(path, "incident.json"), "w") as fh:
            json.dump(incident, fh, indent=1)
        print(f"INCIDENT: rule {rule!r} ({severity}) -> bundle {path}",
              file=sys.stderr, flush=True)
        return path
    except Exception as e:          # pragma: no cover — disk death
        print(f"WARNING: incident bundle dump failed: {e}",
              file=sys.stderr, flush=True)
        return ""


# ---------------------------------------------------------------------
# emergency dumps (watchdog stall / hard exits)
# ---------------------------------------------------------------------

def arm_emergency(recorder: FlightRecorder, bundle_dir: str,
                  registry=None) -> None:
    """Register a recorder for the emergency path: exit routes that
    bypass the owner's finally block (the stall watchdog's os._exit)
    call ``dump_emergency`` and every armed recorder lands a bundle."""
    with _EMERGENCY_LOCK:
        _EMERGENCY.append((recorder, bundle_dir, registry))


def disarm_emergency(recorder: FlightRecorder) -> None:
    with _EMERGENCY_LOCK:
        _EMERGENCY[:] = [e for e in _EMERGENCY if e[0] is not recorder]


def dump_emergency(reason: str) -> int:
    """Best-effort bundle per armed recorder; returns how many were
    dumped. Called from record.flush_open_traces — microseconds before
    an os._exit, so everything is try/except best-effort."""
    with _EMERGENCY_LOCK:
        armed = list(_EMERGENCY)
        _EMERGENCY[:] = []
    n = 0
    for recorder, bundle_dir, registry in armed:
        try:
            recorder.event(reason)
            if dump_bundle(bundle_dir, recorder=recorder,
                           rule=reason, severity="page",
                           window="emergency", reason=reason,
                           registry=registry):
                n += 1
        except Exception:
            pass
    return n


# ---------------------------------------------------------------------
# bundle validation + rendering (the `dpsvm bundle` CLI)
# ---------------------------------------------------------------------

def resolve_bundle_dir(path: str) -> str:
    """Accept a bundle directory OR a parent --bundle-dir: the newest
    ``incident-*`` child wins (mirrors resolve_trace_path's
    newest-artifact convention)."""
    if os.path.isfile(os.path.join(path, "incident.json")):
        return path
    children = sorted(
        (c for c in os.listdir(path)
         if c.startswith("incident-")
         and os.path.isfile(os.path.join(path, c, "incident.json"))),
        key=lambda c: os.path.getmtime(os.path.join(path, c)))
    if not children:
        raise FileNotFoundError(
            f"{path}: neither an incident bundle (no incident.json) "
            "nor a directory containing incident-* bundles")
    return os.path.join(path, children[-1])


def load_incident(bundle_dir: str) -> dict:
    with open(os.path.join(bundle_dir, "incident.json")) as fh:
        return json.load(fh)


def validate_bundle(bundle_dir: str) -> List[str]:
    """Full bundle check; returns problems (empty = valid): the
    incident manifest parses and carries its required keys, every
    required file exists, the embedded trace passes ``validate_trace``
    and the metrics exposition passes the Prometheus grammar
    validator."""
    problems: List[str] = []
    inc_path = os.path.join(bundle_dir, "incident.json")
    try:
        with open(inc_path) as fh:
            incident = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        return [f"incident.json unreadable: {e}"]
    missing = [k for k in INCIDENT_KEYS if k not in incident]
    if missing:
        problems.append(f"incident.json missing keys {missing}")
    if incident.get("severity") not in ("warn", "page"):
        problems.append("incident.json severity must be warn|page, "
                        f"got {incident.get('severity')!r}")
    for fname in BUNDLE_REQUIRED_FILES:
        if not os.path.isfile(os.path.join(bundle_dir, fname)):
            problems.append(f"missing required file {fname}")
    for key, fname in (incident.get("files") or {}).items():
        if not os.path.isfile(os.path.join(bundle_dir, fname)):
            problems.append(f"files[{key!r}] names missing {fname}")
    trace_path = os.path.join(bundle_dir, "trace.jsonl")
    if os.path.isfile(trace_path):
        try:
            records = read_trace(trace_path)
            errs = validate_trace(records)
            problems += [f"trace.jsonl: {e}" for e in errs]
        except ValueError as e:
            problems.append(f"trace.jsonl unreadable: {e}")
    prom_path = os.path.join(bundle_dir, "metrics.prom")
    if os.path.isfile(prom_path):
        from dpsvm_tpu.observability.metrics import validate_exposition
        with open(prom_path) as fh:
            text = fh.read()
        if text.strip():
            problems += [f"metrics.prom: {e}"
                         for e in validate_exposition(text)]
    json_path = os.path.join(bundle_dir, "metrics.json")
    if os.path.isfile(json_path):
        try:
            with open(json_path) as fh:
                json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            problems.append(f"metrics.json unreadable: {e}")
    return problems


def render_bundle(bundle_dir: str) -> str:
    """Human rendering of one bundle: the incident header plus the
    embedded trace's report (observability/report.py)."""
    incident = load_incident(bundle_dir)
    lines = [
        f"incident bundle: {bundle_dir}",
        f"  rule:     {incident.get('rule')} "
        f"[{incident.get('severity')}]",
        f"  window:   {incident.get('window')}",
        f"  reason:   {incident.get('reason')}",
        f"  time:     {incident.get('time')}  "
        f"(git {str(incident.get('git_sha') or 'unknown')[:12]})",
        f"  files:    " + ", ".join(
            sorted((incident.get("files") or {}).values())),
    ]
    trace_path = os.path.join(bundle_dir, "trace.jsonl")
    if os.path.isfile(trace_path):
        try:
            from dpsvm_tpu.observability.report import render_report
            records = read_trace(trace_path)
            lines.append("")
            lines.append("embedded trace:")
            lines.extend("  " + ln
                         for ln in render_report(records).splitlines())
        except (ValueError, OSError) as e:
            lines.append(f"  trace: unrenderable ({e})")
    return "\n".join(lines)

"""The RunTrace recorder: one training run's JSONL artifact.

The reference left its per-phase instrumentation commented out
(``svmTrain.cu:218-293``) and its duality-gap probe dead
(``seq.cpp:352-376``); we resurrected both (utils/timing.py,
ops/diagnostics.py) but they were islands — no single artifact recorded
what a training run *did*. ``RunTrace`` is that artifact: one JSONL
file per run (schema in observability/schema.py, prose in
docs/OBSERVABILITY.md) holding the manifest, a record per host poll,
compile accounting, solver events, and a summary. Every signal in the
per-chunk record rides the solvers' existing packed-stats transfer
(solver/driver.py "Poll economics") or a host-side API read
(``device.memory_stats()``), so a traced run performs ZERO additional
device->host transfers.

Producers: the shared host driver (solver/driver.host_training_loop —
every path through it: single-device, fused, decomposition, and both
SPMD variants), the shrinking manager (solver/shrink.py), and the
benchmark harnesses (bench.py, bench_convergence.py via
``BENCH_TRACE_OUT``). Consumers: the ``dpsvm report`` and ``dpsvm
compare`` CLI subcommands (observability/report.py, compare.py).

This module never touches a device: callers pass device facts in via
``env`` / ``hbm`` / the compile log.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import weakref
from typing import Dict, List, Optional

from dpsvm_tpu.observability.schema import (TRACE_SCHEMA_VERSION,
                                            TraceWriter)

# Every in-flight RunTrace, so emergency exit paths (the stall watchdog's
# os._exit) can stamp a terminal event record before the process dies —
# an abandoned trace with no terminal record is indistinguishable from a
# live run (docs/ROBUSTNESS.md). Weak: a dropped recorder unregisters
# itself.
_OPEN_TRACES: "weakref.WeakSet[RunTrace]" = weakref.WeakSet()


def flush_open_traces(event: str, **extra) -> int:
    """Best-effort: append ``event`` to every still-open trace and close
    it. Called from exit paths that bypass the driver's finally block
    (utils/watchdog.py expiry — a different thread, microseconds before
    os._exit, while the training thread is wedged in a device call, so
    a concurrent write is not a practical concern). Returns the number
    of traces flushed; never raises."""
    count = 0
    for tr in list(_OPEN_TRACES):
        try:
            tr.event(event, **extra)
            tr.close()
            count += 1
        except Exception:
            pass
    # Armed flight recorders land an incident bundle on the same
    # emergency path (observability/blackbox.py): a watchdog stall
    # leaves a postmortem artifact, not just a terminal trace event.
    try:
        from dpsvm_tpu.observability import blackbox
        blackbox.dump_emergency(event)
    except Exception:
        pass
    return count

# Carry-class -> human solver-path name (the driver keys the manifest on
# the carry type; one table so a new solver fails loudly in tests, not
# silently as its class name).
SOLVER_NAMES = {
    "SMOCarry": "smo",
    "DistCarry": "dist-smo",
    "DecompCarry": "decomp",
    "DistDecompCarry": "dist-decomp",
    "FusedCarry": "fused-pallas",
    "PrimalCarry": "approx-primal",
}

# Event types the resilient serving layer emits into a serving trace
# (docs/SERVING.md "Resilience", docs/ROBUSTNESS.md "Self-healing
# serving"): replica circuit-breaker transitions (`eject`/`rebuild`),
# overload shedding tier activations (`shed`), duplicate dispatches
# (`hedge`), and the model-lifecycle loop (`drift` detected ->
# `retrain` finished -> `promote` with ok=True on hot-swap / ok=False
# when the eval gate kept the old generation). The schema treats event
# names as free strings; this table is the documented vocabulary so
# consumers (report rendering, tests) have one source of truth.
SERVING_EVENTS = ("eject", "rebuild", "shed", "hedge", "drift",
                  "retrain", "promote")

# Event types the ELASTIC distributed layer emits into a training
# trace (resilience/elastic.py, docs/DISTRIBUTED.md "Elastic
# training"): `desync` = shards disagree on replicated-by-construction
# poll state (carries `shards`; feeds the on_divergence policy),
# `shard_lost` = a mesh shard died mid-run (the kill-shard drill /
# a real host loss), `reshard` = a resume re-sliced the global
# checkpoint state onto a different mesh (carries `from_shards` /
# `to_shards`; rewinds the n_iter baseline like `rollback` —
# observability/schema.REWIND_EVENTS).
DIST_EVENTS = ("desync", "shard_lost", "reshard")

# Event types the MULTI-HOST layer emits (resilience/hostgroup.py,
# docs/DISTRIBUTED.md "Multi-host"): `host_lost` = a real host process
# died or went heartbeat-silent past the deadline (requires `host_id`),
# `reform` = the group supervisor relaunched the survivors as a
# smaller process group resuming from the newest intact checkpoint
# (requires `from_hosts`/`to_hosts`; rewinds the n_iter baseline like
# `reshard` — observability/schema.REWIND_EVENTS). Both are written by
# the RESUMED attempt's driver from the supervisor's env markers, so
# the recovery story survives the fact that each attempt is a separate
# process writing a fresh trace file.
HOST_EVENTS = ("host_lost", "reform")

# Event types the streaming data layer emits into a training trace
# (data/stream.py, docs/DATA.md): `quarantine` = a data shard failed
# its CRC / finiteness check under on_bad_shard="quarantine" and was
# dropped from every later pass (carries `shard` + `reason` — the
# schema validator requires both); `ingest_resume` = a streaming train
# resumed from a checkpoint (carries the shard count; it rewinds
# NOTHING — deliberately not in schema.REWIND_EVENTS, the resumed
# n_iter baseline stands).
INGEST_EVENTS = ("quarantine", "ingest_resume")

# Event types the LIVE shard-log layer emits (data/live.py +
# approx/primal.fit_approx_stream(live=True) + the continuous-learning
# serving loop — docs/DATA.md "Live shard logs", docs/SERVING.md
# "Continuous learning"): `append_admitted` = one durable appended
# shard entered a reader's admitted view (requires shard + generation;
# carries rows), `ingest_grow` = a live training sweep boundary
# admitted new rows (requires generation + n_new_rows — the divisor/
# step-size math re-derived from the grown view), `refresh` = the
# serving loop chose its refresh flavor (requires refresh_kind =
# "incremental"|"full"; the key is NOT `kind` — that would collide
# with the record kind at write time), `refresh_resume` = a killed
# loop resumed at the gate with its durable candidate.
LIVE_EVENTS = ("append_admitted", "ingest_grow", "refresh",
               "refresh_resume")

# Span names the serving layer records per sampled request (schema v3+,
# docs/OBSERVABILITY.md "Spans"). The `request` root covers admission
# to response; its direct children are the sequential pipeline stages
# (`admission` = parse+validate, `queue_wait` = batcher queue,
# `batch_form` = coalescing window, `device_dispatch` = pool dispatch
# through the engine, `respond` = result assembly + send). Below the
# dispatch stage the pool records `replica_compute` per engine call
# and zero-length markers for the resilience machinery (`hedge_fired`
# / `hedge_won` / `redispatch`). Free strings to the schema; this
# table is the documented vocabulary, like SERVING_EVENTS.
SERVING_SPANS = ("request", "admission", "queue_wait", "batch_form",
                 "device_dispatch", "respond", "replica_compute",
                 "hedge_fired", "hedge_won", "redispatch")

# Event types the model-fleet layer emits into a serving trace
# (dpsvm_tpu/fleet/modelcache.py, docs/SERVING.md "Model fleet"):
# `model_fault` = a cold model was hydrated into the budgeted cache
# (requires `model` + `cold_start_ms` — the measured cold start is the
# whole point of the event; the fleet drill's p99 over these IS the
# `fleet_cold_start_p99_ms` ledger row), `model_evict` = the admission
# ledger paged a resident model's buffers out (requires `model`). The
# watchtower's `model-cache-thrash` rule rates the fault counter these
# events mirror (observability/slo.py).
FLEET_EVENTS = ("model_fault", "model_evict")

# Event types the C×γ grid trainer emits (dpsvm_tpu/fleet/grid.py,
# docs/PERF.md): `grid_cell` = one grid point solved + scored held-out
# (requires `c`/`gamma`/`holdout_acc`; carries n_sv + convergence),
# `grid_winner` = the selected cell (requires `c`/`gamma`; carries
# whether the cascade polish refit it). A grid trace is a training
# trace (solver="grid") whose summary reports the WINNING cell's
# duals plus grid_cells/grid_devices extras.
GRID_EVENTS = ("grid_cell", "grid_winner")

# Event types the continuous-watch layer emits (observability/slo.py +
# blackbox.py, docs/OBSERVABILITY.md "Watch & alerts"): `alert` = a
# rule crossed a state boundary (fired or cleared — `state` says
# which; the schema requires rule/window/severity), `incident` = the
# flight recorder dumped a bundle for a firing (adds `bundle`, the
# directory `dpsvm bundle` renders). Emitted into serving traces by
# the ServingServer's watchtower and into training traces by the
# shared host driver's watch hook.
WATCH_EVENTS = ("alert", "incident")

# Event types the cascade solver emits into its run trace
# (solver/cascade.py, docs/APPROX.md "Cascade"): `screen` = stage-2
# margin-band selection done (carries `n_kept`/`n_total` — the
# subproblem split), `polish` = one exact warm-started solve of the
# kept subproblem finished (carries `round`/`n_kept`), `readmit` =
# the KKT verify of the screened-out rows found violators and grew
# the kept set (carries `round`/`n_readmitted`), `cascade_resume` =
# the run picked up from a durable stage-boundary state file. The
# schema validator enforces the stage ordering
# (observability/schema.py EVENT_EXTRA_KEYS comment).
CASCADE_EVENTS = ("screen", "polish", "readmit", "cascade_resume")


def open_serving_trace(path: str, *, models: Optional[dict] = None,
                       env: Optional[dict] = None,
                       sample_rate: Optional[float] = None) -> "RunTrace":
    """A RunTrace for a SERVING process: manifest solver="serving",
    no chunk records — the manifest, `SERVING_EVENTS` markers as they
    happen, per-request `span` trees for sampled requests
    (``sample_rate``, recorded in the manifest config so a reader
    knows what fraction of traffic the spans represent), and a
    close_serving_trace() summary at drain. The artifact validates
    under the ordinary current schema, so `dpsvm report` and the trace
    tooling consume it unchanged."""
    config = {"models": dict(models or {})}
    if sample_rate is not None:
        config["trace_sample_rate"] = float(sample_rate)
    return RunTrace(path, solver="serving", config=config, env=env)


def close_serving_trace(tr: "RunTrace", *, requests: int = 0,
                        errors: int = 0, seconds: float = 0.0,
                        **extra) -> None:
    """Stamp the zero-filled solver summary a serving trace ends with
    (the solver fields are schema-required; a serving process has no
    duals, so they read as zeros) plus the serving counters."""
    if tr.closed:
        return
    tr.summary(converged=True, n_iter=0, b=0.0, b_lo=0.0, b_hi=0.0,
               n_sv=0, train_seconds=float(seconds),
               requests=int(requests), errors=int(errors), **extra)
    tr.close()


def _config_dict(config) -> dict:
    if config is None:
        return {}
    if dataclasses.is_dataclass(config):
        return dataclasses.asdict(config)
    return dict(config)


class RunTrace:
    """One training run's JSONL recorder.

    Construction writes the manifest; ``chunk``/``event``/``compile``
    append during the run; ``summary`` + ``close`` finish it. All
    record shapes are owned here so every producer (driver, shrink
    manager, benchmarks) emits the one schema
    observability/schema.validate_trace checks.

    The recorder also accumulates the run-level device facts the v2
    summary carries — compile count/seconds, the FLOPs estimate of the
    newest program, the high-water HBM mark across polls — so
    producers only report what they observe and the totals can never
    drift from the records they summarize.
    """

    def __init__(self, path: str, *, config=None, n: int = 0, d: int = 0,
                 gamma: float = 0.0, solver: str = "unknown",
                 it0: int = 0, env: Optional[dict] = None):
        config_d = _config_dict(config)
        kernel = {
            "kind": config_d.get("kernel", "rbf"),
            "gamma": float(gamma),
            "coef0": float(config_d.get("coef0", 0.0)),
            "degree": int(config_d.get("degree", 3)),
        }
        mesh = {"shards": int(config_d.get("shards", 1)),
                "shard_x": bool(config_d.get("shard_x", True))}
        from dpsvm_tpu import __version__
        self._w = TraceWriter(path)
        self._t0 = time.perf_counter()
        self._it0 = int(it0)
        self._closed = False
        self._n_compiles = 0
        self._compile_seconds = 0.0
        self._est_flops: Optional[float] = None
        self._est_bytes: Optional[float] = None
        self._hbm_peak: Optional[int] = None
        # Serving traces are written from many threads (handler threads
        # emitting request spans, pool workers emitting events): one
        # lock serializes the (timestamp, write) pair so `t` stays
        # non-decreasing in file order — the schema's ordering rule.
        self._lock = threading.Lock()
        self._w.write({
            "kind": "manifest",
            "schema": TRACE_SCHEMA_VERSION,
            "version": __version__,
            "solver": solver,
            "n": int(n),
            "d": int(d),
            "gamma": float(gamma),
            "kernel": kernel,
            "mesh": mesh,
            "env": dict(env or {"backend": None, "device_kind": None,
                                "device_count": None}),
            "config": config_d,
            "it0": int(it0),
            "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            # High-resolution wall-clock anchor for cross-host merge
            # (observability/merge.py): `t` values are perf_counter
            # offsets from THIS instant, so hosts sharing a wall clock
            # (one machine, or an NTP-synced pod) align exactly via
            # unix_k - unix_ref — the only anchor a constant straggler
            # lag cannot contaminate.
            "unix": time.time(),
        })
        _OPEN_TRACES.add(self)

    @property
    def path(self) -> str:
        return self._w.path

    @property
    def closed(self) -> bool:
        return self._closed

    def _t(self) -> float:
        return round(time.perf_counter() - self._t0, 6)

    def _note_hbm(self, hbm: Optional[dict]) -> Optional[dict]:
        if not hbm:
            return {"in_use": None, "peak": None, "limit": None}
        peak = hbm.get("peak")
        if peak is not None:
            self._hbm_peak = max(self._hbm_peak or 0, int(peak))
        return {"in_use": hbm.get("in_use"), "peak": peak,
                "limit": hbm.get("limit")}

    def chunk(self, *, n_iter: int, b_lo: float, b_hi: float,
              n_sv: int = 0, cache_hits: int = 0, cache_misses: int = 0,
              rounds: int = 0,
              phases: Optional[Dict[str, float]] = None,
              phase_counts: Optional[Dict[str, int]] = None,
              hbm: Optional[dict] = None,
              **extra) -> None:
        """One host-poll record. Every argument is already on the host
        (the packed-stats read; ``hbm`` is a host-side
        ``device.memory_stats()`` dictionary read) — recording is file
        I/O only."""
        rec = {
            "kind": "chunk",
            "n_iter": int(n_iter),
            "b_lo": float(b_lo),
            "b_hi": float(b_hi),
            "gap": float(b_lo) - float(b_hi),
            "n_sv": int(n_sv),
            "cache_hits": int(cache_hits),
            "cache_misses": int(cache_misses),
            "rounds": int(rounds),
            "t": self._t(),
            "phases": {k: round(float(v), 6)
                       for k, v in (phases or {}).items()},
            "phase_counts": {k: int(v)
                             for k, v in (phase_counts or {}).items()},
            "hbm": self._note_hbm(hbm),
        }
        rec.update(extra)
        self._w.write(rec)

    def event(self, event: str, *, n_iter: int = 0, **extra) -> None:
        """Solver lifecycle marker: checkpoint, program_swap (working-set
        growth), wall_budget, shrink, unshrink."""
        with self._lock:
            rec = {"kind": "event", "event": str(event),
                   "n_iter": int(n_iter), "t": self._t()}
            rec.update(extra)
            self._w.write(rec)

    def span(self, *, trace_id, span_id: int, parent: Optional[int],
             name: str, t_start: float, t_end: float, **extra) -> None:
        """One request-scoped span (schema v3+; serving producers:
        observability/spans.RequestSpans via ServingServer).
        ``t_start``/``t_end`` are ABSOLUTE time.perf_counter readings —
        the recorder rebases them onto its own t0 so every span shares
        the trace's clock. All spans of one request are emitted
        together at request completion, under the write lock, so
        records of concurrent requests interleave whole, never torn."""
        rel0 = round(float(t_start) - self._t0, 6)
        rel1 = round(float(t_end) - self._t0, 6)
        with self._lock:
            rec = {"kind": "span", "trace_id": trace_id,
                   "span_id": int(span_id),
                   "parent": int(parent) if parent is not None else None,
                   "name": str(name), "t_start": rel0, "t_end": rel1,
                   "t": self._t()}
            rec.update(extra)
            self._w.write(rec)

    def compile(self, *, program: str, seconds: float,
                signature: Optional[str] = None,
                flops: Optional[float] = None,
                bytes: Optional[float] = None, n_iter: int = 0,
                **extra) -> None:
        """One XLA compile (or retrace) of a chunk program
        (observability/compilewatch.py detects them; the driver drains
        its log here). ``flops``/``bytes`` are the program's
        cost_analysis estimates — on the chunk runners, the while-loop
        body counted once, i.e. ~per-iteration FLOPs and bytes-accessed
        (docs/OBSERVABILITY.md); together they are the arithmetic
        intensity the roofline verdict divides
        (observability/roofline.py)."""
        with self._lock:
            rec = {"kind": "compile", "program": str(program),
                   "seconds": round(float(seconds), 6),
                   "signature": signature,
                   "flops": float(flops) if flops is not None else None,
                   "bytes": float(bytes) if bytes is not None else None,
                   "n_iter": int(n_iter), "t": self._t()}
            rec.update(extra)
            self._n_compiles += 1
            self._compile_seconds += float(seconds)
            if flops is not None:
                self._est_flops = float(flops)
            if bytes is not None:
                self._est_bytes = float(bytes)
            self._w.write(rec)

    def summary(self, *, converged: bool, n_iter: int, b: float,
                b_lo: float, b_hi: float, n_sv: int,
                train_seconds: float, cache_hits: int = 0,
                cache_misses: int = 0,
                phases: Optional[Dict[str, float]] = None,
                phase_counts: Optional[Dict[str, int]] = None,
                **extra) -> None:
        iters = int(n_iter) - self._it0
        lookups = int(cache_hits) + int(cache_misses)
        rec = {
            "kind": "summary",
            "converged": bool(converged),
            "n_iter": int(n_iter),
            "iters": iters,
            "iters_per_sec": round(iters / train_seconds, 3)
            if train_seconds > 0 else 0.0,
            "b": float(b),
            "b_lo": float(b_lo),
            "b_hi": float(b_hi),
            "gap": float(b_lo) - float(b_hi),
            "n_sv": int(n_sv),
            "cache_hits": int(cache_hits),
            "cache_misses": int(cache_misses),
            "cache_hit_rate": round(cache_hits / lookups, 6)
            if lookups else None,
            "train_seconds": round(float(train_seconds), 6),
            "phases": {k: round(float(v), 6)
                       for k, v in (phases or {}).items()},
            "phase_counts": {k: int(v)
                             for k, v in (phase_counts or {}).items()},
            "n_compiles": self._n_compiles,
            "compile_seconds": round(self._compile_seconds, 6),
            "hbm_peak": self._hbm_peak,
            "est_flops": self._est_flops,
            "est_bytes": self._est_bytes,
            "t": self._t(),
        }
        rec.update(extra)
        with self._lock:
            rec["t"] = self._t()
            self._w.write(rec)

    def close(self) -> None:
        self._closed = True
        _OPEN_TRACES.discard(self)
        self._w.close()

"""Metrics federation: N hosts' metric surfaces -> ONE fleet view.

A multi-host run (resilience/hostgroup.py) leaves N per-host metric
surfaces — each host's ``--metrics-out`` snapshot file and/or its live
``/metricsz`` scrape endpoint — that no existing consumer can read
together: ``dpsvm watch`` tails ONE source, Prometheus would need N
scrape configs and still could not answer "which host is behind".
This module is the aggregation point the fleet observability plane
(docs/OBSERVABILITY.md "Fleet") hangs off:

* ``collect`` reads every host's source (file or URL, mixed freely)
  into per-host sample sets, tolerating unreachable hosts (an
  unreachable host is DATA — ``up = 0`` — not an error);
* ``federate`` folds them into one fleet snapshot: counters SUMMED
  (traffic adds), ages MAXED (the staleness that pages is the worst
  one), ``dpsvm_train_iterations`` MINED (the group's progress is its
  slowest member's — the collective waits for the straggler), plus a
  curated set of per-host series carrying a ``host`` label whose
  cardinality is bounded by the same ``TenantLabelBudget`` machinery
  that bounds tenant labels (metrics.py) — a 300-host fleet cannot
  explode the label space;
* ``render_exposition`` emits the fleet snapshot as a Prometheus text
  exposition that passes ``metrics.validate_exposition`` — one scrape
  target for the whole group;
* ``fleet_watch_sample`` flattens the same facts into the
  ``host:<k>:<metric>`` watch-sample lanes the ``skew`` rule and the
  ``per_host`` templates read (slo.py);
* ``host_artifacts`` gathers every host's heartbeat, trace tail and
  doctor line for the fleet incident bundle (blackbox.py).

Histogram component series (``_bucket``/``_sum``/``_count``) are
deliberately dropped from federation: bucket-wise summation is only
valid when every host uses identical ``le`` grids, and a silently
wrong latency histogram is worse than none. The scalar families carry
the fleet story.

Stdlib only, no backend init: ``dpsvm fleet`` must run on a machine
with no accelerator (the same contract as schema.py/merge.py).
"""

from __future__ import annotations

import json
import math
import os
import re
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

from dpsvm_tpu.observability.metrics import (DEFAULT_TENANT_BUDGET,
                                             TenantLabelBudget,
                                             _SAMPLE_RE, _split_labels)
from dpsvm_tpu.observability.slo import parse_snapshot_header

#: exposition families that get a per-host labelled series in the
#: federated output (name here -> fleet family name). Curated, not
#: everything: per-host fan-out multiplies series count by host count,
#: so only the lanes straggler/skew debugging actually reads ride it.
PER_HOST_SERIES = {
    "dpsvm_train_iterations": "dpsvm_host_iterations",
    "dpsvm_train_gap": "dpsvm_host_gap",
    "dpsvm_train_n_sv": "dpsvm_host_n_sv",
    "dpsvm_train_compiles_total": "dpsvm_host_compiles_total",
}

#: federated family -> aggregation override. Everything else follows
#: the suffix rules: ``*_total`` sums, ``*age*`` maxes, rest maxes.
_AGG_OVERRIDES = {
    "dpsvm_train_iterations": "min",
}

_HIST_SUFFIXES = ("_bucket", "_sum", "_count")

#: hostgroup heartbeat file naming (resilience/hostgroup.py
#: write_heartbeat) — the generation/seq side-channel of federation.
HEARTBEAT_FILE_RE = re.compile(r"^host-(?P<host>\d+)\.json$")


class FleetError(ValueError):
    """A fleet source list that cannot be used at all (empty, or
    host ids that collide)."""


# ---------------------------------------------------------------------
# source reading
# ---------------------------------------------------------------------

def _is_url(src: str) -> bool:
    return src.startswith("http://") or src.startswith("https://")


def _scrape_url(src: str) -> str:
    """Normalize a host source URL to its Prometheus scrape endpoint
    (the serving/metrics servers expose ``/metricsz?format=
    prometheus``); a URL already naming /metricsz is kept."""
    if "metricsz" in src:
        return src
    return src.rstrip("/") + "/metricsz?format=prometheus"


def read_source(src: str, *, timeout: float = 5.0) -> str:
    """One host's exposition text from a snapshot file or a live URL.
    Raises OSError on an unreachable source (collect() converts that
    into ``up=0`` data)."""
    if _is_url(src):
        import urllib.error
        import urllib.request
        try:
            with urllib.request.urlopen(_scrape_url(src),
                                        timeout=timeout) as r:
                return r.read().decode("utf-8", "replace")
        except urllib.error.URLError as e:
            raise OSError(str(e))
    with open(src) as fh:
        return fh.read()


def parse_exposition(text: str) -> List[Tuple[str, Dict[str, str],
                                              float]]:
    """(name, labels, value) triples from an exposition text; bad
    lines are skipped (a half-written foreign file must not kill the
    fleet view — the snapshot writer is atomic, scrapes are whole)."""
    out: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        labels_raw = m.group("labels")
        labels = _split_labels(labels_raw) if labels_raw else []
        if labels is None:
            continue
        try:
            v = float(m.group("value").replace("+Inf", "inf")
                      .replace("-Inf", "-inf").replace("NaN", "nan"))
        except ValueError:
            continue
        out.append((m.group("name"), dict(labels), v))
    return out


def resolve_sources(sources: Sequence[str]) -> Dict[int, str]:
    """host id -> source. Ids are parsed from ``host-K``/``h{K}``/
    ``hostK`` markers in the source string (file names like
    ``metrics_h1.prom``, URLs like ``http://...:9101`` get positional
    ids when nothing matches). Colliding explicit ids are an error —
    two sources claiming host 1 would silently double-count."""
    if not sources:
        raise FleetError("no fleet sources given")
    out: Dict[int, str] = {}
    unnumbered: List[str] = []
    for src in sources:
        base = os.path.basename(src.rstrip("/")) if not _is_url(src) \
            else src
        m = re.search(r"(?:host-?|_h|\bh)(\d+)", base)
        if m:
            host = int(m.group(1))
            if host in out:
                raise FleetError(
                    f"host {host} claimed twice: {out[host]} and {src}")
            out[host] = src
        else:
            unnumbered.append(src)
    nxt = 0
    for src in unnumbered:
        while nxt in out:
            nxt += 1
        out[nxt] = src
        nxt += 1
    return dict(sorted(out.items()))


def collect(sources: Union[Dict[int, str], Sequence[str]], *,
            timeout: float = 5.0,
            now: Optional[float] = None) -> Dict[int, dict]:
    """Read every host's source. Returns host -> state dict:
    ``{"source", "up", "error", "seq", "unix", "age_s", "samples"}``.
    An unreachable host comes back ``up=0`` with the error string —
    the fleet view must render precisely when a host is sick."""
    if not isinstance(sources, dict):
        sources = resolve_sources(list(sources))
    if not sources:
        raise FleetError("no fleet sources given")
    now = time.time() if now is None else float(now)
    out: Dict[int, dict] = {}
    for host, src in sorted(sources.items()):
        st = {"source": src, "up": 1, "error": None, "seq": None,
              "unix": None, "age_s": None, "samples": []}
        try:
            text = read_source(src, timeout=timeout)
        except OSError as e:
            st["up"] = 0
            st["error"] = str(e)
            out[host] = st
            continue
        header = parse_snapshot_header(text)
        if header is not None:
            st["seq"] = header["seq"]
            st["unix"] = header["unix"]
            st["age_s"] = max(0.0, now - header["unix"])
        elif not _is_url(src):
            # a headerless FILE has only its mtime as a staleness fact
            try:
                st["age_s"] = max(0.0, now - os.path.getmtime(src))
            except OSError:
                pass
        else:
            st["age_s"] = 0.0       # a live scrape that answered IS fresh
        st["samples"] = parse_exposition(text)
        out[host] = st
    return out


def read_heartbeats(hosts_dir: str,
                    now: Optional[float] = None) -> Dict[int, dict]:
    """The hostgroup heartbeat files (``host-K.json``) as host ->
    record, each annotated with ``age_s`` (wall clock vs the record's
    own ``t``) and ``path``. Unreadable/corrupt files yield
    ``{"error": ...}`` — a torn heartbeat is a finding, not a crash."""
    now = time.time() if now is None else float(now)
    out: Dict[int, dict] = {}
    try:
        names = os.listdir(hosts_dir)
    except OSError:
        return out
    for name in sorted(names):
        m = HEARTBEAT_FILE_RE.match(name)
        if m is None:
            continue
        host = int(m.group("host"))
        path = os.path.join(hosts_dir, name)
        try:
            with open(path) as fh:
                rec = json.load(fh)
            if not isinstance(rec, dict):
                raise ValueError("not an object")
        except (OSError, ValueError) as e:
            out[host] = {"error": str(e), "path": path}
            continue
        rec = dict(rec)
        rec["path"] = path
        t = rec.get("t")
        if isinstance(t, (int, float)):
            rec["age_s"] = max(0.0, now - float(t))
        out[host] = rec
    return out


# ---------------------------------------------------------------------
# federation
# ---------------------------------------------------------------------

def _is_hist_component(name: str) -> bool:
    return any(name.endswith(s) for s in _HIST_SUFFIXES)


def _host_scalar(samples, name: str) -> Optional[float]:
    """One host's scalar value for a family: multi-series families
    collapse the way sample_from_prometheus does (sum counters, max
    the rest)."""
    vals = [v for n, _lbl, v in samples
            if n == name and not math.isnan(v)]
    if not vals:
        return None
    return sum(vals) if name.endswith("_total") else max(vals)


def federate(host_state: Dict[int, dict], *,
             budget: Optional[TenantLabelBudget] = None,
             heartbeats: Optional[Dict[int, dict]] = None) -> dict:
    """Fold per-host sample sets into one fleet snapshot dict:

    ``aggregate``   family -> fleet scalar (sum/max/min per the rules),
    ``per_host``    fleet family -> {host_label: value} for the
                    curated PER_HOST_SERIES plus liveness/age lanes,
    ``hosts``       host -> digest (up, seq, age_s, n_iter, gap, ...),
    ``lag``         fleet iteration lag (max - min over live hosts),
    ``slowest``     the host holding the minimum iteration count.

    ``budget`` bounds the ``host`` label exactly like tenant labels:
    out-of-budget hosts collapse into the ``other`` series (their
    values AGGREGATE — sum for counters, max for gauges)."""
    if not host_state:
        raise FleetError("no hosts collected")
    budget = budget or TenantLabelBudget(DEFAULT_TENANT_BUDGET)
    heartbeats = heartbeats or {}

    # fleet scalars
    agg: Dict[str, float] = {}
    per_family_vals: Dict[str, List[float]] = {}
    for host, st in host_state.items():
        names = {n for n, _lbl, _v in st["samples"]}
        for name in names:
            if _is_hist_component(name):
                continue
            v = _host_scalar(st["samples"], name)
            if v is not None:
                per_family_vals.setdefault(name, []).append(v)
    for name, vals in per_family_vals.items():
        how = _AGG_OVERRIDES.get(name)
        if how is None:
            if name.endswith("_total"):
                how = "sum"
            elif "age" in name:
                how = "max"
            else:
                how = "max"
        agg[name] = (sum(vals) if how == "sum"
                     else min(vals) if how == "min" else max(vals))

    # per-host labelled series, label bounded by the budget. An
    # overflowed host's values MERGE into the `other` series. One
    # resolve per host per pass: lanes of the same host must all land
    # under ONE label, and repeated touches inside a single federation
    # pass must not churn the budget's LRU (the two-touch admission is
    # calibrated for request streams, not for the ~6 series each host
    # contributes here).
    per_host: Dict[str, Dict[str, float]] = {}
    label_of = {host: budget.resolve(str(host))
                for host in sorted(host_state)}

    def _lane(family: str, host: int, value: float,
              counter: bool) -> None:
        label = label_of[host]
        lanes = per_host.setdefault(family, {})
        if label in lanes:
            lanes[label] = (lanes[label] + value if counter
                            else max(lanes[label], value))
        else:
            lanes[label] = value

    hosts: Dict[int, dict] = {}
    iters: Dict[int, float] = {}
    for host, st in sorted(host_state.items()):
        digest = {"source": st["source"], "up": st["up"],
                  "error": st["error"], "seq": st["seq"],
                  "age_s": st["age_s"]}
        _lane("dpsvm_host_up", host, float(st["up"]), False)
        if st["age_s"] is not None:
            _lane("dpsvm_host_heartbeat_age_seconds", host,
                  float(st["age_s"]), False)
        for src_name, fleet_name in PER_HOST_SERIES.items():
            v = _host_scalar(st["samples"], src_name)
            if v is None:
                continue
            _lane(fleet_name, host, v,
                  fleet_name.endswith("_total"))
            key = {"dpsvm_host_iterations": "n_iter",
                   "dpsvm_host_gap": "gap",
                   "dpsvm_host_n_sv": "n_sv",
                   "dpsvm_host_compiles_total": "compiles"}[fleet_name]
            digest[key] = v
            if fleet_name == "dpsvm_host_iterations":
                iters[host] = v
        hb = heartbeats.get(host)
        if hb and not hb.get("error"):
            for k in ("generation", "seq", "n_iter"):
                if isinstance(hb.get(k), (int, float)):
                    digest[f"hb_{k}"] = hb[k]
            if isinstance(hb.get("age_s"), (int, float)):
                digest["hb_age_s"] = hb["age_s"]
                _lane("dpsvm_host_heartbeat_age_seconds", host,
                      float(hb["age_s"]), False)
        hosts[host] = digest

    # group-generation fact for the reform-storm rule: the heartbeat
    # files carry it (hostgroup increments it at every reformation)
    gens = [hb.get("generation") for hb in heartbeats.values()
            if isinstance(hb.get("generation"), (int, float))]
    if gens:
        agg["dpsvm_fleet_generation"] = float(max(gens))

    lag = (max(iters.values()) - min(iters.values())) if len(iters) > 1 \
        else 0.0
    slowest = (min(iters, key=lambda h: (iters[h], h))
               if len(iters) > 1 else None)
    agg["dpsvm_fleet_hosts"] = float(len(host_state))
    agg["dpsvm_fleet_hosts_up"] = float(
        sum(st["up"] for st in host_state.values()))
    agg["dpsvm_fleet_iteration_lag"] = float(lag)
    return {"hosts": hosts, "aggregate": agg, "per_host": per_host,
            "lag": float(lag), "slowest": slowest}


# ---------------------------------------------------------------------
# output surfaces
# ---------------------------------------------------------------------

def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def render_exposition(snapshot: dict) -> str:
    """The fleet snapshot as a Prometheus text exposition — passes
    ``metrics.validate_exposition`` (pinned in tests): one TYPE line
    per family, families contiguous, counters are the ``_total``
    names."""
    lines: List[str] = []
    for name in sorted(snapshot["aggregate"]):
        kind = "counter" if name.endswith("_total") else "gauge"
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {_fmt(snapshot['aggregate'][name])}")
    for family in sorted(snapshot["per_host"]):
        kind = "counter" if family.endswith("_total") else "gauge"
        lines.append(f"# TYPE {family} {kind}")
        for label in sorted(snapshot["per_host"][family],
                            key=lambda s: (len(s), s)):
            v = snapshot["per_host"][family][label]
            lines.append(f'{family}{{host="{label}"}} {_fmt(v)}')
    return "\n".join(lines) + "\n"


def fleet_watch_sample(snapshot: dict) -> Dict[str, float]:
    """The watch-sample the fleet rules read (slo.py): per-host lanes
    as ``host:<k>:<metric>`` plus the fleet scalars under their
    canonical names (``generation`` feeds the reform-storm rule,
    ``n_iter`` the fleet-progress view)."""
    out: Dict[str, float] = {}
    for host, digest in snapshot["hosts"].items():
        for key in ("n_iter", "gap", "n_sv", "compiles"):
            v = digest.get(key)
            if isinstance(v, (int, float)):
                out[f"host:{host}:{key}"] = float(v)
        age = digest.get("hb_age_s", digest.get("age_s"))
        if isinstance(age, (int, float)):
            out[f"host:{host}:heartbeat_age_seconds"] = float(age)
        out[f"host:{host}:up"] = float(digest.get("up", 0))
    agg = snapshot["aggregate"]
    out["hosts"] = agg.get("dpsvm_fleet_hosts", 0.0)
    out["hosts_up"] = agg.get("dpsvm_fleet_hosts_up", 0.0)
    out["iteration_lag"] = agg.get("dpsvm_fleet_iteration_lag", 0.0)
    out["generation"] = agg.get("dpsvm_fleet_generation", 0.0)
    if "dpsvm_train_iterations" in agg:
        out["n_iter"] = agg["dpsvm_train_iterations"]
    return out


def render_fleet_table(snapshot: dict) -> str:
    """The human `dpsvm fleet` surface: one row per host — progress,
    lag behind the group's fastest member, staleness, liveness — with
    the slowest host marked. Degrades gracefully when a lane is
    missing (an unreachable host still gets its row; that row IS the
    finding)."""
    iters = {h: d.get("n_iter") for h, d in snapshot["hosts"].items()
             if isinstance(d.get("n_iter"), (int, float))}
    fastest = max(iters.values()) if iters else None
    rows = [("host", "up", "iter", "lag", "gap", "hb-age", "seq",
             "source")]
    for host in sorted(snapshot["hosts"]):
        d = snapshot["hosts"][host]
        it = d.get("n_iter")
        lag = (f"{fastest - it:g}" if isinstance(it, (int, float))
               and fastest is not None else "-")
        age = d.get("hb_age_s", d.get("age_s"))
        mark = " <- slowest" if host == snapshot["slowest"] else ""
        rows.append((
            str(host), str(d.get("up", "?")),
            f"{it:g}" if isinstance(it, (int, float)) else "-", lag,
            f"{d['gap']:.3g}" if isinstance(d.get("gap"),
                                            (int, float)) else "-",
            f"{age:.1f}s" if isinstance(age, (int, float)) else "-",
            str(d.get("seq", d.get("hb_seq", "-")) or "-"),
            str(d.get("source", "-")) + mark))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    out = []
    for r in rows:
        out.append("  ".join(c.ljust(w) for c, w in zip(r, widths))
                   .rstrip())
    out.append(f"fleet: {int(snapshot['aggregate'].get('dpsvm_fleet_hosts', 0))} "
               f"host(s), iteration lag {snapshot['lag']:g}"
               + (f", slowest host {snapshot['slowest']}"
                  if snapshot["slowest"] is not None else ""))
    return "\n".join(out)


# ---------------------------------------------------------------------
# incident-bundle artifact collection
# ---------------------------------------------------------------------

def host_artifacts(trace_dir: Optional[str] = None,
                   hosts_dir: Optional[str] = None, *,
                   tail_lines: int = 40,
                   now: Optional[float] = None) -> Dict[int, dict]:
    """Every host's forensic artifacts for a fleet incident bundle
    (blackbox.dump_bundle ``host_artifacts=``): per host a dict of
    ``heartbeat`` (the parsed heartbeat record), ``trace_tail`` (the
    last lines of its newest trace file) and ``doctor`` (a one-host
    liveness diagnosis line). Best-effort per host — a dead host's
    missing pieces must not block bundling the survivors' evidence."""
    out: Dict[int, dict] = {}
    hbs = read_heartbeats(hosts_dir, now=now) if hosts_dir else {}
    fams: Dict[int, str] = {}
    if trace_dir:
        from dpsvm_tpu.observability import merge
        fams = merge.discover_family(trace_dir)
    for host in sorted(set(hbs) | set(fams)):
        art: dict = {}
        hb = hbs.get(host)
        if hb is not None:
            art["heartbeat"] = hb
        path = fams.get(host)
        if path:
            try:
                with open(path) as fh:
                    art["trace_tail"] = fh.readlines()[-tail_lines:]
                art["trace_path"] = path
            except OSError:
                pass
        lines = [f"host {host}:"]
        if hb is None:
            lines.append("  heartbeat: MISSING")
        elif hb.get("error"):
            lines.append(f"  heartbeat: UNREADABLE ({hb['error']})")
        else:
            age = hb.get("age_s")
            lines.append(
                f"  heartbeat: n_iter={hb.get('n_iter')} "
                f"seq={hb.get('seq')} "
                f"generation={hb.get('generation')} "
                f"age={age:.1f}s" if isinstance(age, (int, float))
                else f"  heartbeat: n_iter={hb.get('n_iter')} "
                     f"seq={hb.get('seq')}")
        lines.append(f"  trace: {os.path.basename(path) if path else 'MISSING'}")
        art["doctor"] = "\n".join(lines) + "\n"
        out[host] = art
    return out

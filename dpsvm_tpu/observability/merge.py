"""Cross-host trace merge: N per-host traces -> ONE fleet trace.

A multi-host run (resilience/hostgroup.py) emits one trace file per
host process — the ``trace_h{K}_a{N}.jsonl`` family the supervisor
names — and each file's ``t`` axis is RELATIVE to that process's own
start (observability/record.py keeps ``t = perf_counter() - t0``), so
the three files of a 3-host run are three disjoint, mutually
unalignable timelines. No existing tool can answer the questions a
group run raises: which host is the straggler, how far does iteration
progress skew, where did the group lose/reform a member.

This module closes that: ``merge_traces`` ingests the per-host record
streams, aligns their clocks via shared anchors, tags every record
with its ``host``, and emits ONE schema-v5 trace
(``schema.FLEET_SCHEMA_VERSION``) that ``validate_trace`` accepts and
``dpsvm report`` renders with per-host lanes.

Clock alignment (best anchor wins, per host, against the lowest host
id as the reference timeline):

1. **Manifest ``unix`` anchor** — record.py stamps ``time.time()`` at
   the instant its ``t`` axis starts, so hosts sharing a wall clock
   (one machine, an NTP-synced pod) align EXACTLY via
   ``unix_k - unix_ref``. This is the only anchor a straggler cannot
   contaminate: a host that is uniformly late at every chunk is
   indistinguishable from clock skew under content anchors, but its
   wall-clock lateness survives a wall-clock offset untouched — which
   is exactly the signal straggler attribution needs.
2. **Matched chunk records** — hosts of one data-parallel group step
   the same ``n_iter`` schedule in lockstep (the collectives inside
   each chunk are a barrier), so a chunk with the same ``n_iter`` is
   the same group-wide instant. The offset is the MEDIAN of
   ``t_ref(n) - t_k(n)`` over the common n_iter set — median, because
   the straggler's publish delay is exactly the per-anchor noise we
   must not average in.
3. **Matched recovery markers** — ``host_lost``/``reform`` events are
   emitted by every surviving host at the same group transition;
   occurrence-matched pairs anchor traces that share no chunk (a host
   that died before its first poll).
4. **Manifest wall clock** — the coarse fallback: the manifests'
   ``time`` stamps (1 s resolution) difference.

Identity: traces merge only when their manifests agree on the run
fingerprint (solver, n, d, gamma, kernel) — merging two different
runs' families is a user error (``MergeError``), never a silent
garbage trace.

Shape rules of the merged stream: every body record gains ``host`` and
its ``t`` (and span ``t_start``/``t_end``) moves onto the fleet
timeline; span ``trace_id``s are prefixed ``h{K}:`` so concurrent
hosts' ids can never collide; each host's own summary is demoted to a
``host_summary`` event (the one-summary rule belongs to the
synthesized FLEET summary: converged = every host converged,
n_iter/train_seconds = group max).

Dependency-free (stdlib only), like schema.py: ``dpsvm report`` on a
merged family must run on a machine with no accelerator.
"""

from __future__ import annotations

import json
import os
import re
import statistics
import time
from typing import Dict, List, Optional, Sequence, Union

from dpsvm_tpu.observability.schema import (FLEET_SCHEMA_VERSION,
                                            SUMMARY_KEYS, read_trace)

#: the hostgroup supervisor's per-host trace naming
#: (resilience/hostgroup.py host_loss_drill.make_argv)
TRACE_FAMILY_RE = re.compile(
    r"^trace_h(?P<host>\d+)(?:_a(?P<attempt>\d+))?\.jsonl$")

#: manifest keys two traces must agree on to be the same run
FINGERPRINT_KEYS = ("solver", "n", "d", "gamma", "kernel")

#: events matchable by occurrence index as cross-host clock anchors
ANCHOR_EVENTS = ("host_lost", "reform")


class MergeError(ValueError):
    """The trace family cannot be merged: mismatched run fingerprints,
    a record stream with no manifest, or no traces at all."""


def discover_family(dir_path: str) -> Dict[int, str]:
    """Map host id -> newest per-host trace path under ``dir_path``.

    "Newest" is the highest attempt number (``_a{N}``; a bare
    ``trace_h{K}.jsonl`` counts as attempt 0) — after a reformation
    the surviving hosts' a1 traces carry the recovery story, while the
    dead host keeps only its a0 trace. Returns {} when the directory
    holds no family members (callers decide whether that is an
    error)."""
    best: Dict[int, tuple] = {}
    try:
        names = os.listdir(dir_path)
    except OSError:
        return {}
    for name in names:
        m = TRACE_FAMILY_RE.match(name)
        if not m:
            continue
        host = int(m.group("host"))
        attempt = int(m.group("attempt") or 0)
        if host not in best or attempt > best[host][0]:
            best[host] = (attempt, os.path.join(dir_path, name))
    return {h: p for h, (_a, p) in sorted(best.items())}


def fingerprint(manifest: dict) -> dict:
    return {k: manifest.get(k) for k in FINGERPRINT_KEYS}


def _manifest_epoch(manifest: dict) -> Optional[float]:
    """The manifest ``time`` stamp as a unix epoch (None when
    unparseable) — the coarse clock-alignment fallback."""
    raw = str(manifest.get("time") or "")
    for fmt in ("%Y-%m-%dT%H:%M:%S%z", "%Y-%m-%dT%H:%M:%S"):
        try:
            st = time.strptime(raw, fmt)
        except ValueError:
            continue
        try:
            return time.mktime(st) - (st.tm_gmtoff or 0) \
                if fmt.endswith("%z") else time.mktime(st)
        except (OverflowError, ValueError):
            return None
    return None


def _chunk_anchors(records: Sequence[dict]) -> Dict[int, float]:
    """First chunk ``t`` per ``n_iter`` value."""
    out: Dict[int, float] = {}
    for r in records:
        if r.get("kind") != "chunk":
            continue
        n, t = r.get("n_iter"), r.get("t")
        if isinstance(n, int) and isinstance(t, (int, float)) \
                and n not in out:
            out[n] = float(t)
    return out


def _event_anchors(records: Sequence[dict]) -> Dict[tuple, float]:
    """``t`` per (event name, occurrence index) for ANCHOR_EVENTS."""
    counts: Dict[str, int] = {}
    out: Dict[tuple, float] = {}
    for r in records:
        if r.get("kind") != "event" or r.get("event") not in ANCHOR_EVENTS:
            continue
        ev, t = str(r["event"]), r.get("t")
        idx = counts.get(ev, 0)
        counts[ev] = idx + 1
        if isinstance(t, (int, float)):
            out[(ev, idx)] = float(t)
    return out


def align_offsets(traces: Dict[int, List[dict]]) -> Dict[int, float]:
    """Per-host clock offsets onto the reference (lowest host id)
    timeline: ``t_fleet = t_host + offset``. Anchor preference per the
    module docstring; a host sharing no anchor at all with the
    reference gets offset 0.0 (already honest — there is nothing to
    align against)."""
    ref = min(traces)
    ref_chunks = _chunk_anchors(traces[ref])
    ref_events = _event_anchors(traces[ref])
    ref_epoch = _manifest_epoch(traces[ref][0])
    ref_unix = traces[ref][0].get("unix")
    offsets: Dict[int, float] = {ref: 0.0}
    for host, records in traces.items():
        if host == ref:
            continue
        unix = records[0].get("unix")
        if isinstance(unix, (int, float)) \
                and isinstance(ref_unix, (int, float)):
            offsets[host] = float(unix) - float(ref_unix)
            continue
        chunks = _chunk_anchors(records)
        common = sorted(set(ref_chunks) & set(chunks))
        if common:
            offsets[host] = statistics.median(
                ref_chunks[n] - chunks[n] for n in common)
            continue
        events = _event_anchors(records)
        shared = sorted(set(ref_events) & set(events))
        if shared:
            offsets[host] = statistics.median(
                ref_events[k] - events[k] for k in shared)
            continue
        epoch = _manifest_epoch(records[0])
        if ref_epoch is not None and epoch is not None:
            offsets[host] = epoch - ref_epoch
        else:
            offsets[host] = 0.0
    return offsets


def _check_fingerprints(traces: Dict[int, List[dict]]) -> None:
    for host, records in traces.items():
        if not records or records[0].get("kind") != "manifest":
            raise MergeError(
                f"host {host}: trace does not start with a manifest "
                "record — not a run trace")
    ref = min(traces)
    want = fingerprint(traces[ref][0])
    bad = []
    for host in sorted(traces):
        got = fingerprint(traces[host][0])
        if got != want:
            fields = sorted(k for k in FINGERPRINT_KEYS
                            if got.get(k) != want.get(k))
            bad.append(f"host {host} differs on {fields} "
                       f"({ {k: got[k] for k in fields} } vs "
                       f"{ {k: want[k] for k in fields} })")
    if bad:
        raise MergeError(
            "refusing to merge traces of different runs: "
            + "; ".join(bad))


def _demote_summary(summary: dict, host: int) -> dict:
    """A host's own summary as a ``host_summary`` event record — the
    merged trace keeps exactly one (synthesized) summary."""
    rec = {"kind": "event", "event": "host_summary", "host": host,
           "n_iter": int(summary.get("n_iter", 0) or 0),
           "t": summary.get("t", 0.0)}
    for k in ("converged", "iters", "iters_per_sec", "gap", "n_sv",
              "train_seconds"):
        if k in summary:
            rec[k] = summary[k]
    return rec


def merge_traces(traces: Dict[int, List[dict]],
                 sources: Optional[Dict[int, str]] = None) -> List[dict]:
    """Merge per-host record streams into one schema-v5 fleet trace.

    ``traces`` maps host id -> that host's records (manifest first, as
    ``read_trace`` returns them). Raises MergeError on an empty input,
    a stream with no manifest, or mismatched run fingerprints. The
    result validates under ``schema.validate_trace`` — the caller owes
    no post-processing."""
    if not traces:
        raise MergeError("no traces to merge")
    _check_fingerprints(traces)
    offsets = align_offsets(traces)
    ref = min(traces)

    body: List[dict] = []
    host_summaries: Dict[int, dict] = {}
    for host in sorted(traces):
        off = offsets[host]
        for r in traces[host][1:]:
            if not isinstance(r, dict):
                continue
            rec = dict(r)
            rec["host"] = host
            t = rec.get("t")
            if isinstance(t, (int, float)):
                rec["t"] = round(float(t) + off, 6)
            if rec.get("kind") == "span":
                for k in ("t_start", "t_end"):
                    tv = rec.get(k)
                    if isinstance(tv, (int, float)):
                        rec[k] = round(float(tv) + off, 6)
                rec["trace_id"] = f"h{host}:{rec.get('trace_id')}"
            if rec.get("kind") == "summary":
                host_summaries[host] = rec
                rec = _demote_summary(rec, host)
            body.append(rec)

    # one fleet timeline: non-decreasing t, >= 0. The sort is stable,
    # so each host's own record order (the per-lane n_iter contract)
    # survives; the rebase absorbs a reference host that started later
    # than a peer.
    body.sort(key=lambda r: (r.get("t", 0.0),))
    t_min = min((r["t"] for r in body
                 if isinstance(r.get("t"), (int, float))), default=0.0)
    if t_min < 0:
        for r in body:
            if isinstance(r.get("t"), (int, float)):
                r["t"] = round(r["t"] - t_min, 6)
            if r.get("kind") == "span":
                for k in ("t_start", "t_end"):
                    if isinstance(r.get(k), (int, float)):
                        r[k] = round(r[k] - t_min, 6)

    manifest = dict(traces[ref][0])
    manifest["schema"] = FLEET_SCHEMA_VERSION
    manifest["merged"] = True
    manifest["hosts"] = {
        str(h): {"offset_s": round(offsets[h], 6),
                 "schema": traces[h][0].get("schema"),
                 "source": (os.path.basename(sources[h])
                            if sources and h in sources else None)}
        for h in sorted(traces)}

    out = [manifest] + body
    if host_summaries:
        out.append(_fleet_summary(host_summaries, offsets, body))
    return out


def _fleet_summary(host_summaries: Dict[int, dict],
                   offsets: Dict[int, float],
                   body: List[dict]) -> dict:
    """One group-level summary synthesized from the hosts' own: the
    group converged iff EVERY host converged, progress facts are the
    group max (the group is done when its slowest member is)."""
    ref = min(host_summaries)
    summary = dict(host_summaries[ref])
    summary["converged"] = all(bool(s.get("converged"))
                               for s in host_summaries.values())
    for k in ("n_iter", "iters"):
        summary[k] = max(int(s.get(k, 0) or 0)
                         for s in host_summaries.values())
    summary["train_seconds"] = round(
        max(float(s.get("train_seconds", 0.0) or 0.0)
            for s in host_summaries.values()), 6)
    summary["t"] = max([r.get("t", 0.0) for r in body] + [0.0])
    summary["host"] = None          # group-level, no single lane
    summary["fleet_hosts"] = sorted(host_summaries)
    for k in SUMMARY_KEYS:
        summary.setdefault(k, None)
    return summary


def merge_paths(paths: Union[Dict[int, str], Sequence[str]]
                ) -> List[dict]:
    """Merge trace FILES. ``paths`` is host->path, or a sequence whose
    host ids are parsed from the ``trace_h{K}`` names (positional ids
    as the fallback for alien names)."""
    if not isinstance(paths, dict):
        resolved: Dict[int, str] = {}
        for i, p in enumerate(paths):
            m = TRACE_FAMILY_RE.match(os.path.basename(p))
            host = int(m.group("host")) if m else i
            if host in resolved:
                raise MergeError(
                    f"duplicate host {host}: {resolved[host]} and {p}")
            resolved[host] = p
        paths = resolved
    if not paths:
        raise MergeError("no traces to merge")
    traces = {h: read_trace(p) for h, p in paths.items()}
    return merge_traces(traces, sources=dict(paths))


def merge_dir(dir_path: str) -> List[dict]:
    """Merge the newest-attempt trace family found under a directory
    (the hostgroup run dir)."""
    fam = discover_family(dir_path)
    if not fam:
        raise FileNotFoundError(
            f"{dir_path}: no trace_h*.jsonl family members")
    return merge_paths(fam)


def write_merged(records: List[dict], out_path: str) -> str:
    """Write a merged trace as JSONL (the shape ``dpsvm report`` and
    ``validate_trace`` read back)."""
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        for rec in records:
            fh.write(json.dumps(rec) + "\n")
    os.replace(tmp, out_path)
    return out_path

"""Trace consumers: digest, ASCII report, live ``--follow`` tail.

Everything here is pure file I/O over the JSONL schema
(observability/schema.py) — no backend init, so reports render on a
machine with no accelerator (or a dead tunnel), which is exactly when
they are needed most.
"""

from __future__ import annotations

import math
import os
import sys
import time
from typing import Callable, Dict, List, Optional

from dpsvm_tpu.observability.schema import (TERMINAL_EVENTS, read_trace,
                                            validate_trace)


def load_trace(path: str) -> List[dict]:
    """read + validate; raises ValueError with every problem listed."""
    records = read_trace(path)
    errors = validate_trace(records)
    if errors:
        raise ValueError(f"invalid trace {path}: " + "; ".join(errors))
    return records


def resolve_trace_path(path: str) -> str:
    """A trace argument may be a directory (the burst runner archives
    under ``<results>/traces/``): resolve to its newest ``*.jsonl``.
    Plain files pass through untouched.

    A directory holding a MULTI-host ``trace_h{K}_a{N}`` family
    (resilience/hostgroup.py) is refused with the host list: "newest
    file" would silently answer for one arbitrary host of a group run.
    Callers that can merge use ``load_trace_auto`` instead."""
    if not os.path.isdir(path):
        return path
    from dpsvm_tpu.observability import merge as _merge
    family = _merge.discover_family(path)
    if len(family) > 1:
        raise ValueError(
            f"{path}: holds a {len(family)}-host trace family "
            f"(hosts {', '.join(str(h) for h in sorted(family))}) — "
            "a single newest file would be one arbitrary host's view. "
            "Use `dpsvm report` on the directory (merges the family) "
            "or name one host's file explicitly.")
    candidates = [os.path.join(path, f) for f in os.listdir(path)
                  if f.endswith(".jsonl")]
    if not candidates:
        raise FileNotFoundError(
            f"no *.jsonl trace in directory {path}")
    return max(candidates, key=os.path.getmtime)


def load_trace_auto(path: str) -> List[dict]:
    """``load_trace`` that understands group runs: a directory holding
    a multi-host ``trace_h*`` family is MERGED onto one fleet timeline
    (observability/merge.py) and validated; anything else resolves to
    a single file exactly like before. The entry point behind ``dpsvm
    report``/``compare``, so a 3-host run dir renders per-host lanes
    instead of silently picking one host's trace."""
    if os.path.isdir(path):
        from dpsvm_tpu.observability import merge as _merge
        family = _merge.discover_family(path)
        if len(family) > 1:
            records = _merge.merge_paths(family)
            errors = validate_trace(records)
            if errors:
                raise ValueError(f"merged trace family {path} is "
                                 "invalid: " + "; ".join(errors))
            return records
    return load_trace(resolve_trace_path(path))


def trace_facts(records: List[dict]) -> dict:
    """The flat per-run metrics dict shared by ``report --json``, the
    bench harnesses' result rows, and ``dpsvm compare``. Robust to a
    partial trace (no summary): facts degrade to the last chunk's view
    so an in-flight or killed run still compares/renders."""
    manifest = records[0] if records else {}
    chunks = [r for r in records if r.get("kind") == "chunk"]
    compiles = [r for r in records if r.get("kind") == "compile"]
    quarantines = [r for r in records if r.get("kind") == "event"
                   and r.get("event") == "quarantine"]
    admits = [r for r in records if r.get("kind") == "event"
              and r.get("event") == "append_admitted"]
    grows = [r for r in records if r.get("kind") == "event"
             and r.get("event") == "ingest_grow"]
    summary = next((r for r in records if r.get("kind") == "summary"),
                   None)
    it0 = int(manifest.get("it0", 0) or 0)
    src = summary or (chunks[-1] if chunks else {})
    n_iter = int(src.get("n_iter", it0) or it0)
    if summary is not None:
        iters = summary["iters"]
        seconds = summary["train_seconds"]
        ips = summary["iters_per_sec"]
        hbm_peak = summary.get("hbm_peak")
        n_compiles = summary.get("n_compiles")
        compile_seconds = summary.get("compile_seconds")
        est_flops = summary.get("est_flops")
        est_bytes = summary.get("est_bytes")
    else:
        iters = n_iter - it0
        seconds = float(src.get("t", 0.0) or 0.0)
        ips = round(iters / seconds, 3) if seconds > 0 else 0.0
        peaks = [c.get("hbm", {}).get("peak") for c in chunks]
        peaks = [p for p in peaks if p is not None]
        hbm_peak = max(peaks) if peaks else None
        n_compiles = len(compiles) or None
        compile_seconds = (round(sum(c.get("seconds", 0.0)
                                     for c in compiles), 6)
                           if compiles else None)
        est_flops = next((c.get("flops") for c in reversed(compiles)
                          if c.get("flops") is not None), None)
        est_bytes = next((c.get("bytes") for c in reversed(compiles)
                          if c.get("bytes") is not None), None)
    hits = int(src.get("cache_hits", 0) or 0)
    misses = int(src.get("cache_misses", 0) or 0)
    lookups = hits + misses
    est_flops_per_sec = (est_flops * iters / seconds
                         if est_flops and seconds and iters > 0 else None)
    # Roofline digest (observability/roofline.py): achieved/peak
    # fractions + the compute-vs-memory-bound verdict against the
    # per-backend peak table; nulls on CPU/unknown hardware.
    from dpsvm_tpu.observability import roofline as _roofline
    env = manifest.get("env") or {}
    phases_d = dict((summary or {}).get("phases")
                    or (chunks[-1].get("phases") if chunks else {})
                    or {})
    roof = _roofline.roofline_facts(
        est_flops=est_flops, est_bytes=est_bytes, iters=iters,
        seconds=seconds, device_kind=env.get("device_kind"),
        phases=phases_d)
    return {
        "solver": manifest.get("solver"),
        "n": manifest.get("n"),
        "d": manifest.get("d"),
        "schema": manifest.get("schema"),
        "converged": (summary or {}).get("converged"),
        "n_iter": n_iter,
        "iters": iters,
        "iters_per_sec": ips,
        "train_seconds": seconds,
        "gap": src.get("gap"),
        "n_sv": src.get("n_sv"),
        "cache_hit_rate": (hits / lookups) if lookups else None,
        "n_compiles": n_compiles,
        "compile_seconds": compile_seconds,
        "hbm_peak": hbm_peak,
        "est_flops": est_flops,
        "est_bytes": est_bytes,
        "est_flops_per_sec": est_flops_per_sec,
        "device_kind": env.get("device_kind"),
        "arith_intensity": roof["arith_intensity"],
        "roofline_fraction": (round(roof["flops_fraction"], 6)
                              if roof["flops_fraction"] is not None
                              else None),
        "roofline_verdict": roof["verdict"],
        "roofline": roof,
        "quarantined_shards": len(quarantines),
        "admitted_shards": len(admits),
        "admitted_rows": sum(int(r.get("rows", 0) or 0)
                             for r in admits),
        "ingest_generation": (int(grows[-1].get("generation", 0) or 0)
                              if grows
                              else (int(admits[-1].get("generation", 0)
                                        or 0) if admits else None)),
        "phases": dict((summary or {}).get("phases")
                       or (chunks[-1].get("phases") if chunks else {})
                       or {}),
        "phase_counts": dict((summary or {}).get("phase_counts")
                             or (chunks[-1].get("phase_counts")
                                 if chunks else {}) or {}),
        "curve": [(c["n_iter"], c["gap"]) for c in chunks],
    }


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list (stdlib-only — the
    report path must not import numpy)."""
    if not sorted_vals:
        return float("nan")
    k = min(int(q / 100.0 * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[k]


def span_attribution(records: List[dict],
                     slowest: int = 5) -> Optional[dict]:
    """Aggregate a serving trace's per-request span trees (schema v3)
    into the latency-attribution digest behind ``dpsvm report``:

    * per-stage stats over the root's direct children — count, mean,
      p50/p95, max, and the share of total sampled wall time;
    * the attribution residual ("unattributed"): root wall minus the
      stage sum, reported as its own row — never silently folded into
      a stage;
    * the slowest-requests view: the top-``slowest`` roots by wall
      time with their full per-stage breakdown, so one bad request's
      time is explained, not just counted.

    None when the trace has no span records (training traces, v1/v2)."""
    spans = [r for r in records if r.get("kind") == "span"]
    if not spans:
        return None
    by_trace: Dict[object, List[dict]] = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    stages: Dict[str, List[float]] = {}
    requests = []
    covered_90 = 0
    for tid, group in by_trace.items():
        root = next((s for s in group if s["parent"] is None), None)
        if root is None:
            continue
        dur = (root["t_end"] - root["t_start"]) * 1000.0
        kids = [s for s in group if s["parent"] == root["span_id"]]
        ksum = 0.0
        breakdown: Dict[str, float] = {}
        for s in kids:
            ms = (s["t_end"] - s["t_start"]) * 1000.0
            ksum += ms
            stages.setdefault(s["name"], []).append(ms)
            breakdown[s["name"]] = round(
                breakdown.get(s["name"], 0.0) + ms, 3)
        resid = max(dur - ksum, 0.0)
        stages.setdefault("(unattributed)", []).append(resid)
        coverage = (ksum / dur) if dur > 0 else 1.0
        if coverage >= 0.9:
            covered_90 += 1
        requests.append({
            "trace_id": tid, "total_ms": round(dur, 3),
            "status": root.get("status"),
            "coverage": round(coverage, 4),
            "unattributed_ms": round(resid, 3),
            "breakdown": breakdown,
        })
    if not requests:
        return None
    total_wall = sum(r["total_ms"] for r in requests) or 1.0
    stage_stats = {}
    for name, vals in stages.items():
        vals = sorted(vals)
        stage_stats[name] = {
            "count": len(vals),
            "mean_ms": round(sum(vals) / len(vals), 3),
            "p50_ms": round(_percentile(vals, 50.0), 3),
            "p95_ms": round(_percentile(vals, 95.0), 3),
            "max_ms": round(vals[-1], 3),
            "share": round(sum(vals) / total_wall, 4),
        }
    requests.sort(key=lambda r: -r["total_ms"])
    return {
        "requests": len(requests),
        "covered_90pct": covered_90,
        "covered_90pct_frac": round(covered_90 / len(requests), 4),
        "stages": stage_stats,
        "slowest": requests[:slowest],
    }


def tenant_attribution(records: List[dict],
                       top: Optional[int] = None) -> Optional[dict]:
    """Aggregate a serving trace's tenant-stamped span trees (schema
    v4 — root spans carry ``tenant``/``model`` extras) into the
    by-tenant cost table behind ``dpsvm report`` and ``dpsvm
    tenants``: per tenant, sampled requests, rows, wall / queue-wait /
    device-compute milliseconds, the tenant's share of total sampled
    wall, latency percentiles, and error/504 counts. ``top`` keeps the
    N most expensive tenants (by wall). None when no root span names a
    tenant (training traces, pre-v4 serving traces)."""
    spans = [r for r in records if r.get("kind") == "span"]
    if not spans:
        return None
    by_trace: Dict[object, List[dict]] = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    acc: Dict[str, dict] = {}
    for tid, group in by_trace.items():
        root = next((s for s in group if s["parent"] is None), None)
        if root is None or root.get("tenant") is None:
            continue
        tenant = str(root["tenant"])
        dur = (root["t_end"] - root["t_start"]) * 1000.0
        a = acc.setdefault(tenant, {
            "requests": 0, "rows": 0, "wall_ms": 0.0,
            "queue_wait_ms": 0.0, "compute_ms": 0.0,
            "errors": 0, "deadline_504": 0,
            "wall": [], "models": set()})
        a["requests"] += 1
        a["rows"] += int(root.get("rows", 0) or 0)
        a["wall_ms"] += dur
        a["wall"].append(dur)
        if root.get("model") is not None:
            a["models"].add(str(root["model"]))
        status = root.get("status")
        if status == 504:
            a["deadline_504"] += 1
        elif status is not None and status != 200:
            a["errors"] += 1
        for s in group:
            if s["parent"] != root["span_id"]:
                continue
            ms = (s["t_end"] - s["t_start"]) * 1000.0
            if s["name"] == "queue_wait":
                a["queue_wait_ms"] += ms
            elif s["name"] == "device_dispatch":
                a["compute_ms"] += ms
    if not acc:
        return None
    total_wall = sum(a["wall_ms"] for a in acc.values()) or 1.0
    rows = []
    for tenant, a in acc.items():
        wall = sorted(a["wall"])
        rows.append({
            "tenant": tenant,
            "requests": a["requests"],
            "rows": a["rows"],
            "wall_ms": round(a["wall_ms"], 3),
            "share": round(a["wall_ms"] / total_wall, 4),
            "queue_wait_ms": round(a["queue_wait_ms"], 3),
            "compute_ms": round(a["compute_ms"], 3),
            "p50_ms": round(_percentile(wall, 50.0), 3),
            "p99_ms": round(_percentile(wall, 99.0), 3),
            "errors": a["errors"],
            "deadline_504": a["deadline_504"],
            "models": sorted(a["models"]),
        })
    rows.sort(key=lambda r: (-r["wall_ms"], r["tenant"]))
    n_total = len(rows)
    if top is not None and top > 0:
        rows = rows[:top]
    return {
        "tenants": n_total,
        "total_wall_ms": round(total_wall, 3),
        "rows": rows,
    }


def host_lanes(records: List[dict]) -> Optional[dict]:
    """Per-host lane digest of a merged fleet trace (schema v5,
    observability/merge.py): iteration progress, phase split and
    straggler attribution per host, plus the group-level recovery
    events. None when no record carries a ``host`` tag (single-host
    traces — every pre-v5 consumer sees no change).

    Straggler attribution: chunk records with the same ``n_iter`` are
    the same group-wide instant (the collectives inside a chunk are a
    barrier), so each host's mean ``t`` excess over the leader at the
    matched iterations IS the time that host held the group — the
    per-host answer to "whose dispatch stalls the collective"."""
    tagged = [r for r in records if isinstance(r.get("host"), int)]
    if not tagged:
        return None
    hosts = sorted({r["host"] for r in tagged})
    # matched-iteration anchors: first chunk t per (n_iter, host)
    anchors: Dict[int, Dict[int, float]] = {}
    lanes: Dict[int, dict] = {
        h: {"host": h, "chunks": 0, "n_iter": 0, "last_t": None,
            "behind_s": None, "iter_lag": 0, "converged": None,
            "train_seconds": None, "phases": {}, "events": []}
        for h in hosts}
    for r in tagged:
        h = r["host"]
        kind = r.get("kind")
        if kind == "chunk":
            lane = lanes[h]
            lane["chunks"] += 1
            lane["n_iter"] = max(lane["n_iter"],
                                 int(r.get("n_iter", 0) or 0))
            lane["last_t"] = r.get("t")
            lane["phases"] = dict(r.get("phases") or lane["phases"])
            by_host = anchors.setdefault(int(r.get("n_iter", 0) or 0),
                                         {})
            t = r.get("t")
            if isinstance(t, (int, float)) and h not in by_host:
                by_host[h] = float(t)
        elif kind == "event":
            ev = r.get("event")
            if ev == "host_summary":
                lanes[h]["converged"] = r.get("converged")
                lanes[h]["train_seconds"] = r.get("train_seconds")
                lanes[h]["n_iter"] = max(lanes[h]["n_iter"],
                                         int(r.get("n_iter", 0) or 0))
            else:
                lanes[h]["events"].append(str(ev))
    # mean time behind the leader over the matched iterations
    behind: Dict[int, List[float]] = {h: [] for h in hosts}
    for _n, by_host in anchors.items():
        if len(by_host) < 2:
            continue
        lead = min(by_host.values())
        for h, t in by_host.items():
            behind[h].append(t - lead)
    for h in hosts:
        if behind[h]:
            lanes[h]["behind_s"] = round(
                sum(behind[h]) / len(behind[h]), 6)
    max_iter = max(lane["n_iter"] for lane in lanes.values())
    for lane in lanes.values():
        lane["iter_lag"] = max_iter - lane["n_iter"]
    # the straggler: the host that held the group, when one stands out
    straggler = None
    scored = [(lane["behind_s"] or 0.0, lane["iter_lag"], h)
              for h, lane in lanes.items()]
    worst = max(scored)
    if worst[0] > 0.005 or worst[1] > 0:
        straggler = worst[2]
    # group-level recovery events, deduplicated across the hosts that
    # each recorded their own copy
    group_events: List[dict] = []
    seen = set()
    for r in records:
        if r.get("kind") != "event" or r.get("event") not in (
                "host_lost", "reform"):
            continue
        key = (r["event"], r.get("host_id"), r.get("from_hosts"),
               r.get("to_hosts"), r.get("n_iter"))
        if key in seen:
            continue
        seen.add(key)
        group_events.append({k: r.get(k) for k in (
            "event", "n_iter", "t", "host_id", "from_hosts",
            "to_hosts")})
    return {
        "hosts": [lanes[h] for h in hosts],
        "straggler": straggler,
        "max_iter": max_iter,
        "group_events": group_events,
    }


def summarize_trace(records: List[dict]) -> dict:
    """The machine-readable digest ``dpsvm report --json`` prints."""
    manifest = records[0] if records else {}
    chunks = [r for r in records if r.get("kind") == "chunk"]
    events = [r for r in records if r.get("kind") == "event"]
    compiles = [r for r in records if r.get("kind") == "compile"]
    summary = next((r for r in records if r.get("kind") == "summary"),
                   None)
    return {
        "manifest": manifest,
        "summary": summary,
        "n_chunks": len(chunks),
        "events": events,
        "compiles": compiles,
        "facts": trace_facts(records),
        "spans": span_attribution(records),
        "tenants": tenant_attribution(records),
        "fleet": host_lanes(records),
        "curve": [{"n_iter": c["n_iter"], "gap": c["gap"],
                   "n_sv": c["n_sv"], "t": c["t"]} for c in chunks],
    }


def _fmt_si(v: float) -> str:
    return f"{v:,.0f}" if v >= 100 else f"{v:.3g}"


def _fmt_bytes(v: Optional[float]) -> str:
    if v is None:
        return "n/a"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return f"{v:,.1f} {unit}" if unit != "B" else f"{v:,.0f} B"
        v /= 1024
    return f"{v:,.1f} TiB"


def _fmt_flops(v: Optional[float]) -> str:
    if v is None:
        return "n/a"
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(v) < 1000 or unit == "P":
            return f"{v:,.2f} {unit}FLOP"
        v /= 1000
    return f"{v:,.2f} PFLOP"


def _gap_curve(chunks: List[dict], width: int = 60,
               height: int = 10) -> List[str]:
    """ASCII iter-vs-gap plot (log-scale gap). Robust down to a single
    chunk record (the acceptance floor: manifest + >= 1 chunk +
    summary)."""
    pts = [(c["n_iter"], c["gap"]) for c in chunks if c["gap"] > 0]
    if not pts:
        return ["  (no open-gap chunk records to plot)"]
    its = [p[0] for p in pts]
    lgs = [math.log10(p[1]) for p in pts]
    it_lo, it_hi = min(its), max(its)
    lg_lo, lg_hi = min(lgs), max(lgs)
    it_span = max(it_hi - it_lo, 1)
    lg_span = max(lg_hi - lg_lo, 1e-9)
    grid = [[" "] * width for _ in range(height)]
    for it, lg in zip(its, lgs):
        col = min(int((it - it_lo) / it_span * (width - 1)), width - 1)
        row = min(int((lg_hi - lg) / lg_span * (height - 1)), height - 1)
        grid[row][col] = "*"
    lines = []
    for r in range(height):
        lg = lg_hi - r * lg_span / (height - 1 or 1)
        label = f"{10 ** lg:8.1e}" if r in (0, height - 1) else " " * 8
        lines.append(f"  {label} |" + "".join(grid[r]))
    lines.append("  " + " " * 8 + "+" + "-" * width)
    left = f"{it_lo:,}"
    right = f"{it_hi:,}"
    pad = max(width - len(left) - len(right), 1)
    lines.append("  " + " " * 9 + left + " " * pad + right)
    return lines


def _phase_bars(phases: Dict[str, float],
                counts: Optional[Dict[str, int]] = None) -> List[str]:
    """Per-phase time bars; with counts, each line carries how many
    times the phase ran — a phase slow because it ran 400x reads very
    differently from one slow call."""
    counts = counts or {}
    total = sum(phases.values())
    if not phases or total <= 0:
        return ["  (no phase timings recorded)"]
    width = max(len(k) for k in phases)
    lines = []
    for name, sec in sorted(phases.items(), key=lambda kv: -kv[1]):
        frac = sec / total
        bar = "#" * max(int(round(frac * 30)), 1 if sec > 0 else 0)
        tail = f"  {counts[name]:,}x" if counts.get(name) else ""
        lines.append(f"  {name:<{width}}  {sec:8.3f} s  {frac:5.1%}  "
                     f"{bar}{tail}")
    return lines


def render_report(records: List[dict], width: int = 60) -> str:
    """The human rendering behind ``dpsvm report``."""
    m = records[0]
    chunks = [r for r in records if r.get("kind") == "chunk"]
    events = [r for r in records if r.get("kind") == "event"]
    compiles = [r for r in records if r.get("kind") == "compile"]
    s = next((r for r in records if r.get("kind") == "summary"), None)
    facts = trace_facts(records)
    k = m["kernel"]
    env = m.get("env") or {}
    out = []
    kern = k["kind"]
    if kern in ("rbf", "poly", "sigmoid"):
        kern += f"(gamma={k['gamma']:g})"
    out.append(f"run: {m['solver']}  {m['n']}x{m['d']}  {kern}  "
               f"shards={m['mesh']['shards']}  "
               f"backend={env.get('backend')} "
               f"{env.get('device_kind') or ''}  "
               f"dpsvm_tpu {m['version']}")
    if s is not None:
        status = "converged" if s["converged"] else "NOT converged"
        out.append(f"result: {status} at iter {s['n_iter']:,} in "
                   f"{s['train_seconds']:.2f} s "
                   f"({_fmt_si(s['iters_per_sec'])} it/s)   "
                   f"gap {s['gap']:.3g}  b={s['b']:.6g}  "
                   f"n_sv={s['n_sv']:,}")
    else:
        out.append("result: (no summary record — run still in flight "
                   "or killed)")
    fleet = host_lanes(records)
    if fleet is not None:
        out.append("")
        out.append(f"fleet: {len(fleet['hosts'])} host lane(s) merged "
                   "— docs/OBSERVABILITY.md \"Fleet\"")
        out.append(f"  {'host':>4}  {'chunks':>6} {'iter':>9} "
                   f"{'lag':>6} {'behind':>9}  {'done':>5}  phases")
        for lane in fleet["hosts"]:
            behind = (f"{lane['behind_s']:+.3f}s"
                      if lane["behind_s"] is not None else "n/a")
            done = ("yes" if lane["converged"]
                    else "NO" if lane["converged"] is not None
                    else "?")
            ph = " ".join(
                f"{k}={v:.2f}s" for k, v in sorted(
                    (lane["phases"] or {}).items(),
                    key=lambda kv: -kv[1])[:3])
            mark = (" <- straggler"
                    if lane["host"] == fleet["straggler"] else "")
            out.append(f"  {lane['host']:>4}  {lane['chunks']:>6,} "
                       f"{lane['n_iter']:>9,} {lane['iter_lag']:>6,} "
                       f"{behind:>9}  {done:>5}  {ph}{mark}")
        if fleet["straggler"] is not None:
            lane = next(x for x in fleet["hosts"]
                        if x["host"] == fleet["straggler"])
            why = []
            if lane["behind_s"]:
                why.append(f"avg {lane['behind_s']:.3f}s behind the "
                           "leader at matched iterations")
            if lane["iter_lag"]:
                why.append(f"{lane['iter_lag']:,} iterations behind "
                           "the fastest host")
            out.append(f"  straggler: host {fleet['straggler']} "
                       f"({'; '.join(why) or 'slowest lane'})")
        for ge in fleet["group_events"]:
            if ge["event"] == "host_lost":
                out.append(f"  group: host_lost(host "
                           f"{ge.get('host_id')})@"
                           f"{ge.get('n_iter', 0):,}")
            else:
                out.append(f"  group: reform {ge.get('from_hosts')}->"
                           f"{ge.get('to_hosts')} hosts@"
                           f"{ge.get('n_iter', 0):,}")
    # Device/compiler layer (schema v2; silent on v1 traces, which
    # carry none of these facts). A v2 trace whose backend reports no
    # allocator stats / cost model (CPU) renders an explicit `n/a` —
    # never the Python literal `None`, and never a silently absent
    # line a reader could mistake for a v1 trace.
    v2 = (m.get("schema") or 1) >= 2
    if facts.get("n_compiles"):
        comp_s = facts.get("compile_seconds") or 0.0
        denom = facts.get("train_seconds") or 0.0
        share = (f" ({comp_s / denom:.0%} of train time)"
                 if denom > 0 else "")
        out.append(f"compiles: {facts['n_compiles']} program(s) in "
                   f"{comp_s:.2f} s{share}")
    if facts.get("hbm_peak") is not None:
        limit = None
        for c in chunks:
            limit = (c.get("hbm") or {}).get("limit") or limit
        head = (f"  ({facts['hbm_peak'] / limit:.0%} of "
                f"{_fmt_bytes(limit)} limit)" if limit else "")
        out.append(f"hbm peak: {_fmt_bytes(facts['hbm_peak'])}{head}")
    elif v2:
        out.append("hbm peak: n/a (no allocator stats on this backend)")
    if facts.get("est_flops") is None:
        if v2:
            out.append("throughput: n/a (no cost-model FLOP estimate "
                       "recorded)")
    elif facts.get("est_flops_per_sec") is not None:
        out.append(f"throughput: ~{_fmt_flops(facts['est_flops_per_sec'])}"
                   f"/s achieved (cost-model: "
                   f"{_fmt_flops(facts['est_flops'])}/iter x "
                   f"{facts['iters']:,} iters)")
    else:
        # est_flops recorded but no measurable window (0 iters or 0 s):
        # keep the cost model, suppress the achieved-FLOP/s claim.
        out.append(f"throughput: n/a (cost-model: "
                   f"{_fmt_flops(facts['est_flops'])}/iter; no "
                   "measured window to divide by)")
    # Roofline block (schema v3, observability/roofline.py): achieved
    # vs peak + the compute/memory-bound verdict per phase. Rendered
    # when the trace carries a cost model or the hardware is in the
    # peak table; a v3 CPU trace gets the explicit n/a line.
    v3 = (m.get("schema") or 1) >= 3
    if v3 and facts.get("device_kind") is not None and (
            facts.get("est_flops") is not None
            or (facts.get("roofline") or {}).get("peaks") is not None):
        from dpsvm_tpu.observability import roofline as _roofline
        out.extend(_roofline.render_roofline(facts["roofline"]))
    out.append("")
    out.append("convergence (gap vs iteration, log scale):")
    out.extend(_gap_curve(chunks, width=width))
    out.append("")
    phases = (s or {}).get("phases") or (
        chunks[-1]["phases"] if chunks else {})
    counts = ((s or {}).get("phase_counts")
              or (chunks[-1].get("phase_counts") if chunks else {}))
    out.append("host-loop phase time:")
    out.extend(_phase_bars(phases, counts))
    out.append("")
    src = s or (chunks[-1] if chunks else None)
    if src is not None:
        lookups = src["cache_hits"] + src["cache_misses"]
        if lookups:
            out.append(f"kernel-row cache: {lookups:,} lookups, hit rate "
                       f"{src['cache_hits'] / lookups:.1%} "
                       f"({src['cache_hits']:,} hits / "
                       f"{src['cache_misses']:,} misses)")
        else:
            out.append("kernel-row cache: off (cache_size=0)")
        if src.get("rounds"):
            out.append(f"decomposition outer rounds: {src['rounds']:,}")
    if compiles:
        out.append("compile events: " + ", ".join(
            f"{c['program']}@{c['seconds']:.2f}s" for c in compiles))
    screens = [e for e in events if e.get("event") == "screen"]
    if screens:
        # Cascade stage split (solver/cascade.py): the LAST screen
        # event carries the final subproblem size; polish/readmit
        # events carry the repair history.
        sc = screens[-1]
        polishes = [e for e in events if e.get("event") == "polish"]
        readmits = [e for e in events if e.get("event") == "readmit"]
        readmitted = sum(int(e.get("n_readmitted", 0) or 0)
                         for e in readmits)
        out.append(f"cascade: screened {sc.get('n_total', 0):,} -> "
                   f"{sc.get('n_kept', 0):,} rows; "
                   f"{len(polishes)} polish round(s), "
                   f"{readmitted:,} re-admitted — see docs/APPROX.md "
                   "\"Cascade\"")
    admits = [e for e in events if e.get("event") == "append_admitted"]
    if admits:
        rows = sum(int(e.get("rows", 0) or 0) for e in admits)
        last_gen = admits[-1].get("generation")
        out.append(f"admitted shards: {len(admits)} live append(s) "
                   f"({rows:,} rows; log generation {last_gen}) — "
                   "see docs/DATA.md \"Live shard logs\"")
    quarantines = [e for e in events if e.get("event") == "quarantine"]
    if quarantines:
        rows = sum(int(e.get("rows", 0) or 0) for e in quarantines)
        shards_q = ", ".join(str(e.get("shard")) for e in quarantines)
        out.append(f"quarantined shards: {len(quarantines)} "
                   f"({rows:,} rows dropped; shard {shards_q}) — "
                   "see docs/DATA.md")
    shown_events = [e for e in events
                    if fleet is None
                    or e.get("event") not in ("host_summary",
                                              "host_lost", "reform")]
    if shown_events:
        out.append("events: " + ", ".join(
            f"{e['event']}@{e['n_iter']:,}" for e in shown_events))
    spans = span_attribution(records)
    if spans is not None:
        out.append("")
        out.append(f"request latency attribution "
                   f"({spans['requests']} sampled request(s), "
                   f"{spans['covered_90pct_frac']:.0%} with >= 90% of "
                   "wall attributed):")
        w = max(len(n) for n in spans["stages"])
        out.append(f"  {'stage':<{w}}  {'count':>6} {'mean ms':>9} "
                   f"{'p50 ms':>9} {'p95 ms':>9} {'max ms':>9} "
                   f"{'share':>6}")
        order = sorted(spans["stages"].items(),
                       key=lambda kv: -kv[1]["share"])
        for name, st in order:
            out.append(f"  {name:<{w}}  {st['count']:>6,} "
                       f"{st['mean_ms']:>9,.3f} {st['p50_ms']:>9,.3f} "
                       f"{st['p95_ms']:>9,.3f} {st['max_ms']:>9,.3f} "
                       f"{st['share']:>6.1%}")
        out.append("slowest requests (wall; per-stage ms):")
        for r in spans["slowest"]:
            parts = " | ".join(
                f"{k} {v:,.3f}" for k, v in sorted(
                    r["breakdown"].items(), key=lambda kv: -kv[1]))
            status = (f" [{r['status']}]" if r.get("status") is not None
                      else "")
            out.append(f"  {r['trace_id']}: {r['total_ms']:,.3f} ms"
                       f"{status}  {parts} | unattributed "
                       f"{r['unattributed_ms']:,.3f}")
    tenants = tenant_attribution(records)
    if tenants is not None:
        out.append("")
        out.append(f"per-tenant cost attribution "
                   f"({tenants['tenants']} tenant(s), "
                   f"{tenants['total_wall_ms']:,.1f} ms sampled wall "
                   "— docs/OBSERVABILITY.md \"Per-tenant "
                   "attribution\"):")
        out.extend(render_tenant_table(tenants["rows"]))
    out.append(f"chunk polls recorded: {len(chunks)}")
    return "\n".join(out)


def render_tenant_table(rows: List[dict]) -> List[str]:
    """The by-tenant cost table (one row shape — tenant_attribution
    for traces, ``dpsvm tenants --url`` normalizes /metricsz into the
    same dicts), indented for embedding in the report."""
    if not rows:
        return ["  (no tenant-attributed requests)"]
    w = max(max(len(r["tenant"]) for r in rows), len("tenant"))
    out = [f"  {'tenant':<{w}}  {'reqs':>6} {'rows':>7} "
           f"{'wall ms':>10} {'share':>6} {'queue ms':>9} "
           f"{'compute ms':>10} {'p99 ms':>8} {'err':>4} {'504':>4}"]
    for r in rows:
        p99 = r.get("p99_ms")
        out.append(
            f"  {r['tenant']:<{w}}  {r['requests']:>6,} "
            f"{r['rows']:>7,} {r['wall_ms']:>10,.1f} "
            f"{r['share']:>6.1%} {r['queue_wait_ms']:>9,.1f} "
            f"{r['compute_ms']:>10,.1f} "
            + (f"{p99:>8,.2f}" if p99 is not None else f"{'-':>8}")
            + f" {r['errors']:>4,} {r['deadline_504']:>4,}")
    return out


def _is_terminal(records: List[dict]) -> Optional[str]:
    """'summary' when the run finished, the terminal event name when it
    died visibly (stall/preempt), None while in flight."""
    for r in reversed(records):
        kind = r.get("kind")
        if kind == "summary":
            return "summary"
        if kind == "event" and r.get("event") in TERMINAL_EVENTS:
            return r["event"]
    return None


def follow_trace(path: str, *, interval: float = 1.0,
                 stall_timeout: float = 120.0, width: int = 60,
                 out=None,
                 render: Optional[Callable[[List[dict]], str]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep) -> int:
    """Tail an in-flight JSONL trace and re-render the report until a
    terminal record lands — `dpsvm report --follow`, the watchable
    version of a tunneled chip run.

    Returns 0 when the run finished (summary record), 1 when it died
    visibly (stall/preempt terminal event), 3 when the file stopped
    growing for ``stall_timeout`` seconds (a run killed too hard to
    stamp its own terminal event — e.g. SIGKILL). A not-yet-created
    file counts as not-growing, so following a path before the run
    starts works and still times out if it never does.

    Reads use the torn-line-tolerant reader (the writer flushes per
    record, so a partial final line only means "mid-write")."""
    out = out if out is not None else sys.stdout
    render = render or (lambda recs: render_report(recs, width=width))
    is_tty = getattr(out, "isatty", lambda: False)()
    last_size = -1
    last_grew = clock()
    shown = 0
    while True:
        try:
            size = os.path.getsize(path)
        except OSError:
            size = -1
        if size != last_size:
            last_size = size
            last_grew = clock()
            records = []
            if size > 0:
                try:
                    records = read_trace(path)
                except ValueError:
                    records = []        # interleaved writer mid-line
            if records and records[0].get("kind") == "manifest":
                text = render(records)
                if is_tty:
                    out.write("\x1b[2J\x1b[H" + text + "\n")
                else:
                    if shown:
                        out.write("\n" + "=" * 8 + " refresh " +
                                  "=" * 8 + "\n")
                    out.write(text + "\n")
                out.flush()
                shown += 1
                terminal = _is_terminal(records)
                if terminal == "summary":
                    return 0
                if terminal is not None:
                    out.write(f"run ended: {terminal}\n")
                    out.flush()
                    return 1
        if clock() - last_grew > stall_timeout:
            out.write(f"trace stalled: no growth in {stall_timeout:g} s "
                      f"({path})\n")
            out.flush()
            return 3
        sleep(interval)

"""Persistent perf ledger + historical regression gate
(docs/OBSERVABILITY.md "Perf ledger").

``dpsvm compare`` is strictly pairwise: every PR can pass its A/B gate
while a 2%-per-PR drift accumulates invisibly ("Recipe for Fast
Large-scale SVM Training", arXiv:2207.01016, is the worked example of
why perf trajectories need bookkeeping, not snapshots). The ledger is
the fix: one append-only JSONL file that every measurement producer
writes a schema-versioned record into —

* ``bench.py`` / ``bench_convergence.py`` rows (kind ``bench``),
* every ``benchmarks/burst_runner.py`` row (kind ``burst``), so the
  gate has data from the first window,
* ``dpsvm loadgen`` rows incl. the ``--saturate`` SLO row (kind
  ``loadgen``),
* ``dpsvm compare --fail-on-regress`` verdicts (kind ``compare``).

Each record carries the run identity (git sha, backend, case tag), the
measurement (``value``/``unit`` + the full metrics dict) and a
``trace`` pointer at its provenance trace when one was archived.

``dpsvm perf`` renders per-case history; ``dpsvm perf gate --window N
--fail-on-regress PCT`` applies the historical check: the newest
record against the **median of the previous N** records,
direction-aware like ``compare`` (an it/s drop and a seconds growth
are both regressions) — so drift that accumulated across several
individually-passing PRs still fails CI.

Path resolution: ``DPSVM_PERF_LEDGER`` env (empty string = disabled),
else ``benchmarks/results/perf_ledger.jsonl`` under the repo root.
Appends are best-effort by default (a full disk must not kill a bench
run); readers tolerate a torn final line like the trace reader.

Dependency-free (stdlib only): `dpsvm perf` must run on a machine with
no accelerator, like report/compare.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import time
from typing import Dict, List, Optional, Sequence

LEDGER_ENV = "DPSVM_PERF_LEDGER"
LEDGER_SCHEMA = 1

#: record kinds the documented producers write (free strings otherwise;
#: this is the vocabulary, like record.SERVING_EVENTS). "tune" rows
#: come from `dpsvm tune` (tuning/tuner.py): per-knob probe readings
#: plus the tuned_vs_default A/B verdict. "robust" rows come from the
#: resilience drills (resilience/hostgroup.host_loss_drill):
#: recovery latencies, gated direction "lower" like any latency.
#: "fleet" rows come from the model-fleet subsystem (dpsvm_tpu/fleet):
#: the fleet_cache_drill's cold-start p99 and `dpsvm grid`'s
#: grid_vs_sequential speedup, both trace-pointed (docs/PERF.md).
KINDS = ("bench", "burst", "loadgen", "compare", "tune", "serve",
         "robust", "fleet")

#: unit -> gate direction ("higher" = bigger is better). The per-record
#: ``direction`` field wins; the metric-name heuristics below back this
#: up for rows without a unit.
DIRECTION_BY_UNIT = {
    "iter/s": "higher", "ex/s": "higher", "req/s": "higher",
    "x": "higher", "rows/s": "higher",
    "s": "lower", "ms": "lower", "bytes": "lower",
}

_LOWER_HINTS = ("seconds", "_ms", "_s", "latency", "hbm", "bytes",
                "compile")
_HIGHER_HINTS = ("per_sec", "per_s", "speedup", "rps", "throughput",
                 "accuracy", "availability", "iters", "roofline",
                 "fraction")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_ledger_path() -> str:
    return os.path.join(repo_root(), "benchmarks", "results",
                        "perf_ledger.jsonl")


def ledger_path(explicit: Optional[str] = None) -> Optional[str]:
    """Resolve the ledger file: explicit argument, else the env var
    (EMPTY env value = ledger disabled -> None), else the in-repo
    default."""
    if explicit:
        return explicit
    env = os.environ.get(LEDGER_ENV)
    if env is not None:
        return env or None
    return default_ledger_path()


_GIT_SHA: Optional[str] = None


def git_sha() -> Optional[str]:
    """Current repo sha (cached; env DPSVM_GIT_SHA overrides — CI
    images without a .git dir still get provenance)."""
    global _GIT_SHA
    if _GIT_SHA is not None:
        return _GIT_SHA or None
    env = os.environ.get("DPSVM_GIT_SHA", "").strip()
    if env:
        _GIT_SHA = env
        return env
    try:
        out = subprocess.run(
            ["git", "-C", repo_root(), "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10)
        _GIT_SHA = out.stdout.strip() if out.returncode == 0 else ""
    except Exception:
        _GIT_SHA = ""
    return _GIT_SHA or None


def backend_hint() -> Optional[str]:
    """Best-effort backend tag WITHOUT initializing jax: an already-up
    backend is read from jax's module state, else the platform env
    vars. None when nothing is known — never forces a device probe."""
    import sys
    jx = sys.modules.get("jax")
    if jx is not None:
        try:
            return jx.devices()[0].platform       # already initialized
        except Exception:
            pass
    for var in ("DPSVM_PLATFORM", "JAX_PLATFORMS"):
        v = os.environ.get(var, "").strip()
        if v:
            return v.split(",")[0]
    return None


def direction_for(record: dict) -> str:
    """Gate direction for a record: explicit field, unit table, then
    metric-name heuristics; 'higher' when truly unknown (a throughput
    bias — the common case here)."""
    d = record.get("direction")
    if d in ("higher", "lower"):
        return d
    unit = record.get("unit")
    if unit in DIRECTION_BY_UNIT:
        return DIRECTION_BY_UNIT[unit]
    name = str(record.get("case", "")) + " " + str(
        (record.get("metrics") or {}).get("metric", ""))
    low = name.lower()
    if any(h in low for h in _LOWER_HINTS):
        return "lower"
    if any(h in low for h in _HIGHER_HINTS):
        return "higher"
    return "higher"


def host_count_hint() -> int:
    """The provenance host count: ``DPSVM_HOST_COUNT`` (set by the
    hostgroup supervisor for its children) when parseable, else 1 —
    the single-process default every pre-fleet row implicitly had."""
    raw = os.environ.get("DPSVM_HOST_COUNT", "").strip()
    try:
        n = int(raw)
        return n if n >= 1 else 1
    except ValueError:
        return 1


def make_record(case: str, metrics: Optional[dict] = None, *,
                kind: str = "bench", value: Optional[float] = None,
                unit: Optional[str] = None,
                direction: Optional[str] = None,
                trace: Optional[str] = None,
                backend: Optional[str] = None,
                host_count: Optional[int] = None) -> dict:
    metrics = dict(metrics or {})
    if value is None:
        v = metrics.get("value")
        value = float(v) if isinstance(v, (int, float)) else None
    return {
        "schema": LEDGER_SCHEMA,
        "kind": str(kind),
        "case": str(case),
        "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "git_sha": git_sha(),
        "backend": backend if backend is not None else backend_hint(),
        # multi-host provenance: a 3-host row must never gate against
        # single-host history (docs/OBSERVABILITY.md "Fleet")
        "host_count": (int(host_count) if host_count is not None
                       else host_count_hint()),
        "value": value,
        "unit": unit if unit is not None else metrics.get("unit"),
        "direction": direction,
        "metrics": metrics,
        "trace": trace,
    }


def append(case: str, metrics: Optional[dict] = None, *,
           kind: str = "bench", value: Optional[float] = None,
           unit: Optional[str] = None, direction: Optional[str] = None,
           trace: Optional[str] = None, backend: Optional[str] = None,
           host_count: Optional[int] = None,
           path: Optional[str] = None,
           strict: bool = False) -> Optional[str]:
    """Append one record; returns the ledger path written (None when
    the ledger is disabled or, in non-strict mode, the write failed —
    provenance hiccups must not burn a measured row)."""
    resolved = ledger_path(path)
    if resolved is None:
        return None
    rec = make_record(case, metrics, kind=kind, value=value, unit=unit,
                      direction=direction, trace=trace, backend=backend,
                      host_count=host_count)
    try:
        parent = os.path.dirname(os.path.abspath(resolved))
        os.makedirs(parent, exist_ok=True)
        with open(resolved, "a") as fh:
            fh.write(json.dumps(rec) + "\n")
            fh.flush()
        return resolved
    except OSError:
        if strict:
            raise
        return None


def read(path: str) -> List[dict]:
    """Every intact record, in append order. A torn FINAL line (a
    producer killed mid-write) is dropped, matching the trace reader;
    a torn interior line raises — that is corruption, not a race."""
    records: List[dict] = []
    with open(path) as fh:
        lines = fh.read().splitlines()
    for i, raw in enumerate(lines):
        raw = raw.strip()
        if not raw:
            continue
        try:
            rec = json.loads(raw)
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                break
            raise ValueError(f"{path}:{i + 1}: not a JSON record")
        if isinstance(rec, dict):
            records.append(rec)
    return records


def cases(records: Sequence[dict]) -> List[str]:
    seen: Dict[str, None] = {}
    for r in records:
        c = r.get("case")
        if c:
            seen.setdefault(str(c), None)
    return list(seen)


def series(records: Sequence[dict], case: str,
           metric: str = "value") -> List[dict]:
    """The case's measurement history, append order: records with a
    finite numeric reading of ``metric`` (top-level ``value`` or a key
    of the metrics dict)."""
    out = []
    for r in records:
        if str(r.get("case")) != str(case):
            continue
        v = (r.get("value") if metric == "value"
             else (r.get("metrics") or {}).get(metric))
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        if v != v or v in (float("inf"), float("-inf")):
            continue
        out.append({"value": float(v), "time": r.get("time"),
                    "git_sha": r.get("git_sha"),
                    "backend": r.get("backend"),
                    "unit": r.get("unit"), "record": r})
    return out


def gate(records: Sequence[dict], *, window: int = 5,
         threshold_pct: float = 10.0, case: Optional[str] = None,
         metric: str = "value") -> List[str]:
    """Historical regression verdicts (empty = gate passes).

    Per case: newest value vs the MEDIAN of the up-to-``window``
    records before it — the robust baseline a slow multi-PR drift
    cannot drag along with it (each pairwise step passes, but the
    newest-vs-median delta keeps growing until it trips). Direction
    comes from the newest record (``direction``/``unit``/name
    heuristics). Cases with fewer than 2 readings have no history to
    gate and are skipped.

    Provenance filter: only rows whose ``host_count`` matches the
    newest record's (absent = 1, the pre-fleet default) count as
    history — a 3-host reading regressing against single-host medians
    (or propping them up) would be a category error, not a trend
    (docs/OBSERVABILITY.md "Fleet").
    """
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    targets = [case] if case else cases(records)
    verdicts = []

    def _hc(rec: dict) -> int:
        v = rec.get("host_count")
        return int(v) if isinstance(v, int) and not isinstance(
            v, bool) and v >= 1 else 1

    for c in targets:
        hist = series(records, c, metric=metric)
        if len(hist) < 2:
            continue
        want_hc = _hc(hist[-1]["record"])
        hist = [h for h in hist if _hc(h["record"]) == want_hc]
        if len(hist) < 2:
            continue
        newest = hist[-1]
        base_vals = [h["value"] for h in hist[-(window + 1):-1]]
        base = statistics.median(base_vals)
        direction = direction_for(newest["record"])
        v = newest["value"]
        if base == 0:
            continue
        delta_pct = (v - base) / abs(base) * 100.0
        bad = (delta_pct < -threshold_pct if direction == "higher"
               else delta_pct > threshold_pct)
        if bad:
            what = ("dropped" if direction == "higher" else "grew")
            unit = newest.get("unit") or ""
            verdicts.append(
                f"{c}: {metric} {what} {abs(delta_pct):.1f}% vs "
                f"median of last {len(base_vals)} "
                f"({base:g} -> {v:g}{' ' + unit if unit else ''}, "
                f"threshold {threshold_pct:g}%, direction {direction})")
    return verdicts


# ---------------------------------------------------------------------
# `dpsvm perf` rendering
# ---------------------------------------------------------------------

def _trend_bar(v: float, lo: float, hi: float, width: int = 28) -> str:
    if hi <= lo:
        return "#" * (width // 2)
    frac = (v - lo) / (hi - lo)
    return "#" * max(1, int(round(frac * width)))


def render_history(records: Sequence[dict], *,
                   case: Optional[str] = None, metric: str = "value",
                   last: int = 12, width: int = 28) -> str:
    """Per-case ASCII trend (the `report` gap-curve idiom applied to
    history): one bar per recorded run, newest last, so the drift
    `compare` cannot see is visible at a glance."""
    targets = [case] if case else cases(records)
    out = []
    for c in targets:
        hist = series(records, c, metric=metric)
        if not hist:
            out.append(f"{c}: no numeric {metric!r} readings")
            continue
        shown = hist[-last:]
        vals = [h["value"] for h in shown]
        lo, hi = min(vals), max(vals)
        unit = next((h["unit"] for h in reversed(shown)
                     if h.get("unit")), "")
        direction = direction_for(shown[-1]["record"])
        out.append(f"{c}  [{metric}{', ' + unit if unit else ''}; "
                   f"{len(hist)} run(s), direction {direction}]")
        for h in shown:
            sha = (h.get("git_sha") or "-------")[:7]
            t = (h.get("time") or "")[:16]
            out.append(f"  {t:<16} {sha:<7} {h['value']:>12,.4g}  "
                       f"{_trend_bar(h['value'], lo, hi, width)}")
        if len(hist) > len(shown):
            out.append(f"  ({len(hist) - len(shown)} older run(s) "
                       "not shown)")
        out.append("")
    return "\n".join(out).rstrip()

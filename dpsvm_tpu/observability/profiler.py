"""First-class ``jax.profiler`` integration (docs/OBSERVABILITY.md
"Profiling").

``train --profile-dir DIR`` used to wrap the WHOLE training loop in
``jax.profiler.trace`` — on a real run that artifact is dominated by
the first chunk's XLA compile and grows with run length, so two runs'
profiles were never comparable. ``ProfileSession`` fixes both:

* **Auto-windowed capture** — the device trace starts at a poll
  boundary after ``skip_polls`` polls (the first chunk's compile has
  already happened by the time the first poll returns, so even
  ``skip_polls=0`` excludes it) and stops after ``capture_polls``
  steady-state polls. The artifact is small and shaped the same for
  every run of the same config. Env overrides:
  ``DPSVM_PROFILE_SKIP_POLLS`` / ``DPSVM_PROFILE_POLLS``.
* **Phase annotations** — the driver's ``PhaseTimer`` buckets
  (``dispatch`` / ``poll`` / ``checkpoint`` / ``hook``) and the poll
  boundaries are wrapped in ``jax.profiler.TraceAnnotation`` spans
  named exactly after the phases, so the XLA timeline carries the same
  vocabulary as the run trace's ``phase_counts``.
* **Reconciliation sidecar** — ``close()`` writes
  ``profile_summary.json`` next to the device artifact: the host-side
  phase seconds/call-counts *inside the captured window*, the window
  bounds, and the device artifact inventory. ``dpsvm profile
  summarize DIR`` renders it (optionally against a run trace) so
  XLA-level time and host-level accounting line up in one table —
  without needing TensorBoard to open the xplane protobuf.

jax is imported lazily and every profiler call is wrapped: a backend
whose profiler is unavailable degrades to the sidecar-only summary
instead of killing the run.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Dict, List, Optional

SUMMARY_FILE = "profile_summary.json"
SUMMARY_SCHEMA = 1

#: device-trace artifact suffixes jax's profiler writes under
#: <dir>/plugins/profile/<run>/
ARTIFACT_SUFFIXES = (".xplane.pb", ".trace.json.gz", ".json.gz",
                     ".pb", ".json")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


def find_artifacts(log_dir: str) -> List[dict]:
    """Inventory of device-trace files under ``log_dir`` (relative
    path + size), newest first."""
    out = []
    for root, _dirs, files in os.walk(log_dir):
        for f in files:
            if f == SUMMARY_FILE:
                continue
            if any(f.endswith(s) for s in ARTIFACT_SUFFIXES):
                p = os.path.join(root, f)
                try:
                    out.append({
                        "path": os.path.relpath(p, log_dir),
                        "bytes": os.path.getsize(p),
                        "mtime": os.path.getmtime(p),
                    })
                except OSError:
                    pass
    out.sort(key=lambda a: -a["mtime"])
    for a in out:
        a.pop("mtime", None)
    return out


class ProfileSession:
    """One training run's programmatic profiler window.

    Lifecycle (driven by the shared host driver):

    * construction — decides the window; nothing starts yet;
    * ``annotation(name)`` — the PhaseTimer hook: returns a
      ``TraceAnnotation(name)`` context manager (a no-op outside an
      active trace; the names are recorded either way so the sidecar
      knows the annotation vocabulary);
    * ``note_poll()`` — called once per host poll; starts the device
      trace when the skip window ends, stops it when the capture
      window is full;
    * ``close()`` — stops a still-open trace (run ended early) and
      writes the ``profile_summary.json`` sidecar.
    """

    def __init__(self, log_dir: str, *, skip_polls: Optional[int] = None,
                 capture_polls: Optional[int] = None,
                 solver: str = "unknown"):
        self.log_dir = log_dir
        self.skip_polls = max(_env_int("DPSVM_PROFILE_SKIP_POLLS", 0)
                              if skip_polls is None else int(skip_polls),
                              0)
        self.capture_polls = max(_env_int("DPSVM_PROFILE_POLLS", 4)
                                 if capture_polls is None
                                 else int(capture_polls), 1)
        self.solver = solver
        self._timer = None
        self._polls = 0
        self._active = False
        self._closed = False
        self._started_at_poll: Optional[int] = None
        self._stopped_at_poll: Optional[int] = None
        self._t_start: Optional[float] = None
        self._window_seconds = 0.0
        self._phases_seen: List[str] = []
        self._snap_seconds: Dict[str, float] = {}
        self._snap_counts: Dict[str, int] = {}
        self._win_seconds: Dict[str, float] = {}
        self._win_counts: Dict[str, int] = {}
        self._error: Optional[str] = None
        os.makedirs(log_dir, exist_ok=True)

    # -- PhaseTimer hook ----------------------------------------------

    def attach_timer(self, timer) -> None:
        """The PhaseTimer whose buckets bound the captured window (the
        driver's); snapshotted at trace start/stop so the sidecar
        reports window-local phase time, not whole-run time."""
        self._timer = timer

    def annotation(self, name: str):
        if name not in self._phases_seen:
            self._phases_seen.append(name)
        try:
            import jax
            return jax.profiler.TraceAnnotation(str(name))
        except Exception:
            return contextlib.nullcontext()

    # -- window management --------------------------------------------

    def _snapshot_timer(self) -> None:
        if self._timer is not None:
            self._snap_seconds = dict(self._timer.seconds)
            self._snap_counts = dict(self._timer.counts)

    def _window_delta(self) -> None:
        if self._timer is None:
            return
        self._win_seconds = {
            k: round(v - self._snap_seconds.get(k, 0.0), 6)
            for k, v in self._timer.seconds.items()}
        self._win_counts = {
            k: v - self._snap_counts.get(k, 0)
            for k, v in self._timer.counts.items()}

    def _start(self) -> None:
        self._snapshot_timer()
        self._t_start = time.perf_counter()
        self._started_at_poll = self._polls
        try:
            import jax
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        except Exception as e:     # profiler unavailable: sidecar only
            self._error = f"start_trace failed: {e}"

    def _stop(self) -> None:
        self._stopped_at_poll = self._polls
        if self._t_start is not None:
            self._window_seconds = round(
                time.perf_counter() - self._t_start, 6)
        self._window_delta()
        if self._active:
            self._active = False
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as e:
                self._error = f"stop_trace failed: {e}"

    def note_poll(self) -> None:
        """One host poll boundary passed. With an annotation so the
        poll cadence is visible on the device timeline too."""
        if self._closed or self._stopped_at_poll is not None:
            return
        if (self._started_at_poll is None
                and self._polls >= self.skip_polls):
            self._start()
        elif (self._started_at_poll is not None
              and self._polls - self._started_at_poll
              >= self.capture_polls):
            self._stop()
        self._polls += 1

    def close(self, extra: Optional[dict] = None) -> Optional[str]:
        """Stop a still-open window, write the sidecar, return its
        path. Idempotent; never raises (profiling must not take the
        run down)."""
        if self._closed:
            return None
        self._closed = True
        try:
            if (self._started_at_poll is not None
                    and self._stopped_at_poll is None):
                self._stop()
            summary = {
                "schema": SUMMARY_SCHEMA,
                "solver": self.solver,
                "skip_polls": self.skip_polls,
                "capture_polls": self.capture_polls,
                "polls_seen": self._polls,
                "window": {
                    "started_at_poll": self._started_at_poll,
                    "stopped_at_poll": self._stopped_at_poll,
                    "seconds": self._window_seconds,
                },
                "phases": {
                    name: {"seconds": self._win_seconds.get(name, 0.0),
                           "calls": self._win_counts.get(name, 0)}
                    for name in sorted(set(self._phases_seen)
                                       | set(self._win_seconds))},
                "annotations": list(self._phases_seen),
                "artifacts": find_artifacts(self.log_dir),
                "error": self._error,
                "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            }
            if extra:
                summary.update(extra)
            path = os.path.join(self.log_dir, SUMMARY_FILE)
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(summary, fh, indent=1)
            os.replace(tmp, path)
            return path
        except Exception:
            return None


# ---------------------------------------------------------------------
# `dpsvm profile summarize`
# ---------------------------------------------------------------------

def load_summary(profile_dir: str) -> dict:
    """Read the reconciliation sidecar; FileNotFoundError names the
    expected file if the dir holds only raw device artifacts."""
    path = os.path.join(profile_dir, SUMMARY_FILE)
    if not os.path.isfile(path):
        raise FileNotFoundError(
            f"{path} (was this profile captured by `train "
            "--profile-dir`? raw jax.profiler dirs carry no summary)")
    with open(path) as fh:
        summary = json.load(fh)
    # re-walk: artifacts may have landed after close() on some backends
    summary["artifacts"] = find_artifacts(profile_dir)
    return summary


def _fmt_bytes(v: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(v) < 1024 or unit == "GiB":
            return (f"{v:,.1f} {unit}" if unit != "B"
                    else f"{v:,.0f} B")
        v /= 1024
    return f"{v:,.1f} GiB"


def render_summary(summary: dict,
                   trace_phase_counts: Optional[Dict[str, int]] = None
                   ) -> str:
    """The one reconciliation table: per phase, the host wall seconds
    and call count inside the captured window, next to the run
    trace's own ``phase_counts`` when a trace is supplied — the
    host-level accounting and the device timeline share the phase
    vocabulary by construction (the annotations ARE the phase names),
    so this is where they line up."""
    w = summary.get("window") or {}
    out = [f"profile: {summary.get('solver', 'unknown')}  "
           f"window {w.get('seconds', 0.0):.3f} s  "
           f"(polls {w.get('started_at_poll')}.."
           f"{w.get('stopped_at_poll')} of {summary.get('polls_seen')}"
           f", skipped {summary.get('skip_polls')} warmup)"]
    arts = summary.get("artifacts") or []
    if arts:
        total = sum(a.get("bytes", 0) for a in arts)
        out.append(f"device artifacts: {len(arts)} file(s), "
                   f"{_fmt_bytes(total)} "
                   f"(newest: {arts[0]['path']})")
    else:
        note = summary.get("error") or "window never opened"
        out.append(f"device artifacts: none ({note})")
    phases = summary.get("phases") or {}
    total_s = sum(p.get("seconds", 0.0) for p in phases.values()) or 0.0
    out.append("")
    header = (f"  {'phase':<12} {'host_s':>9} {'share':>7} "
              f"{'calls':>7}")
    if trace_phase_counts is not None:
        header += f" {'trace_calls':>12} {'match':>6}"
    out.append(header)
    names = sorted(phases,
                   key=lambda k: -phases[k].get("seconds", 0.0))
    for name in names:
        p = phases[name]
        sec = p.get("seconds", 0.0)
        share = f"{sec / total_s:6.1%}" if total_s > 0 else "   n/a"
        line = (f"  {name:<12} {sec:9.4f} {share:>7} "
                f"{p.get('calls', 0):>7,}")
        if trace_phase_counts is not None:
            tc = trace_phase_counts.get(name)
            line += (f" {tc if tc is not None else 'n/a':>12} "
                     f"{'yes' if tc is not None else 'NO':>6}")
        out.append(line)
    if trace_phase_counts is not None:
        missing = sorted(set(trace_phase_counts) - set(phases))
        if missing:
            out.append(f"  (trace phases with no annotation: "
                       f"{missing})")
        else:
            out.append("  (every trace phase has a matching "
                       "annotation)")
    ann = summary.get("annotations") or []
    out.append("")
    out.append("annotation spans on the device timeline: "
               + (", ".join(ann) if ann else "(none)"))
    return "\n".join(out)


def summarize_profile(profile_dir: str,
                      trace_path: Optional[str] = None) -> dict:
    """Machine-readable reconciliation: the sidecar summary, plus the
    run trace's ``phase_counts`` and the annotation/phase match
    verdict when a trace is given."""
    summary = load_summary(profile_dir)
    result = dict(summary, profile_dir=profile_dir)
    if trace_path:
        from dpsvm_tpu.observability.report import (load_trace,
                                                    resolve_trace_path,
                                                    trace_facts)
        facts = trace_facts(load_trace(resolve_trace_path(trace_path)))
        counts = facts.get("phase_counts") or {}
        result["trace_phase_counts"] = counts
        result["phases_match"] = (
            set(counts) <= set(summary.get("phases") or {}))
    return result

"""``dpsvm compare A B``: two traces in, one mechanical verdict out.

The ROADMAP's "measurably faster" mandate needs a tool that turns two
traces into a verdict, not a human eyeballing JSONL — especially with
BENCH history sparse (tunnel outages). ``compare`` aligns two run
traces (or the newest trace in each of two directories), prints a
delta table — it/s, gap trajectory at matched iteration marks, phase
split, cache hit rate, compile count/seconds, HBM peak — and exits
non-zero on a regression past ``--fail-on-regress PCT``, so benches
and CI get a perf gate.

Gated metrics (direction-aware):

* ``iters_per_sec`` — B slower than A by more than PCT%;
* ``hbm_peak`` — B's high-water mark above A's by more than PCT%;
* ``compile_seconds`` — B above A by more than PCT% AND by more than
  1 s absolute (sub-second compile jitter is noise, not regression).

Everything else in the table is context, not a gate: ``train_seconds``
depends on budgets/shape, gap marks depend on trajectory, and a run
that is FASTER fails no gate however different it looks.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

from dpsvm_tpu.observability.report import (load_trace,
                                            load_trace_auto,
                                            resolve_trace_path,
                                            trace_facts)

# Below this absolute delta, compile_seconds differences are jitter.
COMPILE_SECONDS_NOISE_FLOOR = 1.0


def _pct(a: Optional[float], b: Optional[float]) -> Optional[float]:
    if a is None or b is None or a == 0:
        return None
    return (b - a) / abs(a) * 100.0


def _gap_at(curve: List[Tuple[int, float]], it: float) -> Optional[float]:
    """log-space linear interpolation of the gap trajectory at ``it``
    (gaps decay geometrically, so log-space is the faithful axis)."""
    pts = [(i, g) for i, g in curve if g is not None and g > 0]
    if not pts:
        return None
    if it <= pts[0][0]:
        return pts[0][1]
    if it >= pts[-1][0]:
        return pts[-1][1]
    for (i0, g0), (i1, g1) in zip(pts, pts[1:]):
        if i0 <= it <= i1:
            if i1 == i0:
                return g1
            w = (it - i0) / (i1 - i0)
            return 10 ** ((1 - w) * math.log10(g0) + w * math.log10(g1))
    return None


def _gap_marks(fa: dict, fb: dict, marks: int = 4
               ) -> Tuple[List[dict], int]:
    """Gap deltas at iteration marks spanning the two curves' common
    iteration range (empty when the runs share no range — e.g. a
    resumed run against a fresh one). Returns (marks, marks_used).

    Marks are CLAMPED to the polls actually recorded: a short run with
    2 chunk records has exactly one interpolation segment, and asking
    for 4 marks there produced duplicated/clamped-endpoint rows that
    read as a real trajectory — the table notes the clamp instead
    (`render_compare`)."""
    ca, cb = fa["curve"], fb["curve"]
    if not ca or not cb:
        return [], 0
    lo = max(ca[0][0], cb[0][0])
    hi = min(ca[-1][0], cb[-1][0])
    if hi <= lo:
        return [], 0
    # polls per trace inside the common range: the interpolation has
    # min(polls)-1 real segments; more marks than that only re-sample
    # the same segments (and round to duplicate n_iter rows on short
    # runs).
    avail = min(sum(1 for i, _g in c if lo <= i <= hi)
                for c in (ca, cb))
    used = max(1, min(int(marks), avail - 1 if avail > 1 else 1,
                      int(hi - lo)))
    out = []
    for k in range(1, used + 1):
        it = lo + (hi - lo) * k / used
        ga, gb = _gap_at(ca, it), _gap_at(cb, it)
        out.append({"n_iter": int(round(it)), "a": ga, "b": gb,
                    "delta_pct": _pct(ga, gb)})
    return out, used


def compare_traces(records_a: List[dict], records_b: List[dict],
                   marks: int = 4) -> dict:
    """Machine-readable comparison of two validated traces. ``a`` is
    the baseline; deltas read as B-relative-to-A."""
    fa, fb = trace_facts(records_a), trace_facts(records_b)
    rows = []
    for key in ("iters_per_sec", "train_seconds", "iters", "n_iter",
                "gap", "n_sv", "cache_hit_rate", "n_compiles",
                "compile_seconds", "hbm_peak", "est_flops",
                "est_bytes", "est_flops_per_sec", "arith_intensity",
                "roofline_fraction"):
        rows.append({"metric": key, "a": fa.get(key), "b": fb.get(key),
                     "delta_pct": _pct(fa.get(key), fb.get(key))})
    phase_names = sorted(set(fa["phases"]) | set(fb["phases"]))
    phases = []
    tot_a = sum(fa["phases"].values()) or 0.0
    tot_b = sum(fb["phases"].values()) or 0.0
    for name in phase_names:
        sa, sb = fa["phases"].get(name), fb["phases"].get(name)
        phases.append({
            "phase": name, "a": sa, "b": sb,
            "a_share": (sa / tot_a) if sa is not None and tot_a else None,
            "b_share": (sb / tot_b) if sb is not None and tot_b else None,
            "a_count": fa["phase_counts"].get(name),
            "b_count": fb["phase_counts"].get(name),
            "delta_pct": _pct(sa, sb)})
    gap_marks, marks_used = _gap_marks(fa, fb, marks)
    return {
        "a": {k: fa.get(k) for k in ("solver", "n", "d", "schema",
                                     "converged", "device_kind",
                                     "roofline_verdict")},
        "b": {k: fb.get(k) for k in ("solver", "n", "d", "schema",
                                     "converged", "device_kind",
                                     "roofline_verdict")},
        "metrics": rows,
        "gap_marks": gap_marks,
        "marks_requested": int(marks),
        "marks_used": marks_used,
        "phases": phases,
    }


def regressions(cmp: dict, pct: float) -> List[str]:
    """Direction-aware regression verdicts past ``pct`` percent;
    empty = the gate passes."""
    by = {r["metric"]: r for r in cmp["metrics"]}
    out = []
    ips = by["iters_per_sec"]
    if (ips["a"] and ips["b"] is not None
            and ips["b"] < ips["a"] * (1 - pct / 100.0)):
        out.append(f"iters_per_sec regressed {-ips['delta_pct']:.1f}% "
                   f"({ips['a']:g} -> {ips['b']:g}, threshold {pct:g}%)")
    hbm = by["hbm_peak"]
    if (hbm["a"] and hbm["b"] is not None
            and hbm["b"] > hbm["a"] * (1 + pct / 100.0)):
        out.append(f"hbm_peak grew {hbm['delta_pct']:.1f}% "
                   f"({hbm['a']:,} -> {hbm['b']:,} bytes, "
                   f"threshold {pct:g}%)")
    cs = by["compile_seconds"]
    if (cs["a"] is not None and cs["b"] is not None
            and cs["b"] > cs["a"] * (1 + pct / 100.0)
            and cs["b"] - cs["a"] > COMPILE_SECONDS_NOISE_FLOOR):
        out.append(f"compile_seconds grew {cs['delta_pct']:.1f}% "
                   f"({cs['a']:g} -> {cs['b']:g} s, threshold {pct:g}% "
                   f"and > {COMPILE_SECONDS_NOISE_FLOOR:g} s)")
    return out


def _cell(v, metric: str = "") -> str:
    if v is None:
        return "n/a"
    if isinstance(v, bool):
        return str(v)
    if metric in ("hbm_peak",):
        return f"{v:,.0f}"
    if isinstance(v, float):
        return f"{v:,.4g}"
    return f"{v:,}"


def render_compare(cmp: dict, label_a: str = "A",
                   label_b: str = "B") -> str:
    """The human delta table behind ``dpsvm compare``."""
    out = []
    a, b = cmp["a"], cmp["b"]
    out.append(f"A: {label_a}  [{a['solver']}  {a['n']}x{a['d']}  "
               f"schema v{a['schema']}  converged={a['converged']}]")
    out.append(f"B: {label_b}  [{b['solver']}  {b['n']}x{b['d']}  "
               f"schema v{b['schema']}  converged={b['converged']}]")
    out.append("")
    w = 18
    out.append(f"  {'metric':<{w}} {'A':>14} {'B':>14} {'delta':>9}")
    for r in cmp["metrics"]:
        d = (f"{r['delta_pct']:+8.1f}%" if r["delta_pct"] is not None
             else "      n/a")
        out.append(f"  {r['metric']:<{w}} {_cell(r['a'], r['metric']):>14} "
                   f"{_cell(r['b'], r['metric']):>14} {d}")
    if cmp["gap_marks"]:
        out.append("")
        clamp = ""
        used = cmp.get("marks_used", len(cmp["gap_marks"]))
        req = cmp.get("marks_requested", used)
        if used < req:
            clamp = (f" [marks clamped {req} -> {used}: short run, "
                     "too few chunk polls in the common range]")
        out.append("  gap trajectory at matched iteration marks "
                   f"(lower = further converged):{clamp}")
        for m in cmp["gap_marks"]:
            d = (f"{m['delta_pct']:+8.1f}%" if m["delta_pct"] is not None
                 else "      n/a")
            out.append(f"  gap@{m['n_iter']:<{w - 4},} "
                       f"{_cell(m['a']):>14} {_cell(m['b']):>14} {d}")
    if a.get("roofline_verdict") or b.get("roofline_verdict"):
        out.append("")
        out.append("  roofline verdict (observability/roofline.py): "
                   f"A {a.get('roofline_verdict') or 'n/a'} "
                   f"({a.get('device_kind') or '?'}) vs "
                   f"B {b.get('roofline_verdict') or 'n/a'} "
                   f"({b.get('device_kind') or '?'})")
    if cmp["phases"]:
        out.append("")
        out.append("  host-loop phase split (seconds, share, calls):")
        for p in cmp["phases"]:
            sa = (f"{p['a']:.3f}s/{p['a_share']:.0%}"
                  if p["a"] is not None and p["a_share"] is not None
                  else "n/a")
            sb = (f"{p['b']:.3f}s/{p['b_share']:.0%}"
                  if p["b"] is not None and p["b_share"] is not None
                  else "n/a")
            ca = f"{p['a_count']:,}x" if p["a_count"] else "-"
            cb = f"{p['b_count']:,}x" if p["b_count"] else "-"
            out.append(f"  {p['phase']:<{w}} {sa:>14} {sb:>14}   "
                       f"{ca} vs {cb}")
    return "\n".join(out)


def compare_paths(path_a: str, path_b: str, marks: int = 4
                  ) -> Tuple[dict, str, str]:
    """Resolve (file or directory), load+validate, compare. Returns
    (comparison, resolved_a, resolved_b). A directory holding a
    multi-host ``trace_h*`` family resolves to itself and compares the
    MERGED fleet timeline (report.load_trace_auto) — never one
    arbitrary host's view of a group run."""
    import os

    def _load(path: str) -> Tuple[List[dict], str]:
        if os.path.isdir(path):
            from dpsvm_tpu.observability import merge as _merge
            if len(_merge.discover_family(path)) > 1:
                return load_trace_auto(path), path
        resolved = resolve_trace_path(path)
        return load_trace(resolved), resolved

    recs_a, ra = _load(path_a)
    recs_b, rb = _load(path_b)
    return compare_traces(recs_a, recs_b, marks=marks), ra, rb

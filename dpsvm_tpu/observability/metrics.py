"""Unified metric registry: the one live metrics surface both halves
of the system feed (docs/OBSERVABILITY.md "Metrics").

Training emits rich per-run JSONL traces and serving exposes a JSON
`/metricsz` blob, but neither is a *live*, standard-format surface a
scraper can consume — and the two halves had no shared instrument
vocabulary. This module is that surface:

* ``MetricsRegistry`` — counters, gauges and fixed-bucket histograms,
  each with optional label sets, thread-safe under concurrent serving
  updates. ``default_registry()`` is the process-wide instance: the
  training driver feeds it from the existing packed-stats polls (so a
  scraped training run costs ZERO additional device->host transfers —
  the same economics as tracing, solver/driver.py "Poll economics")
  and ``dpsvm serve`` passes it to the ``ServingServer`` so one
  process serving and training would expose one registry.
* **Exposition** — ``render_prometheus()`` emits the Prometheus/
  OpenMetrics text format (``# HELP``/``# TYPE`` lines, label
  escaping, histogram ``_bucket``/``_sum``/``_count`` series);
  ``validate_exposition()`` is a line-by-line grammar checker used by
  the CI selfcheck and the test suite, so the exposition can never
  drift into something a real scraper rejects. ``snapshot()`` is the
  JSON twin for the existing ``/metricsz`` consumers.
* **Exporters** — the serving server answers
  ``/metricsz?format=prometheus``; training gets an opt-in read-only
  sidecar (``train --metrics-port N`` -> ``MetricsServer``, same
  handler semantics, torn down at run end) and scrape-less CI gets
  ``train --metrics-out FILE`` periodic text snapshots
  (``write_snapshot`` — atomic tmp+rename per poll).

Deliberately dependency-free (stdlib only — not even numpy): the
registry is imported by the serving layer, the CLI and the driver, and
must never force a backend init. This registry is a contract the
`dpsvm tune` autotuner READS (tuning/tuner.py: every train probe rides
the driver's ``dpsvm_train_*`` feed and snapshots it into its probe
ledger rows); keep the instrument API stable.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: default latency buckets (milliseconds) for serving histograms —
#: fixed at registration like every Prometheus histogram, spanning
#: loopback/TPU-local latencies to deep-overload tails. The sub-
#: millisecond rungs exist because the old floor (1 ms) was coarser
#: than the thing being measured: a loopback stub answers in ~0.1 ms
#: and a TPU-local decision pass in ~0.5 ms, so every such request
#: piled into one bucket and the histogram could not distinguish a
#: 5x regression below 1 ms (pinned in tests/test_metrics.py).
DEFAULT_LATENCY_BUCKETS_MS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                              25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
                              2500.0, 5000.0)


def escape_label_value(v: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote and
    newline (the three characters the text format reserves)."""
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def escape_help(v: str) -> str:
    """# HELP line escaping: backslash and newline only."""
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer() and abs(v) < 2 ** 53:
        return str(int(v))
    return repr(float(v))


class _Series:
    """One labeled time series of a metric family."""

    __slots__ = ("labels", "value", "buckets", "sum", "count")

    def __init__(self, labels: Tuple[str, ...],
                 n_buckets: int = 0):
        self.labels = labels
        self.value = 0.0
        # histogram state: per-bucket cumulative-at-render counts are
        # derived; stored counts are per-bucket increments
        self.buckets = [0] * n_buckets if n_buckets else None
        self.sum = 0.0
        self.count = 0


class _Child:
    """Handle to one labeled series: what producers hold and update.
    All mutation goes through the owning registry's lock."""

    __slots__ = ("_metric", "_series")

    def __init__(self, metric: "_Metric", series: _Series):
        self._metric = metric
        self._series = series

    @property
    def value(self) -> float:
        with self._metric._lock:
            return self._series.value

    def inc(self, amount: float = 1.0) -> None:
        if self._metric.kind == "counter" and amount < 0:
            raise ValueError(
                f"counter {self._metric.name} cannot decrease "
                f"(inc({amount}))")
        with self._metric._lock:
            self._series.value += amount

    def dec(self, amount: float = 1.0) -> None:
        if self._metric.kind != "gauge":
            raise ValueError(f"{self._metric.kind} {self._metric.name} "
                             "cannot dec()")
        with self._metric._lock:
            self._series.value -= amount

    def set(self, v: float) -> None:
        if self._metric.kind != "gauge":
            raise ValueError(f"{self._metric.kind} {self._metric.name} "
                             "cannot set()")
        with self._metric._lock:
            self._series.value = float(v)

    def observe(self, v: float) -> None:
        if self._metric.kind != "histogram":
            raise ValueError(f"{self._metric.kind} {self._metric.name} "
                             "cannot observe()")
        v = float(v)
        m = self._metric
        with m._lock:
            s = self._series
            s.sum += v
            s.count += 1
            for i, ub in enumerate(m.buckets):
                if v <= ub:
                    s.buckets[i] += 1
                    break
            else:
                s.buckets[-1] += 1      # the +Inf bucket

    def histogram_state(self) -> Tuple[List[int], float, int]:
        """(per-bucket increments, sum, count) — test/JSON view."""
        with self._metric._lock:
            return (list(self._series.buckets or ()),
                    self._series.sum, self._series.count)


class _Metric:
    """One metric family: a name, a kind, a help line, a label scheme
    and the labeled series producers have created."""

    def __init__(self, name: str, help_: str, kind: str,
                 label_names: Sequence[str], lock: threading.RLock,
                 buckets: Optional[Sequence[float]] = None):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in label_names:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name {ln!r} "
                                 f"(metric {name})")
        self.name = name
        self.help = str(help_)
        self.kind = kind
        self.label_names = tuple(label_names)
        self._lock = lock
        self._series: Dict[Tuple[str, ...], _Series] = {}
        if kind == "histogram":
            bs = [float(b) for b in (buckets or ())]
            if not bs:
                raise ValueError(f"histogram {name} needs buckets")
            if bs != sorted(bs) or len(set(bs)) != len(bs):
                raise ValueError(f"histogram {name} buckets must be "
                                 f"strictly increasing, got {bs}")
            if not math.isinf(bs[-1]):
                bs.append(float("inf"))
            self.buckets: Tuple[float, ...] = tuple(bs)
        else:
            self.buckets = ()

    def _key(self, values, kv) -> Tuple[str, ...]:
        if values and kv:
            raise ValueError("pass label values positionally OR by "
                             "keyword, not both")
        if kv:
            missing = [k for k in self.label_names if k not in kv]
            extra = [k for k in kv if k not in self.label_names]
            if missing or extra:
                raise ValueError(
                    f"metric {self.name}: labels {self.label_names} "
                    f"(missing {missing}, unexpected {extra})")
            values = tuple(kv[k] for k in self.label_names)
        if len(values) != len(self.label_names):
            raise ValueError(
                f"metric {self.name} takes {len(self.label_names)} "
                f"label value(s) {self.label_names}, got {len(values)}")
        return tuple(str(v) for v in values)

    def labels(self, *values, **kv) -> _Child:
        """The series handle for one label-value combination (created
        on first use). Positional values follow the registration
        order; keyword values may come in any order."""
        key = self._key(values, kv)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = _Series(key, n_buckets=len(self.buckets))
                self._series[key] = s
            return _Child(self, s)

    def remove(self, *values, **kv) -> bool:
        """Drop one labeled series (True when it existed). The escape
        hatch bounded-cardinality surfaces need: a TenantLabelBudget
        eviction removes the evicted tenant's series so the exposition
        can never grow past the label budget. Stale _Child handles to
        a removed series keep working but update an orphan — callers
        must re-resolve through ``labels()`` after an eviction."""
        key = self._key(values, kv)
        with self._lock:
            return self._series.pop(key, None) is not None

    # unlabeled convenience: counter.inc() etc. act on the () series
    def _default(self) -> _Child:
        return self.labels()

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def set(self, v: float) -> None:
        self._default().set(v)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    @property
    def value(self) -> float:
        return self._default().value

    def series(self) -> List[Tuple[Tuple[str, ...], _Series]]:
        with self._lock:
            return sorted(self._series.items())

    def _label_str(self, key: Tuple[str, ...],
                   extra: Tuple[Tuple[str, str], ...] = ()) -> str:
        pairs = [(n, v) for n, v in zip(self.label_names, key)]
        pairs += list(extra)
        if not pairs:
            return ""
        return ("{" + ",".join(
            f'{n}="{escape_label_value(v)}"' for n, v in pairs) + "}")

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {escape_help(self.help)}",
               f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = sorted(self._series.items())
            if self.kind == "histogram":
                for key, s in items:
                    cum = 0
                    for ub, c in zip(self.buckets, s.buckets):
                        cum += c
                        le = "+Inf" if math.isinf(ub) else _fmt_value(ub)
                        out.append(
                            f"{self.name}_bucket"
                            f"{self._label_str(key, (('le', le),))} "
                            f"{cum}")
                    out.append(f"{self.name}_sum{self._label_str(key)} "
                               f"{_fmt_value(s.sum)}")
                    out.append(f"{self.name}_count{self._label_str(key)} "
                               f"{s.count}")
            else:
                for key, s in items:
                    out.append(f"{self.name}{self._label_str(key)} "
                               f"{_fmt_value(s.value)}")
        return out

    def snapshot(self) -> dict:
        with self._lock:
            if self.kind == "histogram":
                series = [
                    {"labels": dict(zip(self.label_names, key)),
                     "buckets": {("+Inf" if math.isinf(ub)
                                  else _fmt_value(ub)): c
                                 for ub, c in zip(self.buckets,
                                                  s.buckets)},
                     "sum": s.sum, "count": s.count}
                    for key, s in sorted(self._series.items())]
            else:
                series = [
                    {"labels": dict(zip(self.label_names, key)),
                     "value": s.value}
                    for key, s in sorted(self._series.items())]
        return {"kind": self.kind, "help": self.help, "series": series}


class MetricsRegistry:
    """Thread-safe named collection of metric families.

    ``counter``/``gauge``/``histogram`` are get-or-create: registering
    the same name twice returns the existing family (so sequential
    training runs in one process share their instruments), but a kind
    / label-scheme / bucket mismatch raises — two producers silently
    disagreeing about a metric is exactly the drift this registry
    exists to prevent.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: List[Callable[[], None]] = []

    def _get_or_create(self, name: str, help_: str, kind: str,
                       labels: Sequence[str],
                       buckets: Optional[Sequence[float]] = None
                       ) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != kind or m.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}{m.label_names}, requested "
                        f"{kind}{tuple(labels)}")
                if kind == "histogram" and buckets is not None:
                    want = [float(b) for b in buckets]
                    if not math.isinf(want[-1] if want else 0.0):
                        want.append(float("inf"))
                    if tuple(want) != m.buckets:
                        raise ValueError(
                            f"histogram {name!r} already registered "
                            f"with buckets {m.buckets}")
                return m
            m = _Metric(name, help_, kind, labels, self._lock,
                        buckets=buckets)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_: str = "",
                labels: Sequence[str] = ()) -> _Metric:
        return self._get_or_create(name, help_, "counter", labels)

    def gauge(self, name: str, help_: str = "",
              labels: Sequence[str] = ()) -> _Metric:
        return self._get_or_create(name, help_, "gauge", labels)

    def histogram(self, name: str, help_: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS
                  ) -> _Metric:
        return self._get_or_create(name, help_, "histogram", labels,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def add_collector(self, fn: Callable[[], None]) -> None:
        """Register a pre-scrape hook: called (in order) before every
        render/snapshot so gauges derived from live state (queue
        depths, replica health) are fresh at scrape time. Collector
        exceptions are swallowed — observability must never take the
        producer down."""
        with self._lock:
            self._collectors.append(fn)

    def _collect(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                pass

    def render_prometheus(self) -> str:
        """The text exposition a Prometheus/OpenMetrics scraper reads
        (content type ``text/plain; version=0.0.4``)."""
        self._collect()
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        """JSON twin of the exposition, for ad-hoc consumers."""
        self._collect()
        with self._lock:
            metrics = dict(self._metrics)
        return {name: metrics[name].snapshot()
                for name in sorted(metrics)}


_DEFAULT: Optional[MetricsRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The process-wide registry both halves feed: the training driver
    always updates it; ``dpsvm serve`` hands it to the ServingServer.
    (Library/test ServingServer instances default to a private registry
    so per-instance counter assertions stay exact.)"""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MetricsRegistry()
        return _DEFAULT


# ---------------------------------------------------------------------
# exposition grammar validation (the test/selfcheck side of the format)
# ---------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^{}]*\})?"
    r" (?P<value>[^ ]+)(?: (?P<ts>-?[0-9]+))?$")
_LABEL_PAIR_RE = re.compile(
    r'^(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<value>(?:[^"\\]|\\["\\n])*)"$')
_VALID_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


def _split_labels(raw: str) -> Optional[List[Tuple[str, str]]]:
    """Parse `{a="x",b="y"}` honoring escapes; None on bad syntax."""
    body = raw[1:-1]
    if not body:
        return []
    pairs: List[Tuple[str, str]] = []
    # split on commas not inside quotes
    parts: List[str] = []
    depth_quote = False
    cur = ""
    i = 0
    while i < len(body):
        ch = body[i]
        if ch == "\\" and depth_quote and i + 1 < len(body):
            cur += body[i:i + 2]
            i += 2
            continue
        if ch == '"':
            depth_quote = not depth_quote
        if ch == "," and not depth_quote:
            parts.append(cur)
            cur = ""
        else:
            cur += ch
        i += 1
    if depth_quote:
        return None
    if cur:
        parts.append(cur)
    for part in parts:
        m = _LABEL_PAIR_RE.match(part.strip())
        if m is None:
            return None
        pairs.append((m.group("name"), m.group("value")))
    return pairs


def _family_of(sample_name: str, typed: Dict[str, str]) -> str:
    """Map a sample name to its metric family (histogram samples use
    the _bucket/_sum/_count suffixes of the family name)."""
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            if typed.get(base) in ("histogram", "summary"):
                return base
    return sample_name


def validate_exposition(text: str) -> List[str]:
    """Line-by-line grammar check of a Prometheus text exposition.
    Returns problems (empty = valid). Checked: HELP/TYPE line shape
    and ordering (TYPE before samples, at most one each per family,
    families contiguous), sample-line grammar incl. label escaping,
    duplicate series, and the histogram invariants — cumulative
    non-decreasing ``_bucket`` counts, a ``+Inf`` bucket equal to
    ``_count``, and a ``_sum`` sample per series."""
    problems: List[str] = []
    typed: Dict[str, str] = {}
    helped: Dict[str, bool] = {}
    seen_samples: Dict[str, List[Tuple[str, Tuple[Tuple[str, str], ...],
                                       float]]] = {}
    family_done: List[str] = []     # families whose block has closed
    current: Optional[str] = None
    seen_series = set()

    def close(fam: Optional[str]) -> None:
        if fam is not None and fam not in family_done:
            family_done.append(fam)

    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                if line.startswith("# "):   # plain comment: allowed
                    continue
                problems.append(f"line {ln}: malformed comment {line!r}")
                continue
            kind, name = parts[1], parts[2]
            if not _NAME_RE.match(name):
                problems.append(f"line {ln}: bad metric name {name!r}")
                continue
            if name != current:
                close(current)
                if name in family_done:
                    problems.append(
                        f"line {ln}: family {name!r} reopened (families "
                        "must be contiguous)")
                current = name
            if kind == "HELP":
                if helped.get(name):
                    problems.append(f"line {ln}: second HELP for {name}")
                helped[name] = True
            else:
                if len(parts) != 4 or parts[3] not in _VALID_TYPES:
                    problems.append(
                        f"line {ln}: TYPE must be one of "
                        f"{_VALID_TYPES}, got {line!r}")
                    continue
                if name in typed:
                    problems.append(f"line {ln}: second TYPE for {name}")
                if name in seen_samples:
                    problems.append(
                        f"line {ln}: TYPE for {name} after its samples")
                typed[name] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            problems.append(f"line {ln}: not a valid sample: {line!r}")
            continue
        name = m.group("name")
        labels_raw = m.group("labels")
        labels = _split_labels(labels_raw) if labels_raw else []
        if labels is None:
            problems.append(f"line {ln}: bad label syntax: "
                            f"{labels_raw!r}")
            continue
        try:
            value = float(m.group("value").replace("+Inf", "inf")
                          .replace("-Inf", "-inf").replace("NaN", "nan"))
        except ValueError:
            problems.append(f"line {ln}: bad sample value "
                            f"{m.group('value')!r}")
            continue
        fam = _family_of(name, typed)
        if fam != current:
            close(current)
            if fam in family_done:
                problems.append(
                    f"line {ln}: family {fam!r} reopened (families "
                    "must be contiguous)")
            current = fam
        series_key = (name, tuple(sorted(labels)))
        if series_key in seen_series:
            problems.append(f"line {ln}: duplicate series "
                            f"{name}{dict(labels)}")
        seen_series.add(series_key)
        seen_samples.setdefault(fam, []).append(
            (name, tuple(labels), value))
        kind = typed.get(fam)
        if kind == "counter" and not math.isnan(value) and value < 0:
            problems.append(f"line {ln}: counter {name} < 0")

    # histogram invariants, per family and per label set (minus `le`)
    for fam, kind in typed.items():
        if kind != "histogram":
            continue
        samples = seen_samples.get(fam, [])
        if not samples:
            problems.append(f"histogram {fam}: TYPE with no samples")
            continue
        by_series: Dict[Tuple[Tuple[str, str], ...], dict] = {}
        for name, labels, value in samples:
            base = tuple(p for p in labels if p[0] != "le")
            st = by_series.setdefault(
                base, {"buckets": [], "sum": None, "count": None})
            if name == fam + "_bucket":
                le = dict(labels).get("le")
                if le is None:
                    problems.append(
                        f"histogram {fam}: _bucket sample without le")
                    continue
                st["buckets"].append((le, value))
            elif name == fam + "_sum":
                st["sum"] = value
            elif name == fam + "_count":
                st["count"] = value
            else:
                problems.append(f"histogram {fam}: stray sample {name}")
        for base, st in by_series.items():
            lbl = dict(base)
            if st["sum"] is None:
                problems.append(f"histogram {fam}{lbl}: missing _sum")
            if st["count"] is None:
                problems.append(f"histogram {fam}{lbl}: missing _count")
            buckets = st["buckets"]
            if not buckets:
                problems.append(f"histogram {fam}{lbl}: no _bucket "
                                "samples")
                continue
            if buckets[-1][0] != "+Inf":
                problems.append(f"histogram {fam}{lbl}: last bucket "
                                f"must be le=\"+Inf\", got "
                                f"{buckets[-1][0]!r}")
            prev = -1.0
            for le, v in buckets:
                if v < prev:
                    problems.append(
                        f"histogram {fam}{lbl}: bucket counts not "
                        f"cumulative at le={le}")
                prev = v
            if (st["count"] is not None and buckets[-1][0] == "+Inf"
                    and buckets[-1][1] != st["count"]):
                problems.append(
                    f"histogram {fam}{lbl}: +Inf bucket "
                    f"{buckets[-1][1]} != _count {st['count']}")
    return problems


def incidents_counter(registry: Optional[MetricsRegistry] = None):
    """The process-wide ``dpsvm_incidents_total`` counter (one per
    registry; get-or-create): every alert-rule firing that produced an
    incident — serving watchtower or training driver alike — counts
    here, so one scrape answers "has this process paged"
    (docs/OBSERVABILITY.md "Watch & alerts")."""
    reg = registry if registry is not None else default_registry()
    return reg.counter(
        "dpsvm_incidents_total",
        "alert-rule firings that opened an incident").labels()


# ---------------------------------------------------------------------
# bounded-cardinality tenant labels (docs/OBSERVABILITY.md
# "Per-tenant attribution")
# ---------------------------------------------------------------------

#: the mandatory overflow bucket every out-of-budget tenant lands in —
#: a fixed label value, so total series stay <= budget + 1 per family.
TENANT_OTHER = "other"

#: default top-K active tenants that get their own label value
#: (``dpsvm serve --tenant-budget`` overrides).
DEFAULT_TENANT_BUDGET = 32

#: longest tenant name accepted at admission; longer ones are clamped
#: (a label value is an identity, not a payload channel).
MAX_TENANT_LEN = 64


def sanitize_tenant(name) -> Optional[str]:
    """Admission-side tenant-name hygiene: strip, replace control
    characters (newline included) with ``_``, clamp to MAX_TENANT_LEN.
    Returns None for an unusable name (empty / whitespace / not a
    string-able scalar) so the caller falls back to its default.

    Printable hostile characters (``"`` and ``\\``) are deliberately
    KEPT: the exposition escapes them (``escape_label_value``) and the
    grammar validator accepts the escaped form — pinned by the
    tamper-case in tests — so a tenant named ``acme"prod`` stays
    identifiable instead of being silently renamed."""
    if name is None or isinstance(name, (dict, list, tuple)):
        return None
    s = str(name)
    s = "".join(ch if ch.isprintable() else "_" for ch in s)
    s = s.strip()[:MAX_TENANT_LEN].strip()
    return s or None


class TenantLabelBudget:
    """Bounded-cardinality tenant -> label-value resolver.

    Prometheus dies by label cardinality: a fleet with an unbounded
    tenant label is one curious client away from a series explosion.
    This resolver admits at most ``budget`` resident tenants; everyone
    else resolves to the ``other`` overflow bucket, so per-family
    series are <= budget + 1 forever (pinned by the 10k-churn test).

    Residency is LRU-of-activity with a deterministic twist: activity
    is a monotone integer tick (no wall clock — replays and tests see
    identical evictions), and a non-resident needs a SECOND touch
    while the budget is full to evict the least-recently-active
    resident. One-shot names — the churny tail — never displace a
    working set, they aggregate into ``other``; a genuinely active
    newcomer gets in on its second request. ``on_evict(tenant)`` fires
    (outside any hot path, same thread) so the owner can drop the
    evicted tenant's series (``_Metric.remove``).

    Thread-safe; stdlib only."""

    OTHER = TENANT_OTHER

    def __init__(self, budget: int = DEFAULT_TENANT_BUDGET,
                 on_evict: Optional[Callable[[str], None]] = None):
        if int(budget) < 1:
            raise ValueError(f"tenant budget must be >= 1, got {budget}")
        self.budget = int(budget)
        self._on_evict = on_evict
        self._lock = threading.Lock()
        self._tick = 0
        self._resident: Dict[str, int] = {}     # tenant -> last tick
        self._waiting: Dict[str, int] = {}      # non-resident touches
        self._evictions = 0
        self._overflow = 0

    def resolve(self, tenant: str) -> str:
        """The label value to use for ``tenant`` right now: the name
        itself while resident (or admitted by this touch), else
        ``other``. Every call counts as activity."""
        tenant = str(tenant)
        if tenant == TENANT_OTHER:
            return TENANT_OTHER
        evicted = None
        with self._lock:
            self._tick += 1
            if tenant in self._resident:
                self._resident[tenant] = self._tick
                return tenant
            if len(self._resident) < self.budget:
                self._resident[tenant] = self._tick
                self._waiting.pop(tenant, None)
                return tenant
            touches = self._waiting.get(tenant, 0) + 1
            if touches >= 2:
                lru = min(self._resident, key=self._resident.get)
                del self._resident[lru]
                self._evictions += 1
                evicted = lru
                self._resident[tenant] = self._tick
                self._waiting.pop(tenant, None)
            else:
                self._waiting[tenant] = touches
                # the waiting map is itself bounded: one-shot churn
                # must not hoard host memory either
                while len(self._waiting) > self.budget:
                    drop = next(iter(self._waiting))
                    del self._waiting[drop]
                self._overflow += 1
        if evicted is not None and self._on_evict is not None:
            try:
                self._on_evict(evicted)
            except Exception:
                pass
        if evicted is not None:
            return tenant
        return TENANT_OTHER

    def is_resident(self, tenant: str) -> bool:
        with self._lock:
            return tenant in self._resident

    def residents(self) -> List[str]:
        """Resident tenants, most recently active first."""
        with self._lock:
            return sorted(self._resident,
                          key=self._resident.get, reverse=True)

    def stats(self) -> dict:
        with self._lock:
            return {"budget": self.budget,
                    "live": len(self._resident),
                    "evictions": self._evictions,
                    "overflow": self._overflow}


# ---------------------------------------------------------------------
# the training half: packed-stats polls -> registry
# ---------------------------------------------------------------------

class TrainingMetrics:
    """Feeds training instruments from the values the driver already
    holds at each poll boundary — the packed-stats read, the PhaseTimer
    buckets, the host-side HBM snapshot and the drained compilewatch
    observations. Every update is host-side dict arithmetic: a scraped
    (or snapshotted) training run performs ZERO additional
    device->host transfers, pinned by tests/test_metrics.py."""

    def __init__(self, registry: Optional[MetricsRegistry] = None, *,
                 solver: str = "", n: int = 0, d: int = 0):
        reg = registry if registry is not None else default_registry()
        self.registry = reg
        self._g_info = reg.gauge(
            "dpsvm_train_run_info",
            "1 while a run is live; labels carry the run identity",
            labels=("solver",))
        self._g_iter = reg.gauge("dpsvm_train_iterations",
                                 "solver iteration count at the last "
                                 "poll")
        self._g_gap = reg.gauge("dpsvm_train_gap",
                                "duality gap (b_lo - b_hi) at the last "
                                "poll")
        self._g_nsv = reg.gauge("dpsvm_train_n_sv",
                                "support-vector count at the last poll")
        self._g_ips = reg.gauge("dpsvm_train_iters_per_sec",
                                "iteration throughput between the last "
                                "two polls")
        self._c_polls = reg.counter("dpsvm_train_polls_total",
                                    "host packed-stats polls")
        self._g_hits = reg.gauge("dpsvm_train_cache_hits",
                                 "kernel-row cache hits (device "
                                 "cumulative)")
        self._g_misses = reg.gauge("dpsvm_train_cache_misses",
                                   "kernel-row cache misses (device "
                                   "cumulative)")
        self._g_hbm = reg.gauge("dpsvm_train_hbm_peak_bytes",
                                "allocator high-water mark (absent "
                                "stats report 0)")
        self._c_compiles = reg.counter("dpsvm_train_compiles_total",
                                       "XLA compiles/retraces of chunk "
                                       "programs")
        self._c_compile_s = reg.counter(
            "dpsvm_train_compile_seconds_total",
            "wall seconds spent in XLA compiles")
        self._g_phase = reg.gauge("dpsvm_train_phase_seconds",
                                  "cumulative host-loop phase seconds",
                                  labels=("phase",))
        self._g_phase_calls = reg.gauge("dpsvm_train_phase_calls",
                                        "cumulative host-loop phase "
                                        "call counts",
                                        labels=("phase",))
        self._g_heartbeat = reg.gauge(
            "dpsvm_train_shard_heartbeat_age_seconds",
            "seconds since a shard's reported progress advanced",
            labels=("shard",))
        self._g_converged = reg.gauge("dpsvm_train_converged",
                                      "1 once the run converged")
        self._info = self._g_info.labels(solver=solver or "unknown")
        self._info.set(1)
        self._g_converged.set(0)
        self._prev: Optional[Tuple[int, float]] = None   # (n_iter, t)

    def on_poll(self, *, n_iter: int, b_lo: float, b_hi: float,
                n_sv: int = 0, cache_hits: int = 0,
                cache_misses: int = 0,
                phases: Optional[Dict[str, float]] = None,
                phase_counts: Optional[Dict[str, int]] = None,
                hbm: Optional[dict] = None,
                shard_ages: Optional[Sequence[float]] = None) -> None:
        now = time.perf_counter()
        self._c_polls.inc()
        self._g_iter.set(n_iter)
        gap = b_lo - b_hi
        self._g_gap.set(gap if math.isfinite(gap) else float("nan"))
        self._g_nsv.set(n_sv)
        self._g_hits.set(cache_hits)
        self._g_misses.set(cache_misses)
        if self._prev is not None and now > self._prev[1]:
            self._g_ips.set((n_iter - self._prev[0])
                            / (now - self._prev[1]))
        self._prev = (int(n_iter), now)
        peak = (hbm or {}).get("peak")
        if peak is not None:
            self._g_hbm.set(int(peak))
        for name, sec in (phases or {}).items():
            self._g_phase.labels(phase=name).set(float(sec))
        for name, cnt in (phase_counts or {}).items():
            self._g_phase_calls.labels(phase=name).set(int(cnt))
        for i, age in enumerate(shard_ages or ()):
            self._g_heartbeat.labels(shard=str(i)).set(float(age))

    def on_compile(self, rec: dict) -> None:
        self._c_compiles.inc()
        self._c_compile_s.inc(float(rec.get("seconds", 0.0)))

    def compile_totals(self) -> Tuple[float, float]:
        """(compiles, compile-seconds) — cumulative process counters,
        read by the driver's watch hook to feed the compile-storm rule
        (observability/slo.py) without a second accounting path."""
        return (float(self._c_compiles.value),
                float(self._c_compile_s.value))

    def on_done(self, *, converged: bool, n_iter: int) -> None:
        self._g_converged.set(1 if converged else 0)
        self._g_iter.set(n_iter)
        self._info.set(0)


# ---------------------------------------------------------------------
# the ingest half: shard reads -> registry (data/stream.py)
# ---------------------------------------------------------------------

class DataMetrics:
    """The ``dpsvm_data_*`` instrument family the streaming data layer
    feeds (docs/DATA.md): shards read/quarantined, transient-I/O
    retries, rows delivered, and wall seconds spent in ingest. Every
    update happens on the host around a file read — a metered
    streaming run performs ZERO additional device->host transfers
    (its packed-stats poll count is pinned equal to an in-memory run's
    in tests/test_stream.py)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        reg = registry if registry is not None else default_registry()
        self.registry = reg
        self._c_read = reg.counter("dpsvm_data_shards_read_total",
                                   "shard reads that passed CRC + "
                                   "finiteness checks")
        self._c_rows = reg.counter("dpsvm_data_rows_read_total",
                                   "dataset rows delivered by shard "
                                   "reads")
        self._c_quar = reg.counter("dpsvm_data_shards_quarantined_total",
                                   "shards quarantined by the "
                                   "on_bad_shard=quarantine policy")
        self._c_retry = reg.counter("dpsvm_data_io_retries_total",
                                    "transient shard-read failures "
                                    "recovered by retry-with-backoff")
        self._c_secs = reg.counter("dpsvm_data_ingest_seconds_total",
                                   "wall seconds spent reading + "
                                   "verifying shards")

    def on_read(self, rows: int = 0) -> None:
        self._c_read.inc()
        if rows:
            self._c_rows.inc(int(rows))

    def on_quarantine(self) -> None:
        self._c_quar.inc()

    def on_retry(self) -> None:
        self._c_retry.inc()

    def on_ingest_seconds(self, seconds: float) -> None:
        if seconds > 0:
            self._c_secs.inc(float(seconds))


# ---------------------------------------------------------------------
# exporters: sidecar HTTP server + scrape-less file snapshots
# ---------------------------------------------------------------------

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def wants_prometheus(path: str) -> bool:
    """True when a /metricsz request asks for the text exposition
    (`?format=prometheus`); shared by the serving server and the
    training sidecar so both speak the same dialect."""
    from urllib.parse import parse_qs, urlsplit
    q = parse_qs(urlsplit(path).query)
    return q.get("format", [""])[0] == "prometheus"


class MetricsServer:
    """Read-only metrics sidecar for a training run: GET ``/metricsz``
    answers the JSON snapshot, ``/metricsz?format=prometheus`` the text
    exposition — the same handler semantics as the serving server's
    endpoint. One daemon thread; ``close()`` tears it down at run end
    (the driver's finally block)."""

    def __init__(self, registry: MetricsRegistry,
                 host: str = "127.0.0.1", port: int = 0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        reg = registry

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            server_version = "dpsvm-metrics"

            def log_message(self, fmt, *args):      # quiet sidecar
                pass

            def do_GET(self):                       # noqa: N802
                if not self.path.startswith("/metricsz"):
                    body = b'{"error": "only /metricsz here"}'
                    self.send_response(404)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if wants_prometheus(self.path):
                    body = reg.render_prometheus().encode()
                    ctype = PROMETHEUS_CONTENT_TYPE
                else:
                    body = json.dumps(reg.snapshot()).encode()
                    ctype = "application/json"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dpsvm-metrics-http",
            kwargs={"poll_interval": 0.1}, daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return int(self._httpd.server_address[1])

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._thread.join(1.0)
        except Exception:
            pass


#: per-path monotonic snapshot sequence numbers (process-local): the
#: header line every ``write_snapshot`` emits so a tailing consumer
#: (``dpsvm watch``, observability/slo.SnapshotFollower) can tell a
#: missed snapshot from a duplicate re-read instead of silently
#: mis-windowing its rates. Reset only with the process.
_SNAPSHOT_SEQS: Dict[str, int] = {}
_SNAPSHOT_LOCK = threading.Lock()


def snapshot_header(seq: int, now: Optional[float] = None) -> str:
    """The one header line (a plain comment to every Prometheus
    parser; slo.parse_snapshot_header reads it back):
    ``# dpsvm-snapshot seq=N unix=T time=ISO``."""
    now = time.time() if now is None else float(now)
    iso = time.strftime("%Y-%m-%dT%H:%M:%S%z", time.localtime(now))
    return f"# dpsvm-snapshot seq={int(seq)} unix={now:.3f} time={iso}"


def write_snapshot(registry: MetricsRegistry, path: str,
                   seq: Optional[int] = None,
                   now: Optional[float] = None) -> int:
    """Atomic text-exposition snapshot (tmp + rename): the scrape-less
    CI story — ``train --metrics-out FILE`` refreshes it every poll, so
    a harness reads a complete, parseable exposition at any moment.
    The first line is the monotonic ``seq`` + wall-timestamp header
    (``snapshot_header``); returns the seq written. Best-effort: a
    full disk must not kill the training run."""
    if seq is None:
        with _SNAPSHOT_LOCK:
            seq = _SNAPSHOT_SEQS.get(path, 0) + 1
            _SNAPSHOT_SEQS[path] = seq
    try:
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as fh:
            fh.write(snapshot_header(seq, now) + "\n")
            fh.write(registry.render_prometheus())
        os.replace(tmp, path)
    except OSError:
        pass
    return int(seq)

"""Continuous SLO watching: declarative alert rules over live metrics.

Every observability layer before this one (traces, spans, roofline,
metrics, perf ledger) is retrospective — a human runs ``dpsvm report``
or ``compare`` after the fact. This module is the *continuous* half:
a small, deterministic rule engine that watches the metric samples the
system already produces (the ``/metricsz`` surfaces, the
``--metrics-out`` snapshots, a live run trace) and turns degradation
into alert state WHILE it is happening — the layer that converts the
instrumentation from reporter into pager (docs/OBSERVABILITY.md
"Watch & alerts"; "Parallel SVMs in Practice", arxiv 1404.1066, on
deployments living or dying on operational tooling).

Design constraints, in order:

* **Deterministic.** Every rule is a pure function of the
  ``(t, sample)`` series it has observed: callers pass explicit
  timestamps (the ``Watchtower`` clock is injectable and only used
  when a caller omits ``t``), so every firing is replayable in CI —
  no wall-clock reads inside rule evaluation, ever.
* **Dependency-free.** stdlib only (not even numpy): imported by the
  serving layer, the CLI and the training driver, and must never
  force a backend init.
* **Host-side.** A watched training run performs ZERO additional
  device->host transfers: every sample fact already rides the
  packed-stats poll (solver/driver.py "Poll economics"); a watched
  serving process reads its own counters.

Rule kinds (specs are plain dicts — JSON on disk, Python inline):

* ``burn_rate`` — the Google-SRE multi-window burn-rate alert on an
  error-budget SLO: given cumulative ``good``/``bad`` counters, an
  ``objective`` (e.g. 0.999 availability), and two windows, the rule
  fires only when BOTH the fast and the slow window burn the error
  budget at >= ``threshold`` x the sustainable rate — fast-only
  spikes (shorter than the fast window) never page, and a sustained
  burn pages within the fast window. Clears with hysteresis
  (``clear_after_s`` of healthy fast-window burn), so a flapping
  source cannot flap the alert.
* ``threshold`` — ``metric`` above/below a bound for ``for_s``
  seconds (queue-depth saturation, shard-heartbeat age, p99).
* ``rate`` — the per-second rate of a cumulative counter over
  ``window_s`` above a bound (compile storms: steady state retraces
  NOTHING, so a sustained compile rate is always pathological).
* ``stagnation`` — a metric whose best-seen value stops improving for
  ``window_s`` (the training gap beyond the HealthMonitor's window —
  the watch-side twin of resilience/health.py's in-run guard).
* ``drop_vs_baseline`` — ``metric`` below ``baseline * (1 -
  drop_pct/100)`` for ``for_s``; the baseline is a literal number or
  resolved ONCE at ruleset load from the perf-ledger median
  (``baseline_case`` — the roofline_fraction drop rule).
* ``fair_share`` — noisy-tenant detection: one tenant's share of the
  fleet's trailing-window queue-wait above ``share_above`` while at
  least ``min_tenants`` tenants are active. Reads the cumulative
  ``tenant:<name>:queue_wait_ms`` / ``tenant:<name>:compute_ms``
  lanes the serving layer exports (docs/OBSERVABILITY.md "Per-tenant
  attribution") — queue wait is the cost a tenant imposes on its
  NEIGHBOURS, so a dominant queue-wait share is the isolation alarm
  even when the tenant's own latency still looks fine.
* ``skew`` — straggler detection for a multi-host group: the lag
  between the fastest and the slowest host's ``host:<k>:<metric>``
  lane (normally ``n_iter``), MEANED over a full ``window_s`` of
  samples, above ``lag_above``. The mean — not the instantaneous gap
  — because a healthy group shows a transient gap at every collective
  boundary (the fast host publishes first), while a straggler holds
  the gap open across the whole window. The firing names the laggard
  (the host with the lowest mean progress): the reason carries the
  literal ``skew[host-K]`` and the transition/state a ``host`` key,
  so the fleet incident bundle can attribute the stall
  (docs/OBSERVABILITY.md "Fleet").

**Per-tenant templates.** A rule spec carrying ``"per_tenant": true``
is a TEMPLATE, not a rule: the ``Watchtower`` discovers active
tenants from the ``tenant:<name>:<metric>`` keys in each sample and
expands the template into one concrete rule per tenant (named
``template[tenant]``, ``{tenant}`` substituted into metric/counter
names), capped at ``tenant_cap`` expansions so alert cardinality is
bounded exactly like the metric label budget (metrics.py
``TenantLabelBudget``). The ``other`` overflow tenant is never
expanded — its lanes aggregate many tenants, so a firing there could
not name a culprit. Templates round-trip verbatim through
``RuleSet.to_specs()``; expanded rules live only inside the tower,
and their transitions/states carry a ``tenant`` key so incident
bundles can name the tenant (serving/server.py ``_on_alert``).

**Per-host templates.** The same pattern over the fleet sample's
``host:<k>:<metric>`` lanes (observability/fleet.py): a spec carrying
``"per_host": true`` expands into one concrete rule per active host
(named ``template[host-K]``, ``{host}`` substituted), capped at
``host_cap`` — so a 3-host group's heartbeat-stale page watches three
lanes from one template, and a 300-host fleet cannot explode alert
cardinality.

Severities and exit codes (the ``dpsvm watch`` contract): ``warn`` ->
exit 4, ``page`` -> exit 5; no alert -> 0; a stale/unreachable source
-> 3 (matching ``report --follow``'s stall exit). Distinct codes so
cron/CI can gate per severity.
"""

from __future__ import annotations

import json
import math
import re
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

SEVERITIES = ("warn", "page")

#: `dpsvm watch` exit codes, per the worst severity that FIRED during
#: the watch (not merely the final state — a burn that fired and
#: cleared still failed the gate).
EXIT_OK = 0
EXIT_STALE = 3          # source unreachable / stopped updating
EXIT_WARN = 4
EXIT_PAGE = 5

RULE_KINDS = ("burn_rate", "threshold", "rate", "stagnation",
              "drop_vs_baseline", "fair_share", "skew")

#: The overflow pseudo-tenant (mirrors metrics.TENANT_OTHER — pinned
#: equal in tests/test_watch.py so the two stay one vocabulary without
#: this stdlib-only module importing the metrics layer).
TENANT_OTHER = "other"

#: Default cap on per-template tenant fan-out: alert cardinality gets
#: the same bound the metric series get (metrics.DEFAULT_TENANT_BUDGET).
TENANT_FAN_OUT_CAP = 32

#: ``tenant:<name>:<metric>`` — the flattened per-tenant sample lanes
#: (sample_from_metricsz_json / serving watch_sample). The tenant part
#: is greedy so tenant names containing ``:`` still parse (the metric
#: suffix never contains one).
_TENANT_KEY_RE = re.compile(r"^tenant:(?P<tenant>.+):(?P<metric>[^:]+)$")

#: Default cap on per-template host fan-out (the fleet twin of
#: TENANT_FAN_OUT_CAP).
HOST_FAN_OUT_CAP = 32

#: ``host:<k>:<metric>`` — the flattened per-host sample lanes the
#: fleet federation layer builds (observability/fleet.py
#: fleet_watch_sample). Host ids are integers, so the pattern is
#: strict where the tenant one is greedy.
_HOST_KEY_RE = re.compile(r"^host:(?P<host>\d+):(?P<metric>.+)$")


class RuleError(ValueError):
    """A rule spec that cannot be parsed/validated."""


def severity_exit_code(severity: Optional[str]) -> int:
    return {None: EXIT_OK, "warn": EXIT_WARN, "page": EXIT_PAGE}[severity]


def worst_severity(a: Optional[str], b: Optional[str]) -> Optional[str]:
    order = {None: 0, "warn": 1, "page": 2}
    return a if order[a] >= order[b] else b


def _num(spec: dict, key: str, default=None, *, required: bool = False,
         positive: bool = False):
    v = spec.get(key, default)
    if v is None:
        if required:
            raise RuleError(f"rule {spec.get('name')!r}: missing "
                            f"required key {key!r}")
        return None
    try:
        v = float(v)
    except (TypeError, ValueError):
        raise RuleError(f"rule {spec.get('name')!r}: {key} must be a "
                        f"number, got {spec.get(key)!r}")
    if not math.isfinite(v):
        raise RuleError(f"rule {spec.get('name')!r}: {key} must be "
                        f"finite, got {v}")
    if positive and v <= 0:
        raise RuleError(f"rule {spec.get('name')!r}: {key} must be "
                        f"> 0, got {v}")
    return v


class Rule:
    """One alert rule: spec parsing, sample-window state, and the
    shared fire/clear state machine (for_s debounce on the way up,
    clear_after_s hysteresis on the way down — the no-flap contract
    pinned in tests/test_watch.py)."""

    def __init__(self, spec: dict):
        if not isinstance(spec, dict):
            raise RuleError(f"rule spec must be a dict, got {spec!r}")
        self.spec = dict(spec)
        self.name = str(spec.get("name") or "").strip()
        if not self.name:
            raise RuleError(f"rule spec missing 'name': {spec!r}")
        self.kind = spec.get("kind")
        if self.kind not in RULE_KINDS:
            raise RuleError(f"rule {self.name!r}: kind must be one of "
                            f"{RULE_KINDS}, got {self.kind!r}")
        self.severity = spec.get("severity", "warn")
        if self.severity not in SEVERITIES:
            raise RuleError(f"rule {self.name!r}: severity must be one "
                            f"of {SEVERITIES}, got {self.severity!r}")
        self.for_s = _num(spec, "for_s", 0.0) or 0.0
        self.clear_after_s = _num(spec, "clear_after_s", 0.0) or 0.0
        # per-kind parameters (validated eagerly so a bad rules file
        # fails at load, not at the 3 a.m. firing)
        k = self.kind
        self.per_tenant = bool(spec.get("per_tenant"))
        self.tenant = spec.get("tenant")
        if self.tenant is not None:
            self.tenant = str(self.tenant)
        self.per_host = bool(spec.get("per_host"))
        self.host = spec.get("host")
        if self.host is not None:
            try:
                self.host = int(self.host)
            except (TypeError, ValueError):
                raise RuleError(f"rule {self.name!r}: host must be an "
                                f"integer, got {spec.get('host')!r}")
        #: skew only: the laggard host the last evaluation attributed
        self._laggard: Optional[int] = None
        if k == "skew":
            if self.per_host:
                raise RuleError(
                    f"rule {self.name!r}: skew is inherently "
                    "cross-host — it reads every host:<k> lane "
                    "itself; 'per_host' templating would watch one "
                    "host against nobody")
            self.metric = str(spec.get("metric") or "n_iter")
            self.window_s = _num(spec, "window_s", required=True,
                                 positive=True)
            self.lag_above = _num(spec, "lag_above", required=True,
                                  positive=True)
        elif k == "fair_share":
            if not self.per_tenant and not self.tenant:
                raise RuleError(
                    f"rule {self.name!r}: fair_share needs 'tenant' "
                    "(or 'per_tenant': true to template over active "
                    "tenants)")
            self.window_s = _num(spec, "window_s", 60.0,
                                 positive=True)
            share = _num(spec, "share_above", 0.5)
            if not (0.0 < share < 1.0):
                raise RuleError(f"rule {self.name!r}: share_above "
                                f"must be in (0, 1), got {share}")
            self.share_above = share
            mt = _num(spec, "min_tenants", 2.0)
            if mt < 1:
                raise RuleError(f"rule {self.name!r}: min_tenants "
                                f"must be >= 1, got {mt}")
            self.min_tenants = int(mt)
            self.min_queue_wait_ms = _num(spec, "min_queue_wait_ms",
                                          1.0) or 0.0
        elif k == "burn_rate":
            self.good = str(spec.get("good") or "")
            self.bad = str(spec.get("bad") or "")
            if not self.good or not self.bad:
                raise RuleError(f"rule {self.name!r}: burn_rate needs "
                                "'good' and 'bad' counter names")
            obj = _num(spec, "objective", required=True)
            if not (0.0 < obj < 1.0):
                raise RuleError(f"rule {self.name!r}: objective must be "
                                f"in (0, 1), got {obj}")
            self.objective = obj
            self.budget = 1.0 - obj
            self.fast_window_s = _num(spec, "fast_window_s",
                                      required=True, positive=True)
            self.slow_window_s = _num(spec, "slow_window_s",
                                      required=True, positive=True)
            if self.slow_window_s < self.fast_window_s:
                raise RuleError(
                    f"rule {self.name!r}: slow_window_s "
                    f"({self.slow_window_s}) must be >= fast_window_s "
                    f"({self.fast_window_s})")
            self.threshold = _num(spec, "threshold", required=True,
                                  positive=True)
        elif k in ("threshold", "drop_vs_baseline", "rate",
                   "stagnation"):
            self.metric = str(spec.get("metric") or "")
            if not self.metric:
                raise RuleError(f"rule {self.name!r}: {k} needs "
                                "'metric'")
            if k == "threshold":
                self.above = _num(spec, "above")
                self.below = _num(spec, "below")
                if (self.above is None) == (self.below is None):
                    raise RuleError(f"rule {self.name!r}: threshold "
                                    "needs exactly one of 'above' / "
                                    "'below'")
            elif k == "rate":
                self.window_s = _num(spec, "window_s", required=True,
                                     positive=True)
                self.above = _num(spec, "above", required=True)
            elif k == "stagnation":
                self.window_s = _num(spec, "window_s", required=True,
                                     positive=True)
                self.min_drop = _num(spec, "min_drop", 0.0) or 0.0
                self.direction = spec.get("direction", "down")
                if self.direction not in ("down", "up"):
                    raise RuleError(f"rule {self.name!r}: direction "
                                    "must be 'down' or 'up'")
            else:   # drop_vs_baseline
                self.drop_pct = _num(spec, "drop_pct", required=True,
                                     positive=True)
                self.baseline = _num(spec, "baseline")
                self.baseline_case = spec.get("baseline_case")
                if self.baseline is None and not self.baseline_case:
                    raise RuleError(
                        f"rule {self.name!r}: drop_vs_baseline needs "
                        "'baseline' (a number) or 'baseline_case' (a "
                        "perf-ledger case whose median becomes the "
                        "baseline)")
        # window of (t, value-or-tuple) samples; pruned per kind
        self._samples: deque = deque()
        # fire/clear state machine
        self.firing = False
        self.since: Optional[float] = None       # state entered at
        self._true_since: Optional[float] = None
        self._false_since: Optional[float] = None
        self.reason = ""
        self.fired_count = 0

    # -- window bookkeeping -------------------------------------------

    def _keep_window_s(self) -> float:
        if self.kind == "burn_rate":
            return self.slow_window_s
        if self.kind in ("rate", "stagnation", "fair_share", "skew"):
            return self.window_s
        # threshold / drop_vs_baseline hold no history beyond the
        # debounce; keep the larger debounce span
        return max(self.for_s, self.clear_after_s, 1.0)

    def _prune(self, t: float) -> None:
        keep = self._keep_window_s()
        # keep ONE sample at-or-before the window edge so window deltas
        # of cumulative counters span the full window, not a truncation
        while (len(self._samples) >= 2
               and self._samples[1][0] <= t - keep):
            self._samples.popleft()

    # -- per-kind condition evaluation --------------------------------

    def _window_delta(self, t: float, window_s: float,
                      idx: int) -> Optional[float]:
        """Delta of cumulative-counter lane ``idx`` over the trailing
        window; None with fewer than two samples in range. A counter
        RESET (value decreased — process restart) re-bases at the
        reset point instead of reporting a negative delta."""
        inside = [(ts, v) for ts, v in self._samples
                  if ts >= t - window_s]
        if len(inside) < 2:
            return None
        total = 0.0
        prev = inside[0][1][idx]
        for _, v in inside[1:]:
            cur = v[idx]
            if cur >= prev:
                total += cur - prev
            prev = cur
        return total

    def _burn(self, t: float, window_s: float) -> Optional[float]:
        good = self._window_delta(t, window_s, 0)
        bad = self._window_delta(t, window_s, 1)
        if good is None or bad is None:
            return None
        total = good + bad
        if total <= 0:
            return None                 # no traffic: no verdict
        return (bad / total) / self.budget

    def _condition(self, t: float,
                   sample: Dict[str, float]) -> Tuple[Optional[bool], str]:
        """(condition, reason). None = insufficient data (no state
        transition either way)."""
        if self.kind == "burn_rate":
            g, b = sample.get(self.good), sample.get(self.bad)
            if g is None or b is None:
                return None, ""
            self._samples.append((t, (float(g), float(b))))
            self._prune(t)
            fast = self._burn(t, self.fast_window_s)
            slow = self._burn(t, self.slow_window_s)
            if fast is None or slow is None:
                return None, ""
            cond = (fast >= self.threshold and slow >= self.threshold)
            return cond, (f"burn {fast:.1f}x (fast "
                          f"{self.fast_window_s:g}s) / {slow:.1f}x "
                          f"(slow {self.slow_window_s:g}s) of the "
                          f"{self.budget:.4g} error budget "
                          f"(threshold {self.threshold:g}x)")
        if self.kind == "fair_share":
            own_qw = sample.get(f"tenant:{self.tenant}:queue_wait_ms")
            own_c = sample.get(f"tenant:{self.tenant}:compute_ms")
            if own_qw is None or own_c is None:
                return None, ""
            tot_qw = tot_c = 0.0
            active = set()
            for key, val in sample.items():
                m = _TENANT_KEY_RE.match(key)
                if m is None or not isinstance(val, (int, float)):
                    continue
                active.add(m.group("tenant"))
                if m.group("metric") == "queue_wait_ms":
                    tot_qw += float(val)
                elif m.group("metric") == "compute_ms":
                    tot_c += float(val)
            self._samples.append(
                (t, (float(own_qw), tot_qw, float(own_c), tot_c,
                     float(len(active)))))
            self._prune(t)
            # like ``rate``: a FULL window before any verdict, so the
            # first busy seconds of a process can't misread as a hog
            if t - self._samples[0][0] < self.window_s:
                return None, ""
            d_own_qw = self._window_delta(t, self.window_s, 0)
            d_tot_qw = self._window_delta(t, self.window_s, 1)
            d_own_c = self._window_delta(t, self.window_s, 2)
            d_tot_c = self._window_delta(t, self.window_s, 3)
            if d_own_qw is None or d_tot_qw is None:
                return None, ""
            n_active = int(self._samples[-1][1][4])
            if (n_active < self.min_tenants
                    or d_tot_qw < self.min_queue_wait_ms):
                # too few tenants / too little queueing for a share to
                # mean anything: explicitly healthy, not no-verdict,
                # so a firing clears when traffic drains
                return False, ""
            qw_share = d_own_qw / d_tot_qw
            comp_share = ((d_own_c or 0.0) / d_tot_c
                          if (d_own_c is not None and d_tot_c)
                          else 0.0)
            return (qw_share >= self.share_above,
                    f"tenant {self.tenant!r} queue_wait share "
                    f"{qw_share:.0%} (compute share {comp_share:.0%}) "
                    f"over {self.window_s:g}s across {n_active} "
                    f"active tenants (threshold "
                    f"{self.share_above:.0%})")
        if self.kind == "skew":
            vals: Dict[int, float] = {}
            suffix = f":{self.metric}"
            for key, val in sample.items():
                m = _HOST_KEY_RE.match(key)
                if (m is not None and key.endswith(suffix)
                        and m.group("metric") == self.metric
                        and isinstance(val, (int, float))
                        and math.isfinite(float(val))):
                    vals[int(m.group("host"))] = float(val)
            if len(vals) < 2:
                # a lone host has nobody to lag behind; explicitly
                # healthy (not no-verdict) so a firing clears when the
                # rest of the group drains away
                return (False, "") if self._samples else (None, "")
            self._samples.append((t, vals))
            self._prune(t)
            # a FULL window before any verdict (the rate/fair_share
            # contract): every collective boundary opens a transient
            # gap while the fast host's publish races the slow one's,
            # so only a gap that SURVIVES the whole window is a
            # straggler
            if t - self._samples[0][0] < self.window_s:
                return None, ""
            inside = [(ts, hv) for ts, hv in self._samples
                      if ts >= t - self.window_s and len(hv) >= 2]
            if len(inside) < 2:
                return None, ""
            lag = sum(max(hv.values()) - min(hv.values())
                      for _, hv in inside) / len(inside)
            # the laggard: lowest mean progress over the window
            sums: Dict[int, List[float]] = {}
            for _, hv in inside:
                for h, v in hv.items():
                    sums.setdefault(h, []).append(v)
            means = {h: sum(vs) / len(vs) for h, vs in sums.items()}
            self._laggard = min(means, key=lambda h: (means[h], h))
            return (lag > self.lag_above,
                    f"skew[host-{self._laggard}]: {self.metric} lag "
                    f"{lag:.3g} between fastest and slowest of "
                    f"{len(means)} hosts over {self.window_s:g}s "
                    f"(threshold {self.lag_above:g})")
        v = sample.get(self.metric)
        if v is None:
            return None, ""
        v = float(v)
        if not math.isfinite(v):
            # a non-finite metric is its own emergency: treat as the
            # bad side of whichever comparison the rule makes
            return True, f"{self.metric} is non-finite ({v})"
        if self.kind == "threshold":
            if self.above is not None:
                return (v > self.above,
                        f"{self.metric}={v:g} above {self.above:g}")
            return (v < self.below,
                    f"{self.metric}={v:g} below {self.below:g}")
        if self.kind == "rate":
            self._samples.append((t, (v,)))
            self._prune(t)
            # a FULL window of history is required before any verdict:
            # a process's first seconds always show a high counter
            # rate (warmup compiles), and delta-over-a-sliver would
            # misread that as a storm
            first_t = self._samples[0][0]
            if t - first_t < self.window_s:
                return None, ""
            delta = self._window_delta(t, self.window_s, 0)
            if delta is None:
                return None, ""
            r = delta / self.window_s
            return (r > self.above,
                    f"{self.metric} rate {r:.3g}/s over "
                    f"{self.window_s:g}s above {self.above:g}/s")
        if self.kind == "stagnation":
            better = (lambda a, b: a < b - self.min_drop) \
                if self.direction == "down" else \
                (lambda a, b: a > b + self.min_drop)
            if not self._samples:
                self._samples.append((t, (v,)))
                return None, ""
            best_t, (best_v,) = self._samples[0]
            if better(v, best_v):
                self._samples.clear()
                self._samples.append((t, (v,)))
                return False, ""
            stale = t - best_t
            return (stale >= self.window_s,
                    f"{self.metric} stuck at {best_v:g} for "
                    f"{stale:.3g}s (window {self.window_s:g}s)")
        # drop_vs_baseline
        if self.baseline is None:
            return None, ""             # unresolvable baseline: no-op
        floor = self.baseline * (1.0 - self.drop_pct / 100.0)
        return (v < floor,
                f"{self.metric}={v:g} below {floor:g} "
                f"({self.drop_pct:g}% under baseline "
                f"{self.baseline:g})")

    # -- the fire/clear state machine ---------------------------------

    def observe(self, t: float, sample: Dict[str, float]
                ) -> Optional[dict]:
        """Feed one sample; returns a transition dict on a state
        change (fire/clear), else None."""
        cond, reason = self._condition(t, sample)
        if cond is None:
            return None
        if cond:
            self._false_since = None
            if self._true_since is None:
                self._true_since = t
            if (not self.firing
                    and t - self._true_since >= self.for_s):
                self.firing = True
                self.since = t
                self.reason = reason
                self.fired_count += 1
                return self._transition("firing", t)
            if self.firing:
                self.reason = reason
        else:
            self._true_since = None
            if self._false_since is None:
                self._false_since = t
            if (self.firing
                    and t - self._false_since >= self.clear_after_s):
                self.firing = False
                self.since = t
                self.reason = ""
                return self._transition("ok", t)
        return None

    def window_desc(self) -> str:
        if self.kind == "burn_rate":
            return (f"fast={self.fast_window_s:g}s/"
                    f"slow={self.slow_window_s:g}s")
        if self.kind in ("rate", "stagnation", "fair_share", "skew"):
            return f"{self.window_s:g}s"
        if self.for_s:
            return f"for={self.for_s:g}s"
        return "instant"

    def _attributed_host(self) -> Optional[int]:
        """The host a firing names: the spec pin (a per_host
        expansion), else the skew laggard."""
        return self.host if self.host is not None else self._laggard

    def _transition(self, state: str, t: float) -> dict:
        out = {"rule": self.name, "kind": self.kind,
               "severity": self.severity, "state": state,
               "window": self.window_desc(), "reason": self.reason,
               "t": round(float(t), 6)}
        if self.tenant:
            out["tenant"] = self.tenant
        host = self._attributed_host()
        if host is not None:
            out["host"] = host
        return out

    def state(self) -> dict:
        out = {"rule": self.name, "kind": self.kind,
               "severity": self.severity,
               "state": "firing" if self.firing else "ok",
               "window": self.window_desc(),
               "since": self.since, "reason": self.reason,
               "fired_count": self.fired_count}
        if self.tenant:
            out["tenant"] = self.tenant
        host = self._attributed_host()
        if host is not None:
            out["host"] = host
        return out

    def to_dict(self) -> dict:
        return dict(self.spec)


class RuleSet:
    """An ordered list of rules, round-trippable to/from plain dicts
    (the one source of truth a rules file, the selfcheck and the
    /metricsz alert states all share)."""

    def __init__(self, rules: Sequence[Rule]):
        self.rules = list(rules)
        names = [r.name for r in self.rules]
        dupes = sorted({n for n in names if names.count(n) > 1})
        if dupes:
            raise RuleError(f"duplicate rule name(s): {dupes}")

    @classmethod
    def from_specs(cls, specs: Sequence[dict],
                   ledger_records: Optional[Sequence[dict]] = None
                   ) -> "RuleSet":
        rules = [Rule(s) for s in specs]
        for r in rules:
            if (r.kind == "drop_vs_baseline" and r.baseline is None
                    and r.baseline_case):
                r.baseline = resolve_ledger_baseline(
                    r.baseline_case, r.spec.get("baseline_metric",
                                                r.metric),
                    window=int(r.spec.get("baseline_window", 5) or 5),
                    records=ledger_records)
        return cls(rules)

    @classmethod
    def from_file(cls, path: str) -> "RuleSet":
        """Load a rules file: a JSON list of rule specs, or an object
        with a ``rules`` list (so a file can carry a comment/metadata
        envelope)."""
        with open(path) as fh:
            data = json.load(fh)
        if isinstance(data, dict):
            data = data.get("rules")
        if not isinstance(data, list) or not data:
            raise RuleError(f"{path}: expected a JSON list of rule "
                            "specs (or {'rules': [...]})")
        return cls.from_specs(data)

    def to_specs(self) -> List[dict]:
        return [r.to_dict() for r in self.rules]

    def __len__(self) -> int:
        return len(self.rules)

    def __iter__(self):
        return iter(self.rules)


def resolve_ledger_baseline(case: str, metric: str = "value", *,
                            window: int = 5,
                            records: Optional[Sequence[dict]] = None
                            ) -> Optional[float]:
    """Median of the case's last ``window`` perf-ledger readings —
    the baseline of the roofline-drop rule. None when the ledger is
    absent/disabled or the case has no finite readings (the rule then
    evaluates to no-verdict instead of inventing a baseline)."""
    try:
        from dpsvm_tpu.observability import ledger
        if records is None:
            path = ledger.ledger_path()
            if path is None:
                return None
            records = ledger.read(path)
        vals: List[float] = []
        for r in records:
            if r.get("case") != case:
                continue
            v = r.get(metric)
            if v is None:
                v = (r.get("metrics") or {}).get(metric)
            if isinstance(v, (int, float)) and math.isfinite(v):
                vals.append(float(v))
        if not vals:
            return None
        tail = sorted(vals[-window:])
        mid = len(tail) // 2
        if len(tail) % 2:
            return tail[mid]
        return 0.5 * (tail[mid - 1] + tail[mid])
    except Exception:
        return None


def active_tenants(sample: Dict[str, float]) -> List[str]:
    """Tenant names present in a sample's ``tenant:<name>:<metric>``
    lanes, sorted (deterministic expansion order), ``other`` excluded
    — the overflow aggregate can never name a culprit."""
    seen = set()
    for key in sample:
        m = _TENANT_KEY_RE.match(key)
        if m is not None and m.group("tenant") != TENANT_OTHER:
            seen.add(m.group("tenant"))
    return sorted(seen)


def expand_tenant_rule(spec: dict, tenant: str) -> dict:
    """One concrete rule spec from a ``per_tenant`` template:
    ``{tenant}`` substituted into the metric/counter names, the rule
    renamed ``template[tenant]`` and pinned to the tenant."""
    out = {k: v for k, v in spec.items() if k != "per_tenant"}
    out["name"] = f"{spec.get('name')}[{tenant}]"
    out["tenant"] = tenant
    for key in ("metric", "good", "bad"):
        v = out.get(key)
        if isinstance(v, str) and "{tenant}" in v:
            out[key] = v.replace("{tenant}", tenant)
    return out


def active_hosts(sample: Dict[str, float]) -> List[int]:
    """Host ids present in a sample's ``host:<k>:<metric>`` lanes,
    sorted — the ``per_host`` expansion source."""
    seen = set()
    for key in sample:
        m = _HOST_KEY_RE.match(key)
        if m is not None:
            seen.add(int(m.group("host")))
    return sorted(seen)


def expand_host_rule(spec: dict, host: int) -> dict:
    """One concrete rule spec from a ``per_host`` template:
    ``{host}`` substituted into the metric/counter names, the rule
    renamed ``template[host-K]`` and pinned to the host."""
    out = {k: v for k, v in spec.items() if k != "per_host"}
    out["name"] = f"{spec.get('name')}[host-{host}]"
    out["host"] = int(host)
    for key in ("metric", "good", "bad"):
        v = out.get(key)
        if isinstance(v, str) and "{host}" in v:
            out[key] = v.replace("{host}", str(host))
    return out


class Watchtower:
    """A RuleSet plus the evaluation loop state: feed samples, get
    transitions; thread-safe (serving feeds from handler threads).

    ``clock`` is injected for determinism and only consulted when a
    caller omits ``t`` — tests and the trace-replay path always pass
    explicit timestamps, so firings replay bit-identically.

    ``per_tenant`` template rules are expanded lazily against the
    tenants each sample shows as active, at most ``tenant_cap``
    concrete rules per template (first-seen wins once the cap is
    reached; an expanded rule persists for the watch's lifetime so a
    briefly-idle tenant keeps its alert history)."""

    def __init__(self, rules, *,
                 clock: Optional[Callable[[], float]] = None,
                 tenant_cap: int = TENANT_FAN_OUT_CAP,
                 host_cap: int = HOST_FAN_OUT_CAP):
        if isinstance(rules, RuleSet):
            self.ruleset = rules
        else:
            self.ruleset = RuleSet.from_specs(list(rules))
        self._clock = clock
        self._lock = threading.Lock()
        self._worst_fired: Optional[str] = None
        self.transitions_total = 0
        self.tenant_cap = max(1, int(tenant_cap))
        self.host_cap = max(1, int(host_cap))
        # template name -> {tenant -> concrete Rule}
        self._tenant_rules: Dict[str, Dict[str, Rule]] = {
            r.name: {} for r in self.ruleset if r.per_tenant}
        # template name -> {host -> concrete Rule}
        self._host_rules: Dict[str, Dict[int, Rule]] = {
            r.name: {} for r in self.ruleset if r.per_host}

    def _expand(self, sample: Dict[str, float]) -> None:
        """Lock held. Materialize concrete rules for newly-active
        tenants/hosts, within the per-template caps."""
        tenants = hosts = None
        for template in self.ruleset:
            if template.per_tenant:
                if tenants is None:
                    tenants = active_tenants(sample)
                expanded = self._tenant_rules[template.name]
                for ten in tenants:
                    if ten in expanded:
                        continue
                    if len(expanded) >= self.tenant_cap:
                        break
                    expanded[ten] = Rule(
                        expand_tenant_rule(template.spec, ten))
            elif template.per_host:
                if hosts is None:
                    hosts = active_hosts(sample)
                hexp = self._host_rules[template.name]
                for h in hosts:
                    if h in hexp:
                        continue
                    if len(hexp) >= self.host_cap:
                        break
                    hexp[h] = Rule(
                        expand_host_rule(template.spec, h))

    def _live_rules(self) -> List[Rule]:
        """Lock held. Evaluation order: concrete base rules, then the
        expansions of each template (templates themselves never see a
        sample — their metric names still hold the placeholder)."""
        out = [r for r in self.ruleset
               if not r.per_tenant and not r.per_host]
        for template in self.ruleset:
            if template.per_tenant:
                out.extend(self._tenant_rules[template.name].values())
            elif template.per_host:
                out.extend(self._host_rules[template.name].values())
        return out

    def observe(self, sample: Dict[str, float],
                t: Optional[float] = None) -> List[dict]:
        """Evaluate every rule against one sample at time ``t``;
        returns the state transitions (possibly empty)."""
        if t is None:
            if self._clock is None:
                import time
                t = time.monotonic()
            else:
                t = self._clock()
        out: List[dict] = []
        with self._lock:
            self._expand(sample)
            for rule in self._live_rules():
                tr = rule.observe(float(t), sample)
                if tr is not None:
                    out.append(tr)
                    self.transitions_total += 1
                    if tr["state"] == "firing":
                        self._worst_fired = worst_severity(
                            self._worst_fired, tr["severity"])
        return out

    def states(self) -> List[dict]:
        with self._lock:
            return [r.state() for r in self._live_rules()]

    def firing(self) -> List[dict]:
        return [s for s in self.states() if s["state"] == "firing"]

    def worst_firing(self) -> Optional[str]:
        worst: Optional[str] = None
        for s in self.firing():
            worst = worst_severity(worst, s["severity"])
        return worst

    @property
    def worst_fired(self) -> Optional[str]:
        """Worst severity that EVER fired during this watch — the
        ``dpsvm watch`` exit-code fact (a fired-and-cleared burn still
        failed the gate)."""
        with self._lock:
            return self._worst_fired

    def exit_code(self) -> int:
        return severity_exit_code(self.worst_fired)


# ---------------------------------------------------------------------
# default rule sets (docs/OBSERVABILITY.md "Watch & alerts")
# ---------------------------------------------------------------------

def default_serving_rules() -> List[dict]:
    """The serving SLO rules every ServingServer watches out of the
    box: a paging multi-window burn-rate alert on availability (504
    deadline misses burning the 99.9% objective's budget), a warning
    on sustained queue saturation (the shed ladder's territory —
    serving/budget.py), and two per-tenant templates — an
    availability burn scoped to one tenant's traffic and the
    ``fair_share`` noisy-neighbour warn — expanded over whatever
    tenants the live sample shows (docs/OBSERVABILITY.md "Per-tenant
    attribution")."""
    return [
        {"name": "availability-burn", "kind": "burn_rate",
         "severity": "page",
         "good": "requests", "bad": "deadline_504",
         "objective": 0.999,
         "fast_window_s": 60.0, "slow_window_s": 600.0,
         "threshold": 14.4, "clear_after_s": 60.0},
        {"name": "queue-saturation", "kind": "threshold",
         "severity": "warn",
         "metric": "queue_fill", "above": 0.8,
         "for_s": 5.0, "clear_after_s": 10.0},
        {"name": "tenant-availability-burn", "kind": "burn_rate",
         "severity": "warn", "per_tenant": True,
         "good": "tenant:{tenant}:requests",
         "bad": "tenant:{tenant}:deadline_504",
         "objective": 0.999,
         "fast_window_s": 60.0, "slow_window_s": 600.0,
         "threshold": 14.4, "clear_after_s": 60.0},
        {"name": "tenant-fair-share", "kind": "fair_share",
         "severity": "warn", "per_tenant": True,
         "window_s": 60.0, "share_above": 0.5, "min_tenants": 2,
         "for_s": 5.0, "clear_after_s": 30.0},
        # A thrashing model cache pages BEFORE p99 does: sustained
        # hydration faults mean the working set outgrew the HBM budget
        # (every fault is a cold start on someone's request), so rate
        # the fault counter like the training side rates compiles
        # (compile-storm). > 1 fault/s sustained over a minute is
        # churn, not warmup (docs/SERVING.md "Model fleet").
        {"name": "model-cache-thrash", "kind": "rate",
         "severity": "warn", "metric": "model_faults",
         "window_s": 60.0, "above": 1.0, "clear_after_s": 60.0},
    ]


def default_training_rules(
        ledger_records: Optional[Sequence[dict]] = None) -> List[dict]:
    """The training-side rules the driver watches when armed
    (``--watch-rules``/``--bundle-dir``): gap stagnation beyond the
    HealthMonitor's in-run window, a compile storm (steady state
    retraces nothing — solver/driver.py), shard-heartbeat age
    (straggler/hang), and a roofline_fraction drop against the
    perf-ledger median when a history exists."""
    return [
        {"name": "gap-stagnation", "kind": "stagnation",
         "severity": "warn", "metric": "gap",
         "window_s": 120.0, "clear_after_s": 0.0},
        {"name": "compile-storm", "kind": "rate", "severity": "warn",
         "metric": "compiles", "window_s": 60.0, "above": 0.5,
         "clear_after_s": 60.0},
        {"name": "shard-heartbeat", "kind": "threshold",
         "severity": "page", "metric": "heartbeat_age",
         "above": 120.0, "for_s": 0.0, "clear_after_s": 0.0},
        {"name": "roofline-drop", "kind": "drop_vs_baseline",
         "severity": "warn", "metric": "roofline_fraction",
         "baseline_case": "bench_headline",
         "baseline_metric": "roofline_fraction",
         "drop_pct": 25.0, "for_s": 0.0, "clear_after_s": 0.0},
    ]


def default_fleet_rules() -> List[dict]:
    """The multi-host group rules ``dpsvm fleet --watch`` and the
    straggler drill arm by default (docs/OBSERVABILITY.md "Fleet"):

    * a paging per-host heartbeat-stale threshold — expanded over the
      ``host:<k>:heartbeat_age_seconds`` lanes the federation layer
      builds, so a silent host pages by NAME;
    * a paging reform-storm rate — the group ``generation`` counter
      (every reformation increments it: resilience/hostgroup.py)
      climbing faster than ~3 reformations / 10 min means the group is
      thrashing, not recovering;
    * the warning ``skew`` rule on per-host iteration progress — one
      chunk of sustained lag (the drill plants 25-iteration chunks) is
      a straggler, the transient collective-boundary gap is not.
    """
    return [
        {"name": "host-heartbeat-stale", "kind": "threshold",
         "severity": "page", "per_host": True,
         "metric": "host:{host}:heartbeat_age_seconds",
         "above": 120.0, "for_s": 0.0, "clear_after_s": 0.0},
        {"name": "reform-storm", "kind": "rate", "severity": "page",
         "metric": "generation", "window_s": 600.0, "above": 0.005,
         "clear_after_s": 120.0},
        {"name": "iteration-skew", "kind": "skew", "severity": "warn",
         "metric": "n_iter", "window_s": 30.0, "lag_above": 20.0,
         "clear_after_s": 10.0},
    ]


def load_rules(source, *, default: str = "serving") -> RuleSet:
    """Resolve a rules argument: None -> the named default set
    (``serving``/``training``/``fleet``), a path ->
    ``RuleSet.from_file``, a list of specs / a RuleSet -> as-is."""
    if source is None:
        specs = {"serving": default_serving_rules,
                 "training": default_training_rules,
                 "fleet": default_fleet_rules}[default]()
        return RuleSet.from_specs(specs)
    if isinstance(source, RuleSet):
        return source
    if isinstance(source, str):
        return RuleSet.from_file(source)
    return RuleSet.from_specs(list(source))


# ---------------------------------------------------------------------
# sample flatteners: every watch source -> one canonical sample dict
# ---------------------------------------------------------------------
#
# The canonical vocabulary rules reference (documented in
# docs/OBSERVABILITY.md "Watch & alerts"):
#
#   serving:  requests, deadline_504, errors, rejected, queue_depth,
#             queue_fill, p99_ms, healthy_replicas, incidents
#   training: n_iter, gap, n_sv, compiles, compile_seconds,
#             heartbeat_age, roofline_fraction, iters_per_sec
#
# Raw exposition names are ALSO included (prefixless rules stay
# readable; power users can reference any exported series).

_PROM_CANON = {
    "dpsvm_serving_requests_total": "requests",
    "dpsvm_serving_deadline_504_total": "deadline_504",
    "dpsvm_serving_errors_total": "errors",
    "dpsvm_serving_rejected_total": "rejected",
    "dpsvm_serving_queue_depth": "queue_depth",
    "dpsvm_fleet_model_faults_total": "model_faults",
    "dpsvm_fleet_model_evictions_total": "model_evictions",
    "dpsvm_serving_replicas_healthy": "healthy_replicas",
    "dpsvm_incidents_total": "incidents",
    "dpsvm_train_iterations": "n_iter",
    "dpsvm_train_gap": "gap",
    "dpsvm_train_n_sv": "n_sv",
    "dpsvm_train_iters_per_sec": "iters_per_sec",
    "dpsvm_train_compiles_total": "compiles",
    "dpsvm_train_compile_seconds_total": "compile_seconds",
    "dpsvm_train_shard_heartbeat_age_seconds": "heartbeat_age",
}

_PROM_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{[^{}]*\})?\s+(?P<value>[^ ]+)\s*$")


def sample_from_prometheus(text: str) -> Dict[str, float]:
    """Flatten a Prometheus text exposition into a sample dict.
    Multiple series of one family collapse: ``_total`` counters sum
    (per-label traffic adds), everything else takes the max (the worst
    queue depth / heartbeat age is the alarming one)."""
    acc: Dict[str, List[float]] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if m is None:
            continue
        try:
            v = float(m.group("value").replace("+Inf", "inf")
                      .replace("-Inf", "-inf").replace("NaN", "nan"))
        except ValueError:
            continue
        acc.setdefault(m.group("name"), []).append(v)
    out: Dict[str, float] = {}
    for name, vals in acc.items():
        agg = sum(vals) if name.endswith("_total") else max(vals)
        out[name] = agg
        canon = _PROM_CANON.get(name)
        if canon:
            out[canon] = agg
    return out


def sample_from_metricsz_json(obj: dict) -> Dict[str, float]:
    """Flatten the serving server's JSON ``/metricsz`` blob into a
    sample (serving/server.py metrics())."""
    out: Dict[str, float] = {}
    for key in ("requests", "errors", "rejected", "deadline_504",
                "expired", "ejections", "rebuilds", "incidents_total"):
        v = obj.get(key)
        if isinstance(v, (int, float)):
            out["incidents" if key == "incidents_total" else key] = \
                float(v)
    lat = obj.get("latency_ms") or {}
    if isinstance(lat.get("p99"), (int, float)):
        out["p99_ms"] = float(lat["p99"])
    depth = 0.0
    for st in (obj.get("models") or {}).values():
        if isinstance(st, dict) and isinstance(
                st.get("queue_depth_rows"), (int, float)):
            depth += float(st["queue_depth_rows"])
    out["queue_depth"] = depth
    # model-fleet cache lanes (serving metrics() "model_cache") — the
    # model-cache-thrash rule's fault counter plus the eviction/
    # residency companions
    mc = obj.get("model_cache") or {}
    if isinstance(mc, dict):
        for key, canon in (("faults", "model_faults"),
                           ("evictions", "model_evictions"),
                           ("resident", "model_cache_resident"),
                           ("budget", "model_cache_budget")):
            v = mc.get(key)
            if isinstance(v, (int, float)):
                out[canon] = float(v)
    # per-tenant lanes (serving metrics() "tenants.per_tenant") —
    # the vocabulary the per_tenant rule templates reference
    per_tenant = (obj.get("tenants") or {}).get("per_tenant") or {}
    if isinstance(per_tenant, dict):
        for ten, st in per_tenant.items():
            if not isinstance(st, dict):
                continue
            for key, v in st.items():
                if isinstance(v, (int, float)):
                    out[f"tenant:{ten}:{key}"] = float(v)
    return out


def sample_from_chunk(rec: dict) -> Tuple[float, Dict[str, float]]:
    """(t, sample) from one run-trace ``chunk`` record — the
    trace-tail watch source (``dpsvm watch --trace``)."""
    t = float(rec.get("t", 0.0))
    out: Dict[str, float] = {}
    for key in ("n_iter", "gap", "n_sv"):
        v = rec.get(key)
        if isinstance(v, (int, float)):
            out[key] = float(v)
    ages = rec.get("shard_ages")
    if isinstance(ages, (list, tuple)) and ages:
        try:
            out["heartbeat_age"] = max(float(a) for a in ages)
        except (TypeError, ValueError):
            pass
    return t, out


# ---------------------------------------------------------------------
# snapshot-sequence tracking (the --metrics-out tail contract)
# ---------------------------------------------------------------------

SNAPSHOT_HEADER_RE = re.compile(
    r"^# dpsvm-snapshot seq=(?P<seq>\d+) unix=(?P<unix>[0-9.]+) "
    r"time=(?P<time>\S+)")


def parse_snapshot_header(text: str) -> Optional[dict]:
    """The ``--metrics-out`` header line (metrics.write_snapshot):
    ``# dpsvm-snapshot seq=N unix=T time=ISO``. None when absent (a
    pre-watch snapshot or a foreign exposition)."""
    first = text.split("\n", 1)[0]
    m = SNAPSHOT_HEADER_RE.match(first)
    if m is None:
        return None
    return {"seq": int(m.group("seq")),
            "unix": float(m.group("unix")),
            "time": m.group("time")}


class SnapshotFollower:
    """Tracks the monotonic ``seq`` of successive ``--metrics-out``
    snapshots so a tailing consumer detects missed and duplicate
    snapshots instead of silently mis-windowing its rates. ``note``
    returns (fresh, problems): ``fresh`` False on a duplicate (same
    snapshot re-read — do NOT re-evaluate rules on it), problems
    naming any gap."""

    def __init__(self):
        self.last_seq: Optional[int] = None
        self.missed = 0
        self.duplicates = 0

    def note(self, header: Optional[dict]) -> Tuple[bool, List[str]]:
        if header is None:
            return True, []         # headerless source: no tracking
        seq = header["seq"]
        problems: List[str] = []
        if self.last_seq is not None:
            if seq == self.last_seq:
                self.duplicates += 1
                return False, []
            if seq < self.last_seq:
                problems.append(
                    f"snapshot seq went backwards ({self.last_seq} -> "
                    f"{seq}): writer restarted")
            elif seq > self.last_seq + 1:
                gap = seq - self.last_seq - 1
                self.missed += gap
                problems.append(
                    f"missed {gap} snapshot(s) between seq "
                    f"{self.last_seq} and {seq}")
        self.last_seq = seq
        return True, problems

"""Run-trace JSONL format: writer, reader, schema validation.

One training run = one JSONL file (``SVMConfig.trace_out`` / the train
CLI's ``--trace-out``): a ``manifest`` record (what was asked for and on
what hardware), then ``chunk`` records at every host poll (the solver's
packed-stats transfer already carries n_iter/gap/SV-count/cache
counters, so tracing adds ZERO device->host transfers — see
solver/driver.py "Poll economics"), ``compile`` records whenever a
chunk program pays an XLA compile or retrace (docs/OBSERVABILITY.md
"Compile accounting"), optional ``event`` records (checkpoint /
program swap / shrink), and a final ``summary`` record.

This module is deliberately dependency-free (no jax import): the
``report``/``compare`` CLI subcommands and the schema self-check must
run without initializing any backend. The recorder that knows about
solvers lives in ``dpsvm_tpu.observability.record``.

The schema is versioned and validated by ``validate_trace`` — the same
function backs ``python -m dpsvm_tpu.telemetry --selfcheck`` (tier-1:
tests/test_observability.py), so a drifting producer fails loudly
instead of silently writing traces the report renderer can no longer
read. Version history:

* v1 — manifest/chunk/event/summary (PR 1). Still accepted: a v1
  manifest selects the v1 key sets and forbids v2-only record kinds.
* v2 — adds the ``compile`` record kind, per-chunk ``hbm`` watermarks
  and ``phase_counts``, and the summary's compile/HBM/FLOP facts
  (``n_compiles``, ``compile_seconds``, ``hbm_peak``, ``est_flops``).
  Additively (still v2): the elastic distributed events — ``desync``
  (cross-shard disagreement; must carry ``shards``), ``reshard``
  (resume re-sliced onto a different mesh; must carry ``from_shards``
  and ``to_shards``, and like ``rollback`` it legitimately rewinds the
  n_iter baseline to its checkpoint's iteration) and ``shard_lost``
  (a mesh shard died mid-run) — docs/DISTRIBUTED.md "Elastic
  training". Chunk records of distributed runs may carry
  ``shard_ages`` (per-shard heartbeat ages, seconds).
* v3 — adds the ``span`` record kind (request-scoped latency
  attribution in serving traces, docs/OBSERVABILITY.md "Spans") and
  the summary's ``est_bytes`` fact (cost-model bytes-accessed per
  iteration — the denominator of the arithmetic-intensity verdict in
  observability/roofline.py). Spans form per-request trees keyed by
  ``trace_id``: one root span per request (``parent`` null) whose
  children attribute the wall time to pipeline stages (queue wait,
  batch formation, device dispatch, ...). Ordering is part of the
  schema: a span ends at or after it starts, a child lies within its
  parent's interval, the root's direct children never sum past the
  root's own duration (the shortfall is the request's *unattributed*
  residual, reported — never hidden — by ``dpsvm report``), and a
  ``parent`` must name a span of the same ``trace_id``.
* v4 — tenant attribution (docs/OBSERVABILITY.md "Per-tenant
  attribution"): serving span roots and ``replica_compute`` children
  may carry ``tenant`` and ``model`` extras identifying who the
  request's time was spent for (``X-Tenant`` header / body ``tenant``
  field, default = model name). Purely additive — no new record
  kinds, no new required keys — so every v3 consumer reads a v4
  trace unchanged and v1/v2/v3 traces keep validating
  (tests/fixtures/trace_v{1,2,3}.jsonl).
* v5 — the merged-fleet trace (observability/merge.py,
  docs/OBSERVABILITY.md "Fleet"): N per-host traces of one group run
  combined into ONE clock-aligned stream where every record carries a
  ``host`` tag. Single-host producers keep writing v4
  (``TRACE_SCHEMA_VERSION``); only the merger stamps
  ``FLEET_SCHEMA_VERSION``. What changes at >= 5: chunk ``n_iter``
  monotonicity is checked PER HOST LANE (interleaved hosts progress
  independently; a rewind event tagged with ``host`` resets only that
  lane, an untagged ``reform`` resets the whole group), and each
  host's own final summary is demoted by the merger to a
  ``host_summary`` event so the one-summary rule still holds for the
  synthesized fleet summary. ``t`` stays globally non-decreasing —
  clock alignment is the merger's job, and a merged trace that
  rewinds time is a broken merge.
"""

from __future__ import annotations

import json
from typing import IO, Dict, List, Optional

TRACE_SCHEMA_VERSION = 4
#: schema stamped by observability/merge.py on a merged multi-host
#: trace — the only producer of v5; single-host writers stay at v4
FLEET_SCHEMA_VERSION = 5
SUPPORTED_SCHEMAS = (1, 2, 3, 4, 5)

# Required keys per record kind. Values may be null where noted in
# docs/OBSERVABILITY.md (e.g. env.device_kind on an uninitialized
# backend, hbm watermarks on CPU); presence is the contract.
MANIFEST_KEYS = ("schema", "version", "solver", "n", "d", "gamma",
                 "kernel", "mesh", "env", "config", "it0", "time")
CHUNK_KEYS_V1 = ("n_iter", "b_lo", "b_hi", "gap", "n_sv", "cache_hits",
                 "cache_misses", "rounds", "t", "phases")
CHUNK_KEYS = CHUNK_KEYS_V1 + ("phase_counts", "hbm")
EVENT_KEYS = ("event", "n_iter", "t")
COMPILE_KEYS = ("program", "seconds", "t")
SUMMARY_KEYS_V1 = ("converged", "n_iter", "iters", "iters_per_sec", "b",
                   "b_lo", "b_hi", "gap", "n_sv", "cache_hits",
                   "cache_misses", "cache_hit_rate", "train_seconds",
                   "phases", "t")
SUMMARY_KEYS_V2 = SUMMARY_KEYS_V1 + ("phase_counts", "n_compiles",
                                     "compile_seconds", "hbm_peak",
                                     "est_flops")
SUMMARY_KEYS = SUMMARY_KEYS_V2 + ("est_bytes",)
SPAN_KEYS = ("trace_id", "span_id", "parent", "name", "t_start",
             "t_end", "t")
KINDS_V1 = ("manifest", "chunk", "event", "summary")
KINDS_V2 = KINDS_V1 + ("compile",)
KINDS = KINDS_V2 + ("span",)

#: slack (seconds) for the span containment/sum checks: producers clamp
#: children to their root's interval at emission, so only float
#: rounding of the recorded 6-decimal timestamps needs absorbing.
SPAN_SLACK_S = 2e-6

# Events that may legitimately FOLLOW the summary record: emergency
# exit paths (the stall watchdog's flush_open_traces, a preemption
# signal landing between summary and close) stamp their marker into an
# already-summarized trace rather than lose it (docs/ROBUSTNESS.md).
# Everything else after a summary is trace corruption or interleaved
# writers — rejected by validate_trace.
TERMINAL_EVENTS = ("stall", "preempt")

# Events that rewind the chunk-record n_iter baseline to their own
# n_iter: `rollback` (checkpoint restored after divergence/corruption),
# `reshard` (resume re-sliced onto a different mesh — the checkpoint's
# iteration restarts the count on the new mesh), and `reform` (a host
# group shrank after a host loss and the resumed attempt restarts from
# the checkpoint's iteration — resilience/hostgroup.py).
REWIND_EVENTS = ("rollback", "reshard", "reform")

# Required extra keys per elastic/ingest/cascade event type (beyond
# EVENT_KEYS): a `desync` without its mesh size, a `reshard` without
# both mesh sizes, or a `quarantine` without the shard and reason is
# useless to every consumer, so the validator rejects them. Note the
# ingest vocabulary's asymmetry: `quarantine` marks a data shard
# dropped mid-run, and `ingest_resume` (a streaming train picking up
# from a checkpoint) REWINDS NOTHING — unlike rollback/reshard it is
# deliberately absent from REWIND_EVENTS, so a chunk record whose
# n_iter regresses after one is still trace corruption.
#
# Cascade events (solver/cascade.py, docs/APPROX.md "Cascade"):
# `screen` carries the kept/total row split, `polish` the repair-round
# index and subproblem size, `readmit` the round index and how many
# KKT violators were re-admitted. Ordering is part of the schema
# (validate_trace): `polish`/`readmit` may only follow a `screen`,
# `readmit` only a `polish`, and readmit round indices never decrease
# — a trace violating any of these was written by a broken (or
# interleaved) producer.
#
# Watch events (observability/slo.py, docs/OBSERVABILITY.md "Watch &
# alerts"): `alert` marks a rule's state TRANSITION (fire or clear —
# the `state` key distinguishes; rule/window/severity are required so
# a consumer can always tell WHICH contract broke and over what
# window), `incident` marks a flight-recorder bundle dump (carries the
# same identity plus `bundle`, the dumped directory).
#
# Live shard-log events (data/live.py + the continuous-learning loop,
# docs/DATA.md "Live shard logs" / docs/SERVING.md "Continuous
# learning"): `append_admitted` marks one durable appended shard
# entering a reader's view (shard + the generation that published it),
# `ingest_grow` marks a sweep boundary at which live training admitted
# new rows (the grown generation + row delta), and `refresh` marks the
# serving loop choosing its refresh flavor — `refresh_kind` MUST be
# "incremental" or "full" (validated below; a refresh of unknown kind
# is a broken producer, not a vocabulary extension). The flavor key is
# `refresh_kind`, not `kind`: every record's own `kind` field IS the
# record kind, and an event extra named `kind` would overwrite it at
# write time.
EVENT_EXTRA_KEYS = {
    "desync": ("shards",),
    "reshard": ("from_shards", "to_shards"),
    "quarantine": ("shard", "reason"),
    "screen": ("n_kept", "n_total"),
    "polish": ("round", "n_kept"),
    "readmit": ("round", "n_readmitted"),
    "alert": ("rule", "window", "severity"),
    "incident": ("rule", "window", "severity", "bundle"),
    "append_admitted": ("shard", "generation"),
    "ingest_grow": ("generation", "n_new_rows"),
    "refresh": ("refresh_kind",),
    # Multi-host recovery (resilience/hostgroup.py): a `host_lost`
    # without the dead host's id, or a `reform` without both group
    # sizes, cannot drive a playbook — rejected like their elastic
    # shard-level counterparts above.
    "host_lost": ("host_id",),
    "reform": ("from_hosts", "to_hosts"),
    # Model-fleet cache events (dpsvm_tpu/fleet/modelcache.py): a
    # `model_fault` without the model name and its measured cold start
    # can drive neither the thrash rule's attribution nor the
    # fleet_cold_start_p99_ms ledger row; a `model_evict` without the
    # name cannot explain the next fault.
    "model_fault": ("model", "cold_start_ms"),
    "model_evict": ("model",),
    # Grid-trainer events (dpsvm_tpu/fleet/grid.py): a `grid_cell`
    # without its coordinates and held-out score is useless to the
    # selection audit; `grid_winner` must at least name the cell.
    "grid_cell": ("c", "gamma", "holdout_acc"),
    "grid_winner": ("c", "gamma"),
}

#: the closed value set of the `refresh` event's `refresh_kind`
REFRESH_KINDS = ("incremental", "full")


class TraceWriter:
    """Append-one-JSON-record-per-line writer, flushed per record so a
    killed run still leaves a parseable partial trace."""

    def __init__(self, path: str):
        self.path = path
        self._fh: Optional[IO[str]] = open(path, "w")

    def write(self, record: dict) -> None:
        if self._fh is None:
            return
        self._fh.write(json.dumps(record) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "TraceWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_trace(path: str) -> List[dict]:
    """Parse a trace file into its records. Raises ValueError on a line
    that is not JSON (a truncated FINAL line — a run killed mid-write —
    is tolerated and dropped, matching the flush-per-record writer)."""
    records: List[dict] = []
    with open(path) as fh:
        lines = fh.read().splitlines()
    for i, raw in enumerate(lines):
        raw = raw.strip()
        if not raw:
            continue
        try:
            records.append(json.loads(raw))
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                break                   # torn final write of a dead run
            raise ValueError(f"{path}:{i + 1}: not a JSON record")
    return records


def _missing(record: dict, keys) -> List[str]:
    return [k for k in keys if k not in record]


def validate_trace(records: List[dict]) -> List[str]:
    """Schema check; returns a list of problems (empty = valid).

    Contract (acceptance bar of docs/OBSERVABILITY.md): exactly one
    leading manifest at a supported schema version (the version selects
    the per-kind key sets — v1 traces keep validating); >= 0 chunk
    records with monotone non-decreasing n_iter (per ``host`` lane in
    a v5 merged trace) and non-negative counters; ``t`` non-decreasing across every record that carries it;
    at most one summary, followed only by terminal events (stall /
    preempt — the emergency flush paths). A ``rollback`` event
    legitimately rewinds the run to its checkpoint's iteration
    (docs/ROBUSTNESS.md), so it resets the n_iter monotonicity
    baseline; nothing resets the ``t`` baseline — a time rewind means
    interleaved writers. Cascade stage events are ordered (see
    EVENT_EXTRA_KEYS): ``polish`` only after ``screen``, ``readmit``
    only after ``polish``, readmit rounds non-decreasing. Span records
    (v3) obey the per-request tree rules in _validate_spans."""
    errors: List[str] = []
    if not records:
        return ["empty trace (no records)"]
    head = records[0]
    schema = head.get("schema") if isinstance(head, dict) else None
    v1 = schema == 1
    if v1:
        kinds, chunk_keys, summary_keys = (
            KINDS_V1, CHUNK_KEYS_V1, SUMMARY_KEYS_V1)
    elif schema == 2:
        kinds, chunk_keys, summary_keys = (
            KINDS_V2, CHUNK_KEYS, SUMMARY_KEYS_V2)
    else:
        kinds, chunk_keys, summary_keys = KINDS, CHUNK_KEYS, SUMMARY_KEYS
    for i, r in enumerate(records):
        if not isinstance(r, dict) or r.get("kind") not in kinds:
            errors.append(f"record {i}: unknown kind "
                          f"{r.get('kind') if isinstance(r, dict) else r!r}")
    if head.get("kind") != "manifest":
        errors.append("record 0: trace must start with a manifest")
    else:
        if schema not in SUPPORTED_SCHEMAS:
            errors.append(f"manifest: schema {schema!r} not in "
                          f"supported {SUPPORTED_SCHEMAS}")
        miss = _missing(head, MANIFEST_KEYS)
        if miss:
            errors.append(f"manifest: missing keys {miss}")
    if sum(isinstance(r, dict) and r.get("kind") == "manifest"
           for r in records) > 1:
        errors.append("multiple manifest records")

    # chunk n_iter monotonicity baselines. Pre-v5 traces have exactly
    # one lane (key None); a v5 merged trace interleaves N hosts that
    # progress independently, so each ``host`` tag is its own lane.
    fleet = isinstance(schema, int) and not isinstance(schema, bool) \
        and schema >= 5
    prev_iter_by_lane: Dict[object, int] = {}
    prev_t = None
    summary_at = None
    saw_screen = False
    saw_polish = False
    prev_readmit_round = None
    spans: List[tuple] = []
    for i, r in enumerate(records):
        if not isinstance(r, dict):
            continue
        kind = r.get("kind")
        t = r.get("t")
        if isinstance(t, (int, float)):
            if prev_t is not None and t < prev_t:
                errors.append(f"record {i}: t {t} < previous {prev_t} "
                              "(time must be non-decreasing)")
            prev_t = t
        if summary_at is not None and not (
                kind == "event" and r.get("event") in TERMINAL_EVENTS):
            errors.append(f"record {i}: only terminal events "
                          f"({'/'.join(TERMINAL_EVENTS)}) may follow "
                          f"the final summary at record {summary_at}")
        if kind == "chunk":
            miss = _missing(r, chunk_keys)
            if miss:
                errors.append(f"record {i}: chunk missing keys {miss}")
                continue
            lane = r.get("host") if fleet else None
            base = prev_iter_by_lane.get(lane)
            if base is not None and r["n_iter"] < base:
                where = (f" in host {lane} lane"
                         if fleet and lane is not None else "")
                errors.append(f"record {i}: n_iter {r['n_iter']} < "
                              f"previous {base} (not monotone{where})")
            prev_iter_by_lane[lane] = r["n_iter"]
            for k in ("n_sv", "cache_hits", "cache_misses", "rounds"):
                if r[k] < 0:
                    errors.append(f"record {i}: {k} = {r[k]} < 0")
        elif kind == "event":
            miss = _missing(r, EVENT_KEYS)
            extra = EVENT_EXTRA_KEYS.get(r.get("event"), ())
            miss += _missing(r, extra)
            if miss:
                errors.append(f"record {i}: event missing keys {miss}")
            elif r.get("event") in REWIND_EVENTS:
                # The run restarted from a checkpoint at this iteration
                # (rollback), possibly on a different mesh (reshard).
                # In a merged fleet trace a host-tagged rewind resets
                # only that host's lane; an untagged one (a group-wide
                # reform) resets every lane seen so far.
                if fleet and "host" not in r:
                    prev_iter_by_lane = {
                        k: r["n_iter"] for k in prev_iter_by_lane}
                else:
                    prev_iter_by_lane[
                        r.get("host") if fleet else None] = r["n_iter"]
            elif r.get("event") == "refresh":
                if r.get("refresh_kind") not in REFRESH_KINDS:
                    errors.append(
                        f"record {i}: refresh_kind "
                        f"{r.get('refresh_kind')!r} not in "
                        f"{REFRESH_KINDS}")
            elif r.get("event") == "ingest_grow":
                if int(r.get("n_new_rows", 0) or 0) < 0:
                    errors.append(f"record {i}: ingest_grow "
                                  f"n_new_rows {r['n_new_rows']} < 0")
            elif r.get("event") == "screen":
                saw_screen = True
            elif r.get("event") == "polish":
                if not saw_screen:
                    errors.append(f"record {i}: polish event before "
                                  "any screen event (cascade stages "
                                  "are ordered)")
                saw_polish = True
            elif r.get("event") == "readmit":
                if not saw_polish:
                    errors.append(f"record {i}: readmit event before "
                                  "any polish event (re-admission "
                                  "repairs a polished model)")
                rnd = r["round"]
                if (prev_readmit_round is not None
                        and rnd < prev_readmit_round):
                    errors.append(
                        f"record {i}: readmit round {rnd} < previous "
                        f"{prev_readmit_round} (rounds must not "
                        "decrease)")
                prev_readmit_round = rnd
        elif kind == "compile":
            miss = _missing(r, COMPILE_KEYS)
            if miss:
                errors.append(f"record {i}: compile missing keys {miss}")
            elif r["seconds"] < 0:
                errors.append(f"record {i}: compile seconds "
                              f"{r['seconds']} < 0")
        elif kind == "span":
            miss = _missing(r, SPAN_KEYS)
            if miss:
                errors.append(f"record {i}: span missing keys {miss}")
            else:
                spans.append((i, r))
        elif kind == "summary":
            miss = _missing(r, summary_keys)
            if miss:
                errors.append(f"record {i}: summary missing keys {miss}")
            if summary_at is not None:
                errors.append(f"record {i}: second summary (first at "
                              f"record {summary_at})")
            else:
                summary_at = i
    errors += _validate_spans(spans)
    return errors


def _validate_spans(spans: List[tuple]) -> List[str]:
    """The per-request span-tree rules (schema v3, module docstring).

    ``spans`` is [(record_index, span_record), ...] with the per-record
    keys already checked. Grouping is by ``trace_id``, so the records
    of concurrent requests may interleave freely in the file — the
    tree rules apply within each request:

    * every span ends at or after it starts;
    * ``parent`` (when not null) names a ``span_id`` of the SAME
      trace_id — an orphan points at a request that never recorded
      its parent, i.e. a broken or interleaved producer;
    * a child's [t_start, t_end] lies within its parent's (producers
      clamp at emission; SPAN_SLACK_S absorbs timestamp rounding);
    * per request there is exactly one root (``parent`` null), and the
      root's DIRECT children — the pipeline stages — never sum past
      the root's own duration. The shortfall is the request's
      "unattributed" residual, a first-class fact `dpsvm report`
      prints; an overshoot means overlapping stage spans, which the
      serving producer never emits.
    """
    errors: List[str] = []
    by_trace: Dict[object, List[tuple]] = {}
    for i, r in spans:
        t0, t1 = r["t_start"], r["t_end"]
        if not (isinstance(t0, (int, float))
                and isinstance(t1, (int, float))):
            errors.append(f"record {i}: span t_start/t_end must be "
                          f"numbers, got {t0!r}/{t1!r}")
            continue
        if t1 < t0:
            errors.append(f"record {i}: span {r['name']!r} ends before "
                          f"it starts (t_end {t1} < t_start {t0})")
            continue
        by_trace.setdefault(r["trace_id"], []).append((i, r))
    for tid, group in by_trace.items():
        ids = {r["span_id"]: (i, r) for i, r in group}
        roots = [(i, r) for i, r in group if r["parent"] is None]
        if len(roots) != 1:
            errors.append(f"trace_id {tid!r}: {len(roots)} root span(s) "
                          "(parent=null) — every request records "
                          "exactly one")
            continue
        _ri, root = roots[0]
        child_sum = 0.0
        for i, r in group:
            p = r["parent"]
            if p is None:
                continue
            if p not in ids:
                errors.append(f"record {i}: span {r['name']!r} has "
                              f"orphan parent {p!r} (no such span_id "
                              f"in trace_id {tid!r})")
                continue
            _pi, parent = ids[p]
            if (r["t_start"] < parent["t_start"] - SPAN_SLACK_S
                    or r["t_end"] > parent["t_end"] + SPAN_SLACK_S):
                errors.append(
                    f"record {i}: span {r['name']!r} "
                    f"[{r['t_start']}, {r['t_end']}] escapes its "
                    f"parent {parent['name']!r} "
                    f"[{parent['t_start']}, {parent['t_end']}]")
            if p == root["span_id"]:
                child_sum += r["t_end"] - r["t_start"]
        root_dur = root["t_end"] - root["t_start"]
        if child_sum > root_dur + SPAN_SLACK_S * max(len(group), 1):
            errors.append(
                f"trace_id {tid!r}: direct children sum to "
                f"{child_sum:.6f}s > the root's {root_dur:.6f}s wall — "
                "stage spans overlap (attribution over 100%)")
    return errors

"""``python -m dpsvm_tpu.observability`` — the schema selfcheck /
validate entry point (identical to ``python -m dpsvm_tpu.telemetry``,
which remains the documented CI gate)."""

import sys

from dpsvm_tpu.observability import main

sys.exit(main())

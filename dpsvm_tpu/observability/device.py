"""Host-side device facts: HBM watermarks.

``device.memory_stats()`` is a host-side dictionary read — the runtime
already tracks allocator state, so sampling it at the existing poll
boundary costs ZERO device->host transfers (the same economics as the
packed-stats counters, docs/OBSERVABILITY.md). On backends without
allocator stats (CPU: ``memory_stats()`` returns None) every field is
null — presence of the keys is the schema contract, not their values.

The kernel-cache / precomputed-kernel footprint decides whether a
shape fits at all (PERF.md; the "Recipe for Fast Large-scale SVM
Training" point that memory budget, not iteration count, bounds
large-scale SVM training), so the high-water mark is a first-class
summary fact (``hbm_peak``).

jax is imported lazily: the report/compare CLI path must run without
initializing any backend.
"""

from __future__ import annotations

from typing import Optional


def memory_snapshot(device=None) -> dict:
    """{"in_use": bytes|None, "peak": bytes|None, "limit": bytes|None}
    for ``device`` (default: the first device). Never raises — a
    backend without stats reports nulls."""
    try:
        import jax

        dev = device if device is not None else jax.devices()[0]
        stats = dev.memory_stats()
    except Exception:
        stats = None
    if not stats:
        return {"in_use": None, "peak": None, "limit": None}

    def grab(*names) -> Optional[int]:
        for name in names:
            v = stats.get(name)
            if v is not None:
                return int(v)
        return None

    return {"in_use": grab("bytes_in_use"),
            "peak": grab("peak_bytes_in_use", "largest_alloc_size"),
            "limit": grab("bytes_limit", "bytes_reservable_limit")}

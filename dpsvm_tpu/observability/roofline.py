"""Hardware-roofline accounting: achieved vs peak, per phase.

The ROADMAP's "as fast as the hardware allows" is a slogan until the
denominator exists: this module turns the trace's cost-model facts
(``est_flops``/``est_bytes`` per iteration, iteration count, wall
seconds — all recorded by observability/compilewatch + record) plus a
per-backend peak table into

* an **achieved-fraction**: measured FLOP/s over the device's peak
  MXU FLOP/s, and measured bytes/s over peak HBM bandwidth;
* an **arithmetic-intensity verdict**: FLOPs per byte accessed vs the
  device's ridge point (peak FLOP/s ÷ peak bandwidth) — above the
  ridge the kernel is *compute-bound* (more FLOP/s needs better MXU
  utilization), below it *memory-bound* (more FLOP/s needs fewer HBM
  round-trips — exactly the case for the fused-Pallas work of ROADMAP
  item 5, which keeps the gradient vector in VMEM);
* a **per-phase split**: the host-loop phases that overlap device
  execution (dispatch / poll / measure) carry the verdict; pure host
  phases (checkpoint, ...) are labeled host-side — time the roofline
  cannot explain must be named, not absorbed.

"GPU-Accelerated Primal Learning" (arXiv:2008.03433) is the worked
example of why this number directs tuning effort: their speedups came
from knowing WHICH resource each phase saturated.

**Peak-table honesty**: peaks are public spec-sheet numbers (dense
bf16/f32 MXU FLOP/s and HBM bandwidth per chip), keyed by substring
match on jax's ``device_kind``. An unrecognized device — and CPU,
whose "peak" depends on the host — yields ``None``: every consumer
(``dpsvm report``, ``dpsvm doctor``, the bench rows) renders an
explicit *unknown/n/a* instead of inventing a denominator. The
fractions are *per chip*: a sharded run's est_flops is the per-chip
program, so the fraction reads as per-chip utilization.

**Estimate honesty**: ``est_bytes`` is XLA's cost-model "bytes
accessed" — LOGICAL traffic, an upper bound on physical HBM traffic
(accesses served from VMEM/caches count too). The bandwidth fraction
is therefore an upper bound and can exceed 100% on cache-friendly
kernels; the AI verdict errs toward memory-bound, which is the safe
direction for directing fusion work (ROADMAP item 5).

Dependency-free (stdlib only): report/compare/doctor must render on a
machine with no accelerator.
"""

from __future__ import annotations

from typing import Dict, List, Optional

#: Public per-chip peaks: (substring keys, canonical name,
#: dense-matmul peak FLOP/s, HBM bytes/s). Matching is
#: case-insensitive on jax's `device_kind` string ("TPU v5 lite",
#: "TPU v4", ...). FLOP/s is the bf16 MXU peak — the precision the
#: measured hot paths run at (docs/PERF.md "f32 vs bf16"); f32 peaks
#: are half, noted in the table consumers print.
PEAKS = (
    (("v5 lite", "v5e"), "TPU v5e", 197e12, 819e9),
    (("v5p", "v5 pod"), "TPU v5p", 459e12, 2765e9),
    (("v6 lite", "v6e", "trillium"), "TPU v6e", 918e12, 1640e9),
    (("v4",), "TPU v4", 275e12, 1228e9),
    (("v3",), "TPU v3", 123e12, 900e9),
    (("v2",), "TPU v2", 46e12, 700e9),
)

#: PhaseTimer phases that overlap device execution: the chunk program
#: runs while the host is dispatching the next chunk or blocking on
#: the stats poll (solver/driver.py "Poll economics"); bench.py's
#: measure window is the same thing under another name. Everything
#: else is host-side work the roofline cannot attribute to the chip.
DEVICE_PHASES = ("dispatch", "poll", "measure", "compile+warmup")


def peaks_for(device_kind: Optional[str]) -> Optional[dict]:
    """The peak row for a device kind, or None for unrecognized
    hardware (CPU included — an honest unknown, docs/OBSERVABILITY.md
    "Roofline")."""
    if not device_kind:
        return None
    low = str(device_kind).lower()
    for keys, name, flops, bw in PEAKS:
        if any(k in low for k in keys):
            return {"device": name, "peak_flops": flops,
                    "peak_hbm_Bps": bw,
                    "ridge_flops_per_byte": flops / bw}
    return None


def roofline_facts(*, est_flops: Optional[float],
                   est_bytes: Optional[float],
                   iters: Optional[float], seconds: Optional[float],
                   device_kind: Optional[str],
                   phases: Optional[Dict[str, float]] = None) -> dict:
    """The roofline digest rendered by ``dpsvm report``/``compare``
    and folded into bench/burst rows.

    Always returns the full key set (presence is the contract, like
    the trace schema): unknown hardware or a missing cost model yields
    nulls, never absent keys."""
    peaks = peaks_for(device_kind)
    out = {
        "device_kind": device_kind,
        "peaks": peaks,
        "achieved_flops_per_sec": None,
        "achieved_bytes_per_sec": None,
        "flops_fraction": None,
        "bandwidth_fraction": None,
        "arith_intensity": None,
        "verdict": None,
        "phases": {},
    }
    measurable = (est_flops and seconds and iters
                  and seconds > 0 and iters > 0)
    if measurable:
        out["achieved_flops_per_sec"] = est_flops * iters / seconds
    if est_bytes and seconds and iters and seconds > 0 and iters > 0:
        out["achieved_bytes_per_sec"] = est_bytes * iters / seconds
    if est_flops and est_bytes:
        out["arith_intensity"] = est_flops / est_bytes
    if peaks is not None:
        if out["achieved_flops_per_sec"] is not None:
            out["flops_fraction"] = (out["achieved_flops_per_sec"]
                                     / peaks["peak_flops"])
        if out["achieved_bytes_per_sec"] is not None:
            out["bandwidth_fraction"] = (out["achieved_bytes_per_sec"]
                                         / peaks["peak_hbm_Bps"])
        if out["arith_intensity"] is not None:
            out["verdict"] = (
                "compute-bound"
                if out["arith_intensity"]
                >= peaks["ridge_flops_per_byte"]
                else "memory-bound")
    # Per-phase split: device-overlapped phases inherit the kernel's
    # verdict (the chunk program IS what runs during them); host
    # phases are the roofline's blind spot and say so.
    total = sum((phases or {}).values())
    for name, sec in sorted((phases or {}).items(),
                            key=lambda kv: -kv[1]):
        device = name in DEVICE_PHASES
        out["phases"][name] = {
            "seconds": round(float(sec), 6),
            "share": round(sec / total, 4) if total > 0 else None,
            "kind": "device" if device else "host",
            "verdict": (out["verdict"] if device else "host-side"),
        }
    return out


def _fmt_flops(v: float) -> str:
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(v) < 1000 or unit == "P":
            return f"{v:,.1f} {unit}FLOP/s"
        v /= 1000
    return f"{v:,.1f} PFLOP/s"


def _fmt_bw(v: float) -> str:
    return f"{v / 1e9:,.1f} GB/s"


def render_roofline(rf: dict) -> List[str]:
    """The human lines ``dpsvm report`` prints under "roofline:"."""
    peaks = rf.get("peaks")
    if peaks is None:
        return [f"roofline: n/a (no peak table for device kind "
                f"{rf.get('device_kind')!r} — fractions need a known "
                "denominator; see dpsvm doctor)"]
    out = [f"roofline: {peaks['device']}: peak "
           f"{_fmt_flops(peaks['peak_flops'])} (bf16 MXU), "
           f"{_fmt_bw(peaks['peak_hbm_Bps'])} HBM, ridge "
           f"{peaks['ridge_flops_per_byte']:,.0f} FLOP/B"]
    if rf.get("flops_fraction") is not None:
        out.append(
            f"roofline: achieved "
            f"{_fmt_flops(rf['achieved_flops_per_sec'])} = "
            f"{rf['flops_fraction']:.1%} of peak"
            + (f"; {_fmt_bw(rf['achieved_bytes_per_sec'])} = "
               f"{rf['bandwidth_fraction']:.1%} of HBM bandwidth"
               if rf.get("bandwidth_fraction") is not None else ""))
    else:
        out.append("roofline: achieved fraction n/a (no cost-model "
                   "FLOP estimate or no measured window)")
    if rf.get("verdict") is not None:
        out.append(
            f"roofline: arithmetic intensity "
            f"{rf['arith_intensity']:,.1f} FLOP/B -> {rf['verdict']} "
            f"(ridge {peaks['ridge_flops_per_byte']:,.0f})")
    for name, p in rf.get("phases", {}).items():
        share = (f"{p['share']:.0%}" if p["share"] is not None
                 else "n/a")
        out.append(f"roofline:   {name:<14} {p['seconds']:8.3f} s "
                   f"{share:>5}  [{p['verdict']}]")
    return out


def doctor_lines(device_kinds) -> List[str]:
    """`dpsvm doctor`'s peak-table printout: the roofline denominators
    for every visible device kind, with an honest `unknown` for
    unrecognized hardware instead of a silent n/a later in report."""
    out: List[str] = []
    seen = []
    for kind in device_kinds or [None]:
        if kind in seen:
            continue
        seen.append(kind)
        peaks = peaks_for(kind)
        if peaks is None:
            out.append(f"{kind!r}: unknown device kind — no peak "
                       "table entry; `dpsvm report` will render "
                       "roofline fractions as n/a")
        else:
            out.append(
                f"{kind!r} -> {peaks['device']}: peak "
                f"{_fmt_flops(peaks['peak_flops'])} bf16 MXU "
                f"(f32 ~ half), {_fmt_bw(peaks['peak_hbm_Bps'])} HBM, "
                f"ridge {peaks['ridge_flops_per_byte']:,.0f} FLOP/B")
    return out


def fraction(*, est_flops: Optional[float], iters: Optional[float],
             seconds: Optional[float],
             device_kind: Optional[str]) -> Optional[float]:
    """The one-number ledger column (``roofline_fraction`` on
    bench/burst rows): achieved/peak FLOP/s, or None when either side
    is unknown — `dpsvm perf gate` skips null readings, so CPU rows
    never gate on a made-up denominator."""
    rf = roofline_facts(est_flops=est_flops, est_bytes=None,
                        iters=iters, seconds=seconds,
                        device_kind=device_kind)
    f = rf["flops_fraction"]
    return round(f, 6) if f is not None else None

"""Request-scoped span trees: where one serving request's time went.

`/metricsz` answers "how slow are requests" (p50/p95/p99 over a
window) but not "WHY was this one slow" — a 48 ms request that spent
46 ms queued needs a different fix (admission control, more replicas)
than one that spent 46 ms in the device dispatch (bigger buckets,
hedging). "Parallel SVMs in Practice" (arXiv:1404.1066) puts exactly
this per-request operational visibility on the deployment-critical
list. This module is the recorder the serving stack threads through
itself (docs/OBSERVABILITY.md "Spans"):

* the HTTP layer opens one ``RequestSpans`` per SAMPLED request
  (``dpsvm serve --trace-sample-rate``) — the root ``request`` span;
* each pipeline stage brackets itself: ``admission`` (parse +
  validate) in the handler, ``queue_wait``/``batch_form``/
  ``device_dispatch`` in the micro-batcher (serving/batcher.py),
  ``replica_compute`` + the hedge/redispatch markers in the replica
  pool (serving/pool.py), ``respond`` back in the handler;
* at request completion ``finish()`` closes the tree — clamping every
  child into the root's interval and force-ending still-open stages
  at the root end, so a request that died waiting (504) shows WHERE
  it was waiting instead of losing the span — and the server emits
  the spans as ``span`` records (schema v3+) into the serving trace
  (observability/record.RunTrace.span).

Everything here is stdlib (perf_counter + a lock): recording a span is
two clock reads and a list append, which is what keeps the sampled
steady-state overhead inside the pinned bound (tests/test_spans.py).

The tree invariants the schema validator enforces
(observability/schema._validate_spans) are established HERE: children
clamped inside the root, stage spans sequential so the root's direct
children can never sum past its wall time — the shortfall is the
request's *unattributed* residual, reported by ``dpsvm report``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

#: the root span's name — one per request, parent=null.
ROOT = "request"


class Span:
    """One named interval (absolute perf_counter endpoints; ``end`` is
    None while open). ``extra`` lands verbatim on the trace record."""

    __slots__ = ("span_id", "parent", "name", "start", "end", "extra")

    def __init__(self, span_id: int, parent: Optional[int], name: str,
                 start: float, extra: dict):
        self.span_id = span_id
        self.parent = parent
        self.name = name
        self.start = start
        self.end: Optional[float] = None
        self.extra = extra

    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start


class RequestSpans:
    """One request's span tree, built concurrently by the handler
    thread, the batcher worker and the pool workers (thread-safe).

    ``start(name, parent=...)`` opens a child span — ``parent`` names
    an earlier span (default: the root) and is resolved by name, last
    opened wins, so the pool can hang ``replica_compute`` under
    whichever ``device_dispatch`` is current without holding a
    reference across the queue. ``start`` returns the Span; enders
    that might race a same-named sibling (hedged computes) pass the
    Span back to ``end`` instead of the name."""

    __slots__ = ("trace_id", "_lock", "_spans", "_by_name", "_next_id",
                 "finished", "tenant", "model")

    def __init__(self, trace_id, first_stage: Optional[str] = None):
        self.trace_id = trace_id
        self._lock = threading.Lock()
        self._next_id = 0
        self._spans: List[Span] = []
        self._by_name: Dict[str, Span] = {}
        self.finished = False
        # Tenant/model identity (schema v4, docs/OBSERVABILITY.md
        # "Per-tenant attribution"): set by the HTTP layer right after
        # it parses the request body, read by every downstream stage
        # (the pool stamps them on replica_compute spans) and merged
        # into the root's extras at finish — the tree IS the carrier,
        # so no pipeline signature needs a tenant parameter.
        self.tenant: Optional[str] = None
        self.model: Optional[str] = None
        root = self._open(ROOT, parent_id=None, extra={})
        if first_stage:
            # first stage opens at the root's exact timestamp: a
            # thread preempted between "create tree" and "bracket
            # stage 1" would otherwise leak the stall into the
            # unattributed residual
            self._open(first_stage, parent_id=root.span_id, extra={},
                       at=root.start)

    def _open(self, name: str, parent_id: Optional[int],
              extra: dict, at: Optional[float] = None) -> Span:
        sp = Span(self._next_id, parent_id, name,
                  time.perf_counter() if at is None else at, extra)
        self._next_id += 1
        self._spans.append(sp)
        self._by_name[name] = sp
        return sp

    @property
    def root(self) -> Span:
        return self._spans[0]

    def start(self, name: str, parent: str = ROOT, **extra) -> Span:
        with self._lock:
            psp = self._by_name.get(parent)
            pid = psp.span_id if psp is not None else 0
            sp = self._open(name, pid, extra)
            if pid == 0:
                # The root's direct children are SEQUENTIAL pipeline
                # stages: starting the next stage closes the previous
                # one at exactly this instant, so no time can fall
                # into the cracks between two brackets (the residual
                # stays what is genuinely unattributed). Deeper spans
                # (hedged replica computes) may overlap and are never
                # auto-closed.
                for prev in self._spans[1:-1]:
                    if prev.parent == 0 and prev.end is None:
                        prev.end = sp.start
            return sp

    def end(self, span, **extra) -> None:
        """Close a span by name (the common sequential stages) or by
        the Span object ``start`` returned (concurrent same-named
        spans, e.g. hedged computes). Unknown name / already-ended =
        no-op: enders must never throw into the serving path."""
        now = time.perf_counter()
        with self._lock:
            sp = (self._by_name.get(span) if isinstance(span, str)
                  else span)
            if sp is None or sp.end is not None:
                return
            sp.end = now
            if extra:
                sp.extra.update(extra)

    def mark(self, name: str, parent: str = ROOT, **extra) -> None:
        """Zero-length marker span (hedge fired/won, redispatch):
        a point event that still rides the span tree."""
        with self._lock:
            psp = self._by_name.get(parent)
            sp = self._open(name, psp.span_id if psp else 0, extra)
            sp.end = sp.start

    def finish(self, **extra) -> List[Span]:
        """End the root (merging ``extra`` — status, row count,
        deadline facts), close the tree and return its spans.

        Still-open children are force-ended at the root's end rather
        than dropped: a request that blew its deadline mid-queue keeps
        its ``queue_wait`` span to the bitter end — that IS the
        attribution. Every child is then clamped into its PARENT's
        (already clamped) interval — creation order guarantees parents
        precede children — so the schema's containment rule holds
        exactly; a hedged loser's ``replica_compute`` that outlives
        the request's dispatch stage is truncated to its overlap with
        it (the tail ran, but no longer on this request's clock). The
        root gains ``unattributed_ms``: root wall minus the sum of its
        direct children — the residual `dpsvm report` prints (never
        silently absorbed into a stage)."""
        now = time.perf_counter()
        with self._lock:
            if self.finished:
                return list(self._spans)
            self.finished = True
            root = self._spans[0]
            root.end = now
            if extra:
                root.extra.update(extra)
            # tenant/model land on the ROOT span (schema v4) on every
            # exit path — 200s and the handler's error back-stop alike
            # — so attribution never depends on how the request died.
            if self.tenant is not None:
                root.extra.setdefault("tenant", self.tenant)
            if self.model is not None:
                root.extra.setdefault("model", self.model)
            child_sum = 0.0
            clamped = {root.span_id: root}
            for sp in self._spans[1:]:
                if sp.end is None:
                    sp.end = now
                    sp.extra.setdefault("cut_at_root_end", True)
                parent = clamped.get(sp.parent, root)
                new_start = min(max(sp.start, parent.start), parent.end)
                new_end = min(max(sp.end, parent.start), parent.end)
                if new_end < sp.end - 1e-9:
                    sp.extra.setdefault("cut_at_parent_end", True)
                sp.start, sp.end = new_start, new_end
                clamped[sp.span_id] = sp
                if sp.parent == root.span_id:
                    child_sum += sp.end - sp.start
            root.extra["unattributed_ms"] = round(
                max(root.end - root.start - child_sum, 0.0) * 1000.0, 3)
            return list(self._spans)

    def breakdown(self) -> Dict[str, float]:
        """{stage name: milliseconds} for the root's direct children
        (+ ``total_ms`` and ``unattributed_ms``) — the per-request
        view the HTTP response returns under ``X-Trace-Spans`` and the
        loadgen knee rows aggregate. Only meaningful after finish()."""
        with self._lock:
            root = self._spans[0]
            if root.end is None:
                return {}
            out: Dict[str, float] = {
                "total_ms": round((root.end - root.start) * 1000.0, 3)}
            for sp in self._spans[1:]:
                if sp.parent == root.span_id and sp.end is not None:
                    out[sp.name] = round(
                        out.get(sp.name, 0.0)
                        + (sp.end - sp.start) * 1000.0, 3)
            ua = root.extra.get("unattributed_ms")
            if ua is not None:
                out["unattributed_ms"] = ua
            return out

    def emit_into(self, trace) -> int:
        """Write every span as a schema span record into ``trace`` (an
        observability/record.RunTrace). Returns records written. The
        caller finishes first; an unfinished tree emits nothing (a
        half-built tree would violate the schema it is supposed to
        satisfy)."""
        if not self.finished:
            return 0
        with self._lock:
            spans = list(self._spans)
        for sp in spans:
            trace.span(trace_id=self.trace_id, span_id=sp.span_id,
                       parent=sp.parent, name=sp.name,
                       t_start=sp.start, t_end=sp.end, **sp.extra)
        return len(spans)


def should_sample(index: int, rate: float) -> bool:
    """Deterministic stride sampling: request ``index`` (0-based
    admission counter) is sampled iff the cumulative quota
    ``floor((i+1)*rate)`` advances at it. rate=1 samples everything,
    rate=0 nothing, rate=0.25 every 4th — evenly spread with no RNG,
    so tests and replays see the same picks."""
    r = min(max(float(rate), 0.0), 1.0)
    if r <= 0.0:
        return False
    return int((index + 1) * r) > int(index * r)

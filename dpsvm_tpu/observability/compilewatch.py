"""Compile/retrace accounting for the solvers' chunk programs.

XLA compilation is one of the two costs that dominate TPU wall-clock
in this codebase (docs/PERF.md: ~0.5-3 s client compile plus ~3 s
server-side program load per program on the tunneled chip; every
``grow_working_set`` swap and every shrinking-manager capacity bucket
is its own program). PR 1's RunTrace was blind to it — a run that
spent 12 s compiling and 3 s iterating traced exactly like the
reverse. This module makes every compile an observable fact:

* ``instrument(fn, program)`` wraps a jitted chunk runner. Each call
  compares the jit's tracing-cache size before and after: growth means
  THIS call paid a trace+lower+compile, and the call's wall seconds
  are (to within one async dispatch, microseconds) the compile cost.
  A warm cache — e.g. the lru_cached runner builders re-serving a
  previous run's program, or the persistent XLA compile cache — is
  correctly observed as zero compiles.
* detected compiles are appended to a process-global log; the host
  driver (and the shrink manager / bench harnesses) ``drain()`` it
  into the run trace as ``compile`` records at the next poll
  boundary. The log is process-global on purpose: compiles fire
  inside solver internals that know nothing about traces, and the
  queue-then-drain pattern matches the driver's pending-event queue.
* the first compile per program also records a cost_analysis FLOPs
  estimate (``fn.lower(avals).cost_analysis()`` — host-side tracing
  only, no second backend compile). On the chunk runners the
  while-loop body is counted ONCE, so the number reads as
  ~FLOPs-per-iteration; ``report`` multiplies by the iteration count
  for its achieved-FLOP/s line (docs/OBSERVABILITY.md).

No jax import at module level: the report/compare CLI path imports
the observability package without initializing any backend.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

_LOG: List[dict] = []
_LOCK = threading.Lock()
# Fallback signature sets for callables without jit's _cache_size,
# keyed by id of the underlying callable (shared across instrument()
# wrappers of the same runner, mirroring the jit cache's lifetime).
_SEEN: Dict[int, set] = {}


def _signature(args, kwargs) -> tuple:
    """Hashable (shape, dtype) tree of a call's arguments — the retrace
    key. Non-array leaves (python scalars, static strings) ride as
    repr, close enough to jit's static-argument hashing for
    accounting."""
    import jax

    def leaf(a):
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None and dtype is not None:
            return (tuple(shape), str(dtype))
        return repr(a)

    return tuple(leaf(a)
                 for a in jax.tree_util.tree_leaves((args, kwargs)))


def _cache_size(fn) -> Optional[int]:
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:
        return None


def _cost_estimates(fn, args, kwargs) -> tuple:
    """cost_analysis ('flops', 'bytes accessed') of the program ``fn``
    compiles for this call signature, via a host-side re-lower on
    avals (no backend compile). Nones when the backend/abstraction
    declines — the trace records the facts as null rather than failing
    the run. The pair is the arithmetic intensity the roofline verdict
    divides (observability/roofline.py)."""
    try:
        import jax

        def aval(a):
            shape = getattr(a, "shape", None)
            dtype = getattr(a, "dtype", None)
            if shape is not None and dtype is not None:
                return jax.ShapeDtypeStruct(tuple(shape), dtype)
            return a

        specs = jax.tree_util.tree_map(aval, args)
        kspecs = jax.tree_util.tree_map(aval, kwargs)
        ca = fn.lower(*specs, **kspecs).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = ca or {}
        flops = ca.get("flops")
        nbytes = ca.get("bytes accessed")
        return (float(flops) if flops is not None else None,
                float(nbytes) if nbytes is not None else None)
    except Exception:
        return None, None


def observe(program: str, seconds: float, *,
            signature: Optional[str] = None,
            flops: Optional[float] = None,
            bytes: Optional[float] = None) -> None:
    """Append one compile observation (public so harnesses that compile
    outside jit — e.g. explicit AOT paths — can report too)."""
    with _LOCK:
        _LOG.append({"program": str(program),
                     "seconds": float(seconds),
                     "signature": signature,
                     "flops": flops,
                     "bytes": bytes,
                     "wall": time.perf_counter()})


def drain() -> List[dict]:
    """Take every pending compile observation (oldest first). The
    driver calls this at poll boundaries; a consumer with no trace
    still drains so observations can never leak into the next run."""
    with _LOCK:
        out, _LOG[:] = _LOG[:], []
    return out


def pending() -> int:
    with _LOCK:
        return len(_LOG)


def instrument(fn: Callable, program: str, *,
               jitted: Any = None) -> Callable:
    """Wrap a (jitted) chunk runner so every compile/retrace it pays is
    logged. ``jitted`` points at the underlying jit object when ``fn``
    itself is a partial/closure over it (the fused path); it is the
    thing whose tracing cache is watched and whose ``lower`` provides
    the FLOPs estimate."""
    import functools

    target = jitted if jitted is not None else fn
    lowerable = target if hasattr(target, "lower") else None
    # The fused path wraps a partial over its jit (the statics live in
    # the partial's keywords); re-lowering needs them back.
    static_kwargs = (dict(fn.keywords)
                     if isinstance(fn, functools.partial)
                     and lowerable is not None and fn.func is lowerable
                     else {})
    cost_seen: Dict[str, tuple] = {}

    def wrapped(*args, **kwargs):
        before = _cache_size(target)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        after = _cache_size(target)
        if before is None or after is None:
            # No jit cache probe on this callable: fall back to a
            # signature set keyed on the callable's id, shared by every
            # wrapper of the same runner so a program warmed by a
            # previous run is still observed as zero compiles.
            seen = _SEEN.setdefault(id(target), set())
            sig = _signature(args, kwargs)
            compiled = sig not in seen
            seen.add(sig)
        else:
            compiled = after > before
        if compiled:
            seconds = time.perf_counter() - t0
            sig_s = None
            try:
                sig_s = str(_signature(args, kwargs))
            except Exception:
                pass
            flops = nbytes = None
            if lowerable is not None and program not in cost_seen:
                # One estimate per program name: re-lowering is cheap
                # (host tracing only) but not free, and a retrace of
                # the same program has the same per-iteration cost.
                flops, nbytes = _cost_estimates(
                    lowerable, args, {**static_kwargs, **kwargs})
                cost_seen[program] = (flops, nbytes)
            observe(program, seconds, signature=sig_s, flops=flops,
                    bytes=nbytes)
        return out

    wrapped.__name__ = f"observed[{program}]"
    return wrapped

"""Unified run observability: traces, device/compiler accounting,
reports, live follow, cross-run comparison.

Grown out of ``dpsvm_tpu.telemetry`` (which remains as a re-exporting
shim): PR 1's RunTrace answered *what the host loop did*; this package
adds the device/compiler layer — the two things that actually dominate
TPU wall-clock here are XLA compilation (every growth program swap and
working-set regrow recompiles the chunk runner; PERF.md attributes
0.5-3 s per first-compile on the tunneled chip) and device memory (the
kernel-cache / precomputed-kernel footprint decides whether a shape
fits at all).

Layout (docs/OBSERVABILITY.md):

* ``schema``       — JSONL record shapes + ``validate_trace`` (v2; v1
                     still validates). Dependency-free.
* ``record``       — the ``RunTrace`` recorder every producer writes
                     through (driver, shrink manager, benchmarks).
* ``compilewatch`` — compile/retrace detection around the solvers'
                     chunk runners; drained into traces at poll
                     boundaries.
* ``device``       — host-side HBM watermark sampling (None-safe on
                     CPU).
* ``report``       — digest + ASCII report + ``--follow`` live tail.
* ``compare``      — two-trace delta table + regression gate
                     (``dpsvm compare``).
* ``metrics``      — process-wide metric registry (counters / gauges /
                     histograms), Prometheus text exposition +
                     grammar validator, the training-poll feeder and
                     the ``--metrics-port`` sidecar.
* ``profiler``     — auto-windowed ``jax.profiler`` capture with
                     phase-named TraceAnnotation spans and the
                     ``dpsvm profile summarize`` reconciliation
                     sidecar.
* ``ledger``       — persistent append-only perf ledger + the
                     ``dpsvm perf gate`` historical regression check.
* ``merge``        — cross-host trace merge for multi-host group runs
                     (``trace_h<K>`` families -> one host-tagged
                     schema-v5 timeline; ``dpsvm report`` renders the
                     per-host lanes).
* ``fleet``        — metrics federation over N hosts' snapshots /
                     live endpoints + the fleet watch sample
                     (``dpsvm fleet``).

Importing this package initializes no backend: jax is imported lazily
inside the functions that need it (compilewatch, device, profiler), so
``dpsvm report``/``compare``/``perf`` run on a machine with no
accelerator.
"""

from __future__ import annotations

from typing import List, Optional

from dpsvm_tpu.observability.compare import (compare_paths,
                                             compare_traces,
                                             regressions,
                                             render_compare)
from dpsvm_tpu.observability.record import (SOLVER_NAMES, RunTrace,
                                            flush_open_traces)
from dpsvm_tpu.observability.report import (follow_trace, host_lanes,
                                            load_trace,
                                            load_trace_auto,
                                            render_report,
                                            resolve_trace_path,
                                            span_attribution,
                                            summarize_trace,
                                            trace_facts)
from dpsvm_tpu.observability.metrics import (MetricsRegistry,
                                             default_registry,
                                             validate_exposition)
from dpsvm_tpu.observability.schema import (TRACE_SCHEMA_VERSION,
                                            TraceWriter, read_trace,
                                            validate_trace)

__all__ = [
    "TRACE_SCHEMA_VERSION", "TraceWriter", "read_trace",
    "validate_trace", "RunTrace", "SOLVER_NAMES", "flush_open_traces",
    "load_trace", "load_trace_auto", "render_report",
    "summarize_trace", "trace_facts",
    "span_attribution", "host_lanes", "resolve_trace_path",
    "follow_trace",
    "compare_traces", "compare_paths", "render_compare", "regressions",
    "MetricsRegistry", "default_registry", "validate_exposition",
    "selfcheck", "main",
]

# A v1 trace embedded verbatim: the schema gate asserts that old
# traces keep validating after every v2+ change (the committed file
# fixture lives at tests/fixtures/trace_v1.jsonl; this inline copy
# makes the CLI selfcheck self-contained).
V1_SAMPLE_RECORDS: List[dict] = [
    {"kind": "manifest", "schema": 1, "version": "0.0", "solver": "smo",
     "n": 100, "d": 4, "gamma": 0.25,
     "kernel": {"kind": "rbf", "gamma": 0.25, "coef0": 0.0, "degree": 3},
     "mesh": {"shards": 1, "shard_x": True},
     "env": {"backend": "cpu", "device_kind": "host", "device_count": 1},
     "config": {}, "it0": 0, "time": "2026-01-01T00:00:00+0000"},
    {"kind": "chunk", "n_iter": 512, "b_lo": 0.5, "b_hi": -0.5,
     "gap": 1.0, "n_sv": 10, "cache_hits": 0, "cache_misses": 0,
     "rounds": 0, "t": 0.1, "phases": {"dispatch": 0.01, "poll": 0.05}},
    {"kind": "event", "event": "checkpoint", "n_iter": 512, "t": 0.2},
    {"kind": "summary", "converged": True, "n_iter": 900, "iters": 900,
     "iters_per_sec": 3000.0, "b": 0.1, "b_lo": 0.1004, "b_hi": 0.0996,
     "gap": 0.0008, "n_sv": 12, "cache_hits": 0, "cache_misses": 0,
     "cache_hit_rate": None, "train_seconds": 0.3,
     "phases": {"dispatch": 0.02, "poll": 0.2}, "t": 0.31},
]


def selfcheck(tmp_dir: Optional[str] = None) -> List[str]:
    """Produce a synthetic v2 trace through the real writer, then run
    it through the real validator, renderer and comparator; also
    validate the embedded v1 sample. Returns problems (empty = OK).
    Tier-1 (tests/test_observability.py) and ``python -m
    dpsvm_tpu.telemetry --selfcheck`` both call this, so a schema drift
    between producer and validator fails loudly in CI."""
    import os
    import tempfile

    problems = []
    with tempfile.TemporaryDirectory(dir=tmp_dir) as td:
        path = os.path.join(td, "selfcheck.jsonl")
        tr = RunTrace(path, config={"kernel": "rbf", "shards": 2,
                                    "shard_x": True, "coef0": 0.0,
                                    "degree": 3},
                      n=1000, d=32, gamma=0.5, solver="smo", it0=0,
                      env={"backend": "cpu", "device_kind": "host",
                           "device_count": 2})
        tr.compile(program="smo-chunk", seconds=1.25,
                   signature="((1000,), float32)", flops=2.0e6)
        for i, gap in enumerate((1.5, 0.3, 0.0009)):
            tr.chunk(n_iter=(i + 1) * 512, b_lo=gap / 2, b_hi=-gap / 2,
                     n_sv=100 * (i + 1), cache_hits=i * 10,
                     cache_misses=i * 20, rounds=i,
                     phases={"dispatch": 0.1 * i, "poll": 0.2 * i},
                     phase_counts={"dispatch": i + 1, "poll": i + 1},
                     hbm={"in_use": 1 << 28, "peak": (1 << 28) + i,
                          "limit": 16 << 30})
        tr.event("checkpoint", n_iter=1024)
        tr.summary(converged=True, n_iter=1536, b=0.0, b_lo=0.00045,
                   b_hi=-0.00045, n_sv=300, train_seconds=1.5,
                   cache_hits=20, cache_misses=40,
                   phases={"dispatch": 0.3, "poll": 0.6},
                   phase_counts={"dispatch": 3, "poll": 3})
        tr.close()
        try:
            records = load_trace(path)
        except ValueError as e:
            return [str(e)]
        digest = summarize_trace(records)
        if digest["n_chunks"] != 3 or digest["summary"] is None:
            problems.append(f"digest mismatch: {digest['n_chunks']} "
                            "chunks or missing summary")
        s = digest["summary"]
        facts = {k: (s or {}).get(k)
                 for k in ("n_compiles", "compile_seconds",
                           "est_flops", "hbm_peak")}
        if facts != {"n_compiles": 1, "compile_seconds": 1.25,
                     "est_flops": 2.0e6, "hbm_peak": (1 << 28) + 2}:
            problems.append(f"summary device facts drifted: {facts}")
        text = render_report(records)
        for needle in ("run: smo", "converged at iter 1,536",
                       "hit rate 33.3%", "checkpoint@1,024",
                       "compiles: 1 program(s)", "hbm peak:",
                       "throughput: ~"):
            if needle not in text:
                problems.append(f"report rendering lost {needle!r}")
        # A trace must compare cleanly against itself with zero
        # regressions at any threshold.
        cmp = compare_traces(records, records)
        if regressions(cmp, 0.001):
            problems.append("self-comparison reported a regression: "
                            f"{regressions(cmp, 0.001)}")
        render_compare(cmp)
    # v1 back-compat: the embedded sample must keep validating and
    # rendering (hbm/compile facts absent, not invented).
    v1_errors = validate_trace(V1_SAMPLE_RECORDS)
    if v1_errors:
        problems.append(f"v1 sample no longer validates: {v1_errors}")
    else:
        v1_text = render_report(V1_SAMPLE_RECORDS)
        if "hbm peak" in v1_text or "compiles:" in v1_text:
            problems.append("v1 rendering invented v2 device facts")
    problems += _selfcheck_metrics()
    problems += _selfcheck_ledger(tmp_dir)
    problems += _selfcheck_spans(tmp_dir)
    problems += _selfcheck_roofline(tmp_dir)
    problems += _selfcheck_watch(tmp_dir)
    problems += _selfcheck_tenants(tmp_dir)
    problems += _selfcheck_fleet(tmp_dir)
    return problems


def _selfcheck_watch(tmp_dir: Optional[str] = None) -> List[str]:
    """The continuous-watch gate (docs/OBSERVABILITY.md "Watch &
    alerts"): rule round-trip -> a planted burn against an injectable
    clock fires the multi-window rule and ONLY then -> flight-recorder
    bundle dump -> the bundle re-validates (embedded trace schema
    v3, exposition grammar) -> the alert clears after the burn stops
    — plus the live half: a fault-injected slow replica turns real
    HTTP requests into a 504 storm that must fire the serving
    watchtower, dump a bundle and clear once the fault lifts."""
    import json
    import os
    import tempfile
    import urllib.request

    from dpsvm_tpu.observability import blackbox, slo

    problems: List[str] = []
    # 1. rule round-trip: specs -> RuleSet -> specs, bit-identical
    specs = slo.default_serving_rules() + slo.default_training_rules()
    rs = slo.RuleSet.from_specs(specs)
    if rs.to_specs() != specs:
        problems.append("rule round-trip drifted "
                        f"({rs.to_specs()} != {specs})")
    # 2. planted burn on an injectable clock: healthy for 120 ticks,
    # then a 50% 504 ratio — the page rule must fire, and a healthy
    # steady state must never have fired
    tower = slo.Watchtower(slo.load_rules(None, default="serving"))
    fired_at = None
    for i in range(400):
        t = float(i)
        bad = max(0, i - 120) * 5.0 if i <= 240 else 600.0
        trs = tower.observe({"requests": i * 10.0,
                             "deadline_504": bad}, t=t)
        for tr in trs:
            if tr["state"] == "firing" and fired_at is None:
                if i <= 120:
                    problems.append("burn rule fired on healthy "
                                    f"steady state at t={t}")
                fired_at = t
    if fired_at is None:
        problems.append("planted 50% 504 burn never fired the "
                        "burn-rate rule")
    elif not any(s["state"] == "ok" for s in tower.states()
                 if s["rule"] == "availability-burn"):
        problems.append("burn-rate alert did not clear after the "
                        "burn stopped")
    if tower.worst_fired != "page" or tower.exit_code() != slo.EXIT_PAGE:
        problems.append(f"watch exit-code contract drifted: "
                        f"{tower.worst_fired} -> {tower.exit_code()}")
    # 3. bundle dump -> re-validate
    with tempfile.TemporaryDirectory(dir=tmp_dir) as td:
        fr = blackbox.FlightRecorder(blackbox.make_manifest(
            solver="selfcheck-watch"))
        fr.chunk(n_iter=512, b_lo=0.5, b_hi=-0.5)
        fr.event("alert", rule="availability-burn",
                 window="fast=60s/slow=600s", severity="page",
                 state="firing", reason="selfcheck burn")
        reg = MetricsRegistry()
        reg.counter("dpsvm_selfcheck_total", "check").inc()
        path = blackbox.dump_bundle(
            td, recorder=fr, rule="availability-burn",
            severity="page", window="fast=60s/slow=600s",
            reason="selfcheck burn", registry=reg)
        if not path:
            problems.append("bundle dump failed")
        else:
            errs = blackbox.validate_bundle(path)
            if errs:
                problems.append(f"dumped bundle no longer validates: "
                                f"{errs}")
            if blackbox.resolve_bundle_dir(td) != path:
                problems.append("resolve_bundle_dir lost the bundle")
        # 4. the live drill: slow-replica fault -> 504 storm through
        # REAL HTTP -> the server's own watchtower fires + dumps ->
        # fault lifts -> recovery (alert clears)
        problems += _watch_storm_drill(td)
    return problems


def _watch_storm_drill(td: str) -> List[str]:
    """Fault-injected 504 storm against a stub-engine ServingServer
    (no backend init): the in-process half of the drill that
    tests/test_watch.py also pins as a subprocess."""
    import json
    import os
    import time
    import urllib.request

    from dpsvm_tpu.observability import blackbox, slo

    try:
        import numpy as np

        from dpsvm_tpu.resilience import faultinject
        from dpsvm_tpu.serving.server import ServingServer
    except Exception as e:              # pragma: no cover — env issue
        return [f"watch drill setup failed: {e}"]

    class _Engine:
        num_attributes = 4
        calibrated = False
        manifest = {"task": "selfcheck-stub", "num_attributes": 4}

        def infer(self, x, want):
            n = int(np.shape(x)[0])
            return {k: (np.ones(n, np.int32) if k == "labels"
                        else np.zeros(n, np.float32))
                    for k in want}

        def bucket_counts(self):
            return {}

    class _Registry:
        def __init__(self):
            self._e = _Engine()

        def names(self):
            return ["default"]

        def engine(self, name):
            return self._e

        def build(self, name):
            return _Engine()

        def manifests(self):
            return {"default": dict(self._e.manifest, generation=1)}

    problems: List[str] = []
    bundle_dir = os.path.join(td, "storm-bundles")
    # tight windows so the drill runs in ~2 s of wall clock; the
    # determinism tests live on the injectable clock, this drills the
    # REAL feed path end-to-end
    rules = [{"name": "availability-burn", "kind": "burn_rate",
              "severity": "page", "good": "requests",
              "bad": "deadline_504", "objective": 0.999,
              "fast_window_s": 0.4, "slow_window_s": 1.0,
              "threshold": 2.0, "clear_after_s": 0.3}]
    # ~30 slowed computes cover the storm phase, then the fault lifts
    faultinject.install(faultinject.FaultPlan(
        serve_slow_replica_ms=60, serve_slow_for=30))
    srv = None
    try:
        srv = ServingServer(_Registry(), port=0, max_batch=4,
                            max_delay_ms=0.2, watch_rules=rules,
                            bundle_dir=bundle_dir).start()
        body = json.dumps({"instances": [[0.0] * 4],
                           "timeout_ms": 15}).encode()

        def post():
            req = urllib.request.Request(
                srv.url + "/v1/predict", data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    r.read()
                    return r.status
            except urllib.error.HTTPError as e:
                e.read()
                return e.code

        deadline = time.monotonic() + 20.0
        fired = False
        while time.monotonic() < deadline and not fired:
            post()
            fired = any(s["state"] == "firing"
                        for s in srv.watch.states())
        if not fired:
            problems.append("504 storm never fired the serving "
                            "burn-rate rule")
        # recovery: the fault has a finite budget (serve_slow_for), so
        # continued traffic is healthy and the alert must clear
        cleared = False
        while time.monotonic() < deadline and not cleared:
            post()
            cleared = all(s["state"] == "ok"
                          for s in srv.watch.states())
            if not cleared:
                time.sleep(0.05)
        if not cleared:
            problems.append("alert did not clear after the slow-"
                            "replica fault lifted")
        m = srv.metrics()
        if not m.get("incidents_total"):
            problems.append("dpsvm_incidents_total never incremented")
        if not any(e.get("event") == "alert" for e in m.get("events",
                                                            [])):
            problems.append("events ring has no alert entry")
        bundles = [b for b in (os.listdir(bundle_dir)
                               if os.path.isdir(bundle_dir) else [])
                   if b.startswith("incident-")]
        if not bundles:
            problems.append("storm fired but dumped no bundle")
        else:
            bpath = blackbox.resolve_bundle_dir(bundle_dir)
            errs = blackbox.validate_bundle(bpath)
            if errs:
                problems.append(f"storm bundle invalid: {errs}")
            inc = blackbox.load_incident(bpath)
            if inc.get("rule") != "availability-burn":
                problems.append("incident.json lost the rule name")
    except Exception as e:
        problems.append(f"watch storm drill crashed: {e!r}")
    finally:
        try:
            if srv is not None:
                srv.drain(timeout=10.0)
        except Exception:
            pass
        faultinject.clear()
    return problems


def _selfcheck_tenants(tmp_dir: Optional[str] = None) -> List[str]:
    """The per-tenant attribution gate (docs/OBSERVABILITY.md
    "Per-tenant attribution"): multi-tenant stub traffic with a
    planted hog -> per-tenant series land on both /metricsz faces
    (validator-clean exposition) -> the tenant-fair-share rule fires
    NAMING the hog -> the incident bundle carries the tenant -> the
    trace's span roots attribute every sampled request to its
    tenant."""
    import json
    import os
    import tempfile
    import time
    import urllib.error
    import urllib.request

    from dpsvm_tpu.observability import blackbox
    from dpsvm_tpu.observability.metrics import validate_exposition

    try:
        import numpy as np

        from dpsvm_tpu.serving.loadgen import tenant_of
        from dpsvm_tpu.serving.server import ServingServer
    except Exception as e:              # pragma: no cover — env issue
        return [f"tenant drill setup failed: {e}"]

    class _Engine:
        num_attributes = 4
        calibrated = False
        manifest = {"task": "selfcheck-stub", "num_attributes": 4}

        def infer(self, x, want):
            n = int(np.shape(x)[0])
            return {k: (np.ones(n, np.int32) if k == "labels"
                        else np.zeros(n, np.float32))
                    for k in want}

        def bucket_counts(self):
            return {}

    class _Registry:
        def __init__(self):
            self._e = _Engine()

        def names(self):
            return ["default", "aux"]

        def engine(self, name):
            return self._e

        def build(self, name):
            return _Engine()

        def manifests(self):
            return {n: dict(self._e.manifest, generation=1)
                    for n in self.names()}

    problems: List[str] = []
    with tempfile.TemporaryDirectory(dir=tmp_dir) as td:
        bundle_dir = os.path.join(td, "tenant-bundles")
        trace_path = os.path.join(td, "tenant-trace.jsonl")
        rules = [{"name": "tenant-fair-share", "kind": "fair_share",
                  "severity": "warn", "per_tenant": True,
                  "window_s": 0.8, "share_above": 0.5,
                  "min_tenants": 2, "for_s": 0.0,
                  "clear_after_s": 10.0}]
        srv = None
        try:
            srv = ServingServer(_Registry(), port=0, max_batch=4,
                                max_delay_ms=0.2, watch_rules=rules,
                                bundle_dir=bundle_dir,
                                trace_out=trace_path,
                                trace_sample_rate=1.0,
                                tenant_budget=8).start()

            def post(i):
                body = {"instances": [[0.0] * 4],
                        "model": ("aux" if i % 7 == 3 else "default"),
                        "tenant": tenant_of(i, 8, 0.8)}
                req = urllib.request.Request(
                    srv.url + "/v1/predict",
                    data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10) as r:
                    r.read()

            deadline = time.monotonic() + 20.0
            fired = {}
            i = 0
            while time.monotonic() < deadline and not fired:
                post(i)
                i += 1
                fired = next(
                    (s for s in srv.watch.states()
                     if s["state"] == "firing"
                     and s["rule"].startswith("tenant-fair-share[")),
                    {})
            if not fired:
                problems.append("planted hot tenant never fired the "
                                "fair-share rule")
            elif fired.get("tenant") != "t0":
                problems.append("fair-share fired for "
                                f"{fired.get('tenant')!r}, not the "
                                "planted hog t0")
            # both /metricsz faces carry the per-tenant series
            with urllib.request.urlopen(
                    srv.url + "/metricsz?format=prometheus",
                    timeout=10) as r:
                expo = r.read().decode()
            errs = validate_exposition(expo)
            if errs:
                problems.append("per-tenant exposition invalid: "
                                f"{errs}")
            if 'dpsvm_tenant_requests_total{tenant="t0"}' not in expo:
                problems.append("tenant series missing from the "
                                "prometheus exposition")
            with urllib.request.urlopen(srv.url + "/metricsz",
                                        timeout=10) as r:
                mz = json.loads(r.read())
            per = (mz.get("tenants") or {}).get("per_tenant") or {}
            if not per or max(
                    per, key=lambda t: per[t]["requests"]) != "t0":
                problems.append("JSON cost ledger did not rank the "
                                f"hog first: {sorted(per)}")
            for name in ("default", "aux"):
                if name not in (mz.get("per_model") or {}):
                    problems.append(f"per_model block lost {name!r}")
        except Exception as e:
            problems.append(f"tenant drill crashed: {e!r}")
        finally:
            try:
                if srv is not None:
                    srv.drain(timeout=10.0)
            except Exception:
                pass
        # the incident bundle names the culprit and validates clean
        bundles = [b for b in (os.listdir(bundle_dir)
                               if os.path.isdir(bundle_dir) else [])
                   if b.startswith("incident-")]
        if not bundles:
            problems.append("fair-share fired but dumped no bundle")
        else:
            bpath = blackbox.resolve_bundle_dir(bundle_dir)
            errs = blackbox.validate_bundle(bpath)
            if errs:
                problems.append(f"tenant bundle invalid: {errs}")
            inc = blackbox.load_incident(bpath)
            if inc.get("tenant") != "t0":
                problems.append("incident.json does not name the "
                                f"tenant: {inc.get('tenant')!r}")
        # every sampled span root attributes its request to a tenant
        try:
            records = load_trace(trace_path)
        except (OSError, ValueError) as e:
            problems.append(f"tenant trace unreadable: {e}")
            records = []
        roots = [r for r in records
                 if r.get("kind") == "span" and r.get("name") == "request"]
        if not roots:
            problems.append("tenant trace recorded no request roots")
        if any("tenant" not in r for r in roots):
            problems.append("a sampled span root lost its tenant")
    return problems


def _selfcheck_spans(tmp_dir: Optional[str] = None) -> List[str]:
    """Span round-trip (schema v3, docs/OBSERVABILITY.md "Spans"):
    serve real HTTP requests through the REAL serving stack — stub
    engine, so no backend init — under --trace-out at sample rate 1.0,
    then validate the v3 artifact and assert the attribution residual
    stays under 10% of each request's wall (the acceptance bar: spans
    must explain where the time went, not leave it unattributed)."""
    import json
    import os
    import tempfile
    import urllib.request

    try:
        import numpy as np

        from dpsvm_tpu.serving.server import ServingServer
    except Exception as e:              # pragma: no cover — env issue
        return [f"span selfcheck setup failed: {e}"]

    class _Engine:
        num_attributes = 4
        calibrated = False
        manifest = {"task": "selfcheck-stub", "num_attributes": 4}

        def infer(self, x, want):
            n = int(np.shape(x)[0])
            out = {}
            if "labels" in want:
                out["labels"] = np.ones(n, np.int32)
            if "decision" in want:
                out["decision"] = np.zeros(n, np.float32)
            return out

        def bucket_counts(self):
            return {}

    class _Registry:
        def __init__(self):
            self._e = _Engine()

        def names(self):
            return ["default"]

        def engine(self, name):
            return self._e

        def build(self, name):
            return _Engine()

        def manifests(self):
            return {"default": dict(self._e.manifest, generation=1)}

    problems: List[str] = []
    with tempfile.TemporaryDirectory(dir=tmp_dir) as td:
        path = os.path.join(td, "serve.jsonl")
        srv = ServingServer(_Registry(), port=0, max_batch=8,
                            max_delay_ms=0.5, trace_out=path,
                            trace_sample_rate=1.0).start()
        try:
            body = json.dumps(
                {"instances": [[0.0] * 4, [1.0] * 4]}).encode()
            for _ in range(6):
                req = urllib.request.Request(
                    srv.url + "/v1/predict", data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=15) as r:
                    r.read()
        finally:
            srv.drain(timeout=15.0)
        try:
            records = load_trace(path)      # validates v3 en route
        except ValueError as e:
            return [f"serving span trace failed validation: {e}"]
        if (records[0].get("schema") or 0) < 3:
            problems.append("serving trace is not schema v3")
        att = span_attribution(records)
        if att is None or att["requests"] < 6:
            problems.append(f"span attribution lost requests: {att}")
        elif att["covered_90pct_frac"] < 0.99:
            problems.append(
                "attribution residual >= 10% on "
                f"{1 - att['covered_90pct_frac']:.0%} of requests "
                f"(slowest: {att['slowest'][:1]})")
        text = render_report(records)
        for needle in ("request latency attribution",
                       "slowest requests", "device_dispatch"):
            if needle not in text:
                problems.append(f"span report rendering lost "
                                f"{needle!r}")
    return problems


def _selfcheck_roofline(tmp_dir: Optional[str] = None) -> List[str]:
    """Roofline round-trip (docs/OBSERVABILITY.md "Roofline"): a
    synthetic v3 bench trace on a known device (TPU v5e peaks) must
    render an achieved-vs-peak fraction and a compute/memory-bound
    verdict per phase; an unknown device must read as an honest n/a;
    and a perf-ledger history of roofline_fraction readings must be
    gateable by `dpsvm perf gate` (planted utilization drop fails)."""
    import os
    import tempfile

    from dpsvm_tpu.observability import ledger, roofline

    problems: List[str] = []
    if roofline.peaks_for("TPU v4") is None:
        problems.append("peak table lost TPU v4")
    if roofline.peaks_for("cpu") is not None:
        problems.append("peak table invented a CPU peak")
    with tempfile.TemporaryDirectory(dir=tmp_dir) as td:
        path = os.path.join(td, "bench_v5e.jsonl")
        tr = RunTrace(path, config={"kernel": "rbf"}, n=60000, d=784,
                      gamma=0.25, solver="bench-smo",
                      env={"backend": "tpu",
                           "device_kind": "TPU v5 lite",
                           "device_count": 1})
        # ~2.4e9 FLOP and ~3e7 B per iteration near the BENCH_r02
        # operating point — AI ~80 FLOP/B, below the v5e ridge (~241),
        # so the honest verdict is memory-bound.
        tr.compile(program="bench-smo-chunk", seconds=1.0,
                   flops=2.4e9, bytes=3.0e7)
        tr.chunk(n_iter=100_000, b_lo=0.1, b_hi=-0.1,
                 phases={"dispatch": 1.0, "poll": 4.5},
                 phase_counts={"dispatch": 10, "poll": 10})
        tr.summary(converged=True, n_iter=100_000, b=0.0, b_lo=0.001,
                   b_hi=-0.001, n_sv=100, train_seconds=6.0,
                   phases={"dispatch": 1.0, "poll": 4.5},
                   phase_counts={"dispatch": 10, "poll": 10})
        tr.close()
        try:
            records = load_trace(path)
        except ValueError as e:
            return [f"roofline sample failed validation: {e}"]
        facts = trace_facts(records)
        frac = facts.get("roofline_fraction")
        if not (frac and 0 < frac < 1):
            problems.append(f"roofline_fraction not computed: {frac}")
        if facts.get("roofline_verdict") != "memory-bound":
            problems.append("v5e bench point must read memory-bound, "
                            f"got {facts.get('roofline_verdict')}")
        text = render_report(records)
        for needle in ("roofline: TPU v5e", "of peak",
                       "[memory-bound]"):
            if needle not in text:
                problems.append(f"roofline rendering lost {needle!r}")
        # unknown hardware: explicit n/a, never an invented number
        records[0] = dict(records[0],
                          env={"backend": "cpu", "device_kind": "cpu",
                               "device_count": 1})
        if trace_facts(records).get("roofline_fraction") is not None:
            problems.append("unknown device got a roofline fraction")
        if "roofline: n/a" not in render_report(records):
            problems.append("unknown device lost the explicit "
                            "roofline n/a line")
        # ledger gate on the roofline_fraction column
        lpath = os.path.join(td, "ledger.jsonl")
        for v in (0.60, 0.61, 0.59, 0.60, 0.60, 0.40):
            ledger.append("bench_headline",
                          {"value": 16000.0, "unit": "iter/s",
                           "roofline_fraction": v},
                          kind="bench", path=lpath, strict=True)
        records_l = ledger.read(lpath)
        if ledger.gate(records_l, window=5, threshold_pct=10.0,
                       metric="roofline_fraction") == []:
            problems.append("planted roofline_fraction drop PASSED "
                            "the perf gate")
        if ledger.gate(records_l[:-1], window=5, threshold_pct=10.0,
                       metric="roofline_fraction"):
            problems.append("clean roofline_fraction history failed "
                            "the perf gate")
    return problems


def _selfcheck_fleet(tmp_dir: Optional[str] = None) -> List[str]:
    """The fleet-observability gate (docs/OBSERVABILITY.md "Fleet"):
    a synthetic 3-host trace family with a planted straggler must
    merge into ONE schema-v5 validator-clean timeline whose lanes and
    report NAME the straggler -> a mismatched fingerprint must refuse
    to merge -> the skew rule fires naming the laggard host and
    clears after it catches up -> per-host metrics snapshots federate
    into a validator-clean exposition with the right aggregation
    (iterations min'ed, compiles summed) -> the fleet incident bundle
    carries every host's artifacts and re-validates. The subprocess
    twin (real hosts, real hang fault) is
    ``resilience/hostgroup.py straggler_drill``."""
    import json
    import os
    import tempfile

    from dpsvm_tpu.observability import blackbox, fleet, merge, slo
    from dpsvm_tpu.observability.metrics import write_snapshot
    from dpsvm_tpu.observability.report import (host_lanes,
                                                load_trace_auto)

    problems: List[str] = []
    with tempfile.TemporaryDirectory(dir=tmp_dir) as td:
        # one schema-v4 run through the REAL writer, then three host
        # copies of it: same wall-clock start (equal manifest `unix`
        # anchors), host 1 holding the group longer at every chunk —
        # the planted straggler
        base = os.path.join(td, "template.jsonl")
        tr = RunTrace(base, config={"kernel": "rbf", "shards": 3,
                                    "shard_x": True, "coef0": 0.0,
                                    "degree": 3},
                      n=3000, d=16, gamma=0.5, solver="dist-smo",
                      it0=0, env={"backend": "cpu",
                                  "device_kind": "host",
                                  "device_count": 1})
        for i in range(4):
            tr.chunk(n_iter=(i + 1) * 128, b_lo=0.4 - 0.1 * i,
                     b_hi=-(0.4 - 0.1 * i), n_sv=40 + i,
                     cache_hits=i, cache_misses=i, rounds=i,
                     phases={"dispatch": 0.01, "poll": 0.02})
        tr.summary(converged=True, n_iter=512, b=0.0, b_lo=1e-3,
                   b_hi=-1e-3, n_sv=44, train_seconds=1.0,
                   cache_hits=4, cache_misses=4,
                   phases={"dispatch": 0.04, "poll": 0.08},
                   phase_counts={"dispatch": 4, "poll": 4})
        tr.close()
        template = load_trace(base)
        fam = os.path.join(td, "fam")
        os.makedirs(fam)
        for h in (0, 1, 2):
            recs = [dict(r) for r in template]
            recs[0]["unix"] = 1.7e9          # same wall-clock start
            chunk_i = 0
            for r in recs[1:]:
                if not isinstance(r.get("t"), (int, float)):
                    continue
                if r.get("kind") == "chunk":
                    chunk_i += 1
                lag = 0.4 * chunk_i if h == 1 else 0.0
                r["t"] = round(1.0 * chunk_i + lag + 0.001 * h, 6)
            with open(os.path.join(fam, f"trace_h{h}.jsonl"),
                      "w") as fh:
                for r in recs:
                    fh.write(json.dumps(r) + "\n")
        merged = merge.merge_dir(fam)
        errs = validate_trace(merged)
        if errs:
            problems.append(f"merged fleet trace invalid: {errs}")
        lanes = host_lanes(merged)
        if lanes is None or lanes["straggler"] != 1:
            problems.append("planted straggler not attributed: "
                            f"{lanes and lanes['straggler']}")
        text = render_report(merged)
        if "straggler: host 1" not in text:
            problems.append("fleet report lost the straggler line")
        # dpsvm report on the directory must auto-merge the family;
        # the single-trace resolver must refuse it naming the hosts
        if len(load_trace_auto(fam)) != len(merged):
            problems.append("load_trace_auto did not merge the "
                            "trace family")
        try:
            resolve_trace_path(fam)
            problems.append("resolve_trace_path silently picked one "
                            "host of a multi-host family")
        except ValueError:
            pass
        # mismatched run fingerprints must refuse to merge
        bad = os.path.join(td, "bad")
        os.makedirs(bad)
        for h, gamma in ((0, 0.5), (1, 0.25)):
            recs = [dict(r) for r in template]
            recs[0]["gamma"] = gamma
            with open(os.path.join(bad, f"trace_h{h}.jsonl"),
                      "w") as fh:
                for r in recs:
                    fh.write(json.dumps(r) + "\n")
        try:
            merge.merge_dir(bad)
            problems.append("mismatched fingerprints merged anyway")
        except merge.MergeError:
            pass
        # the skew rule: host 1 a full chunk behind over the window
        # fires NAMING it, then clears once the lanes level; the
        # per-host heartbeat template expands over the same sample
        tower = slo.Watchtower(slo.load_rules(None, default="fleet"))
        fired = []
        for i in range(80):
            front = 128.0 * (1 + i // 8)
            sample = {}
            for h in (0, 1, 2):
                lagging = h == 1 and i <= 32
                sample[f"host:{h}:n_iter"] = (front - 64.0 if lagging
                                              else front)
                sample[f"host:{h}:heartbeat_age_seconds"] = 1.0
            sample["generation"] = 0.0
            fired += [t for t in tower.observe(sample, t=float(i))
                      if t["rule"] == "iteration-skew"]
        if not fired or fired[0]["state"] != "firing":
            problems.append("planted iteration skew never fired")
        elif (fired[0].get("host") != 1
              or "skew[host-1]" not in fired[0]["reason"]):
            problems.append("skew rule did not name host 1: "
                            f"{fired[0]}")
        if not any(t["state"] == "ok" for t in fired):
            problems.append("skew alert did not clear after the "
                            "laggard caught up")
        if not any(s["rule"] == "host-heartbeat-stale[host-2]"
                   for s in tower.states()):
            problems.append("per-host heartbeat template did not "
                            "expand over the active hosts")
        # federation: two sidecar snapshots -> one fleet snapshot,
        # iterations min'ed, compiles summed, exposition valid
        srcs = []
        for h, (iters, compiles) in enumerate(((500.0, 3),
                                               (380.0, 2))):
            reg = MetricsRegistry()
            reg.gauge("dpsvm_train_iterations", "it").set(iters)
            reg.gauge("dpsvm_train_gap", "gap").set(0.01 * (h + 1))
            reg.counter("dpsvm_train_compiles_total",
                        "compiles").inc(compiles)
            path = os.path.join(td, f"metrics_h{h}.prom")
            write_snapshot(reg, path, seq=5 + h)
            srcs.append(path)
        snap = fleet.federate(fleet.collect(srcs))
        agg = snap["aggregate"]
        if (agg.get("dpsvm_train_iterations") != 380.0
                or agg.get("dpsvm_train_compiles_total") != 5.0):
            problems.append(f"federation aggregation drifted: {agg}")
        if snap["lag"] != 120.0 or snap["slowest"] != 1:
            problems.append("fleet lag/slowest drifted: "
                            f"{snap['lag']}/{snap['slowest']}")
        expo = fleet.render_exposition(snap)
        errs = validate_exposition(expo)
        if errs:
            problems.append(f"fleet exposition invalid: {errs}")
        if 'dpsvm_host_iterations{host="1"} 380' not in expo:
            problems.append("per-host iteration lane missing from "
                            "the fleet exposition")
        if "host:1:n_iter" not in fleet.fleet_watch_sample(snap):
            problems.append("fleet watch sample lost the host lanes")
        # the fleet incident bundle: every host's artifacts ride
        # along and the bundle re-validates
        hb_dir = os.path.join(td, "hosts")
        os.makedirs(hb_dir)
        for h in (0, 1, 2):
            with open(os.path.join(hb_dir, f"host-{h}.json"),
                      "w") as fh:
                json.dump({"host": h, "n_iter": 512, "generation": 0,
                           "pid": 1000 + h, "t": 1.7e9, "seq": 9}, fh)
        arts = fleet.host_artifacts(fam, hb_dir)
        if sorted(arts) != [0, 1, 2]:
            problems.append(f"host_artifacts lost hosts: "
                            f"{sorted(arts)}")
        fr = blackbox.FlightRecorder(blackbox.make_manifest(
            solver="selfcheck-fleet"))
        fr.event("alert", rule="iteration-skew", window="30s",
                 severity="warn", state="firing",
                 reason=fired[0]["reason"] if fired else "skew")
        bpath = blackbox.dump_bundle(
            os.path.join(td, "bundles"), recorder=fr,
            rule="iteration-skew", severity="warn", window="30s",
            reason="selfcheck skew",
            extra={"extra": {"host": 1}}, host_artifacts=arts)
        if not bpath:
            problems.append("fleet bundle dump failed")
        else:
            errs = blackbox.validate_bundle(bpath)
            if errs:
                problems.append(f"fleet bundle invalid: {errs}")
            inc = blackbox.load_incident(bpath)
            if (inc.get("extra") or {}).get("host") != 1:
                problems.append("fleet incident lost the straggler "
                                "host")
            if not os.path.exists(os.path.join(
                    bpath, "host-1-heartbeat.json")):
                problems.append("fleet bundle lost host 1's "
                                "heartbeat artifact")
    return problems


def _selfcheck_metrics() -> List[str]:
    """Registry -> exposition -> grammar validator round-trip, plus a
    tamper check (the validator must actually reject broken text) —
    the schema gate of the metrics surface, sibling of the trace
    writer/validator round-trip above."""
    problems = []
    reg = MetricsRegistry()
    c = reg.counter("dpsvm_check_requests_total", "selfcheck counter",
                    labels=("model",))
    c.labels(model="default").inc(3)
    c.labels(model='odd"name\nwith escapes').inc()
    reg.gauge("dpsvm_check_gap", "selfcheck gauge").set(0.125)
    h = reg.histogram("dpsvm_check_latency_ms", "selfcheck histogram",
                      buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 50.0, 5000.0):
        h.observe(v)
    text = reg.render_prometheus()
    errs = validate_exposition(text)
    if errs:
        problems.append(f"exposition no longer validates: {errs}")
    if c.labels(model="default").value != 3:
        problems.append("counter read-back drifted")
    tampered = text.replace('le="+Inf"} 4', 'le="+Inf"} 3')
    if not validate_exposition(tampered):
        problems.append("exposition validator accepted a broken "
                        "histogram (+Inf bucket != _count)")
    snap = reg.snapshot()
    if snap.get("dpsvm_check_gap", {}).get("series", [{}])[0].get(
            "value") != 0.125:
        problems.append("JSON snapshot lost the gauge value")
    return problems


def _selfcheck_ledger(tmp_dir: Optional[str] = None) -> List[str]:
    """Perf-ledger append/read/gate round-trip: a planted 20%
    historical regression MUST fail the gate; a clean history and a
    single-run case must pass (docs/OBSERVABILITY.md "Perf ledger")."""
    import os
    import tempfile

    from dpsvm_tpu.observability import ledger

    problems = []
    with tempfile.TemporaryDirectory(dir=tmp_dir) as td:
        path = os.path.join(td, "ledger.jsonl")
        for v in (100.0, 101.0, 99.0, 100.0, 100.0, 100.5):
            ledger.append("clean_case", {"value": v, "unit": "iter/s"},
                          kind="bench", path=path, strict=True)
        for v in (100.0, 100.0, 101.0, 99.0, 100.0, 80.0):
            ledger.append("planted_regression",
                          {"value": v, "unit": "iter/s"},
                          kind="bench", path=path, strict=True,
                          trace="traces/planted.jsonl")
        ledger.append("single_run", {"value": 5.0, "unit": "s"},
                      kind="burst", path=path, strict=True)
        records = ledger.read(path)
        if len(records) != 13:
            problems.append(f"ledger round-trip lost records "
                            f"({len(records)}/13)")
        clean = ledger.gate(records, window=5, threshold_pct=10.0,
                            case="clean_case")
        if clean:
            problems.append(f"clean history failed the gate: {clean}")
        planted = ledger.gate(records, window=5, threshold_pct=10.0,
                              case="planted_regression")
        if not planted:
            problems.append("planted 20% regression PASSED the "
                            "historical gate")
        if ledger.gate(records, window=5, threshold_pct=10.0,
                       case="single_run"):
            problems.append("single-run case (no history) failed the "
                            "gate")
        # the full-ledger sweep must flag exactly the planted case
        allv = ledger.gate(records, window=5, threshold_pct=10.0)
        if [v.split(":")[0] for v in allv] != ["planted_regression"]:
            problems.append(f"full-ledger gate verdicts drifted: {allv}")
    return problems


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json
    import sys

    p = argparse.ArgumentParser(prog="python -m dpsvm_tpu.telemetry")
    p.add_argument("--selfcheck", action="store_true",
                   help="writer -> validator -> renderer -> comparator "
                        "round-trip on a synthetic trace (the CI schema "
                        "gate), plus v1 back-compat")
    p.add_argument("--validate", default=None, metavar="TRACE",
                   help="validate an existing trace file (or the newest "
                        "*.jsonl in a directory)")
    args = p.parse_args(argv)
    if args.selfcheck:
        problems = selfcheck()
        if problems:
            print("telemetry selfcheck FAILED:", file=sys.stderr)
            for pr in problems:
                print(f"  {pr}", file=sys.stderr)
            return 1
        print("telemetry selfcheck OK "
              f"(schema v{TRACE_SCHEMA_VERSION}, v1 accepted; metrics "
              "exposition + ledger gate + serving span round-trip + "
              "roofline render + watch gate (burn-rate fire/clear, "
              "504-storm drill, incident-bundle round-trip) + tenant "
              "gate (per-tenant series on both /metricsz faces, "
              "fair-share names the hog, bundle carries the tenant, "
              "span roots attributed) + fleet gate (trace-family "
              "merge names the straggler, fingerprint refusal, skew "
              "rule fire/clear, federation exposition, fleet bundle) "
              "checked)")
        return 0
    if args.validate:
        try:
            resolved = resolve_trace_path(args.validate)
            records = load_trace(resolved)
        except (OSError, ValueError) as e:
            print(f"INVALID: {e}", file=sys.stderr)
            return 1
        print(json.dumps({"valid": True, "records": len(records),
                          "path": resolved}))
        return 0
    p.print_help()
    return 2

"""Data loading: dense CSV / libsvm datasets, synthetic fixtures,
converters, and the out-of-core streaming shard layer (data/stream.py,
docs/DATA.md).

CI gate: ``python -m dpsvm_tpu.data --selfcheck`` — sibling of the
telemetry/resilience/serving/approx gates. Runs the full streaming
story end to end on CPU: convert -> stream-train -> quarantine drill
(one corrupted shard + one injected transient read failure, schema-
valid trace with the ``quarantine`` event) -> bitwise
preempt-and-resume of the streaming trajectory -> byte-identical
manifest after a killed-and-resumed conversion.
"""

from dpsvm_tpu.data.loader import (load_csv, load_libsvm, load_dataset,
                                   sniff_format, csv_shape)
from dpsvm_tpu.data.synthetic import make_blobs, make_xor, make_mnist_like

__all__ = ["load_csv", "load_libsvm", "load_dataset", "sniff_format",
           "csv_shape", "make_blobs", "make_xor", "make_mnist_like",
           "selfcheck", "main"]


def selfcheck(tmp_dir=None):
    """Run the streaming data pipeline end to end on an embedded
    sample; return a list of problems (empty = healthy)."""
    import json
    import os
    import tempfile

    import numpy as np

    problems = []
    ctx = tempfile.TemporaryDirectory() if tmp_dir is None else None
    base = tmp_dir if tmp_dir is not None else ctx.name
    try:
        from dpsvm_tpu.config import SVMConfig
        from dpsvm_tpu.data import stream as streamlib
        from dpsvm_tpu.data.synthetic import make_blobs, save_csv
        from dpsvm_tpu.resilience import faultinject

        x, y = make_blobs(n=384, d=6, seed=7)
        src = os.path.join(base, "blobs.csv")
        save_csv(src, x, y)

        # 1. convert -> open -> verify: manifest CRCs + stats hold.
        sdir = os.path.join(base, "shards")
        streamlib.convert_to_shards(src, sdir, rows_per_shard=96)
        ds = streamlib.ShardedDataset.open(sdir)
        if ds.n != len(y) or ds.n_shards != 4:
            problems.append(f"conversion shape: n={ds.n} "
                            f"shards={ds.n_shards} (wanted 384/4)")
        bad = ds.verify()
        if bad:
            problems.append(f"fresh shards failed verify: {bad}")
        xm, ym = ds.materialize()
        if not (np.array_equal(xm, x.astype(np.float32))
                and np.array_equal(ym, y)):
            problems.append("materialized rows != source rows")

        # 2. resumable conversion: stop after 2 shards (the kill),
        # resume, and the manifest must land BYTE-identical to the
        # uninterrupted directory's.
        kdir = os.path.join(base, "shards_killed")
        partial = streamlib.convert_to_shards(src, kdir,
                                              rows_per_shard=96,
                                              _stop_after_shards=2)
        if os.path.exists(os.path.join(kdir, streamlib.MANIFEST_NAME)):
            problems.append("killed conversion left a manifest")
        if not os.path.exists(os.path.join(kdir, streamlib.CURSOR_NAME)):
            problems.append("killed conversion left no cursor")
        if partial.get("rows_done") != 192:
            problems.append(f"cursor rows_done {partial.get('rows_done')}"
                            " != 192")
        streamlib.convert_to_shards(src, kdir, rows_per_shard=96)
        with open(os.path.join(sdir, streamlib.MANIFEST_NAME), "rb") as f:
            a = f.read()
        with open(os.path.join(kdir, streamlib.MANIFEST_NAME), "rb") as f:
            b = f.read()
        if a != b:
            problems.append("resumed manifest is not byte-identical")

        # 3. stream-train + acceptance drill: total data over the
        # budget that materialization would need, one corrupt shard
        # (quarantined), one transient read failure (retried) — the
        # run completes with a schema-valid trace.
        from dpsvm_tpu.approx.primal import fit_approx_stream
        from dpsvm_tpu.models.svm import decision_function
        from dpsvm_tpu.observability.schema import (read_trace,
                                                    validate_trace)

        trace = os.path.join(base, "stream.jsonl")
        cfg = SVMConfig(solver="approx-rff", approx_dim=64, c=10.0,
                        epsilon=5e-3, max_iter=600, chunk_iters=64,
                        on_bad_shard="quarantine", mem_budget_mb=64.0,
                        trace_out=trace, verbose=False)
        faultinject.install(faultinject.FaultPlan(io_corrupt_shard=2,
                                                  io_read_fail_once=3))
        try:
            model, result = fit_approx_stream(ds, cfg)
        finally:
            faultinject.clear()
        if 1 not in ds.quarantined:
            problems.append(f"corrupt shard 2 not quarantined "
                            f"({ds.quarantined})")
        recs = read_trace(trace)
        errs = validate_trace(recs)
        if errs:
            problems.append(f"stream trace invalid: {errs}")
        quar = [r for r in recs if r.get("kind") == "event"
                and r.get("event") == "quarantine"]
        if not quar or "shard" not in quar[0]:
            problems.append("no quarantine event in the stream trace")
        pred = np.where(np.asarray(decision_function(model, x)) < 0,
                        -1, 1)
        acc = float(np.mean(pred == y))
        if acc < 0.9:
            problems.append(f"stream-trained accuracy {acc:.3f} < 0.9 "
                            "(despite one quarantined shard)")

        # 4. bitwise resume of the streaming trajectory: preempt at
        # the first poll, resume from the snapshot, final weights must
        # equal the uninterrupted run's bit for bit.
        from dpsvm_tpu.resilience.preempt import PreemptedError

        ds2 = streamlib.ShardedDataset.open(sdir)
        base_cfg = dict(solver="approx-rff", approx_dim=64, c=10.0,
                        epsilon=1e-6, max_iter=96, chunk_iters=32,
                        verbose=False)
        m_full, _ = fit_approx_stream(ds2, SVMConfig(**base_cfg))
        ck = os.path.join(base, "stream_ck.npz")
        faultinject.install(faultinject.FaultPlan(preempt_at_poll=1))
        try:
            fit_approx_stream(ds2, SVMConfig(checkpoint_path=ck,
                                             checkpoint_every=32,
                                             **base_cfg))
            problems.append("injected preemption did not raise")
        except PreemptedError:
            pass
        finally:
            faultinject.clear()
        m_res, _ = fit_approx_stream(
            ds2, SVMConfig(resume_from=ck, **base_cfg))
        if not np.array_equal(m_full.w, m_res.w):
            problems.append(
                "streaming resume is not bitwise-identical "
                f"(max delta {float(np.max(np.abs(m_full.w - m_res.w)))})")

        # 5. the budget guard refuses an over-budget materialization
        # with the shard math in the message.
        try:
            ds.materialize(mem_budget_mb=0.001)
            problems.append("mem-budget guard admitted an over-budget "
                            "materialization")
        except streamlib.MemBudgetError as e:
            if "rows" not in str(e) or "shards" not in str(e):
                problems.append(f"budget refusal lacks the shard math: "
                                f"{e}")

        # 6. live-append gate (docs/DATA.md "Live shard logs"): a torn
        # publish is NEVER read (the watcher holds its view), a stale
        # generation is refused, clean publishes are admitted, and a
        # preemption at an admission boundary resumes BITWISE —
        # re-admitting exactly the shards the dead run had consumed.
        from dpsvm_tpu.data import live as livelib

        ldir = os.path.join(base, "livelog")
        streamlib.convert_to_shards(src, ldir, rows_per_shard=96)
        ds_l = streamlib.ShardedDataset.open(ldir)
        watcher = livelib.ShardLogWatcher(ds_l)
        faultinject.install(
            faultinject.FaultPlan(live_torn_publish=1))
        try:
            livelib.append_shard(ldir, x[:96], y[:96])
            problems.append("torn publish did not crash the writer")
        except livelib.WriterCrashError:
            pass
        finally:
            faultinject.clear()
        if watcher.poll() or ds_l.generation != 0:
            problems.append("watcher advanced on a TORN publish")
        if watcher.torn_observed != 1:
            problems.append(f"torn publish not observed "
                            f"({watcher.torn_observed})")
        livelib.append_shard(ldir, x[:96], y[:96])   # repairs the log
        watcher.poll()
        if ds_l.generation != 1 or ds_l.n != 480:
            problems.append(f"repaired publish not admitted (gen "
                            f"{ds_l.generation}, n {ds_l.n})")
        # Stale-generation refusal is relative to the READER's view: the
        # watcher is now AT generation 1, so a replayed gen-1 publish
        # with changed content must be refused, not admitted.
        faultinject.install(
            faultinject.FaultPlan(live_stale_generation=1))
        try:
            livelib.append_shard(ldir, x[96:160], y[96:160])
        finally:
            faultinject.clear()
        watcher.poll()
        if ds_l.generation != 1 or watcher.stale_observed < 1:
            problems.append(
                f"stale-generation publish not refused (gen "
                f"{ds_l.generation}, stale {watcher.stale_observed})")
        # The next clean publish advances the generation and carries
        # BOTH the stale-published shard and the new one — the watcher
        # admits them together, never having read the stale bytes.
        livelib.append_shard(ldir, x[160:200], y[160:200])
        watcher.poll()
        if ds_l.generation != 2 or ds_l.n != 384 + 96 + 64 + 40:
            problems.append(f"clean publishes not admitted (gen "
                            f"{ds_l.generation}, n {ds_l.n})")
        # kill -> bitwise resume across the admission boundary
        from dpsvm_tpu.resilience.preempt import PreemptedError as _PE
        live_cfg = dict(solver="approx-rff", approx_dim=32, c=10.0,
                        epsilon=1e-9, max_iter=64, chunk_iters=32,
                        verbose=False)
        ds_a = streamlib.ShardedDataset.open(ldir, at_generation=0)
        m_live, _ = fit_approx_stream(ds_a, SVMConfig(**live_cfg),
                                      live=True)
        lck = os.path.join(base, "live_ck.npz")
        ds_b = streamlib.ShardedDataset.open(ldir, at_generation=0)
        faultinject.install(faultinject.FaultPlan(preempt_at_poll=1))
        try:
            fit_approx_stream(ds_b, SVMConfig(checkpoint_path=lck,
                                              checkpoint_every=32,
                                              **live_cfg), live=True)
            problems.append("live preemption did not raise")
        except _PE:
            pass
        finally:
            faultinject.clear()
        ds_c = streamlib.ShardedDataset.open(ldir, at_generation=0)
        m_lres, _ = fit_approx_stream(
            ds_c, SVMConfig(resume_from=lck, **live_cfg), live=True)
        if not np.array_equal(m_live.w, m_lres.w):
            problems.append(
                "live resume is not bitwise-identical (max delta "
                f"{float(np.max(np.abs(m_live.w - m_lres.w)))})")
    except Exception as e:              # noqa: BLE001 - gate reports
        import traceback
        traceback.print_exc()
        problems.append(f"selfcheck crashed: {type(e).__name__}: {e}")
    finally:
        if ctx is not None:
            ctx.cleanup()
    return problems


def main(argv=None):
    """``python -m dpsvm_tpu.data --selfcheck`` entry point."""
    import argparse
    import sys

    parser = argparse.ArgumentParser(prog="python -m dpsvm_tpu.data")
    parser.add_argument("--selfcheck", action="store_true",
                        help="run the streaming-data CI gate")
    args = parser.parse_args(argv)
    if not args.selfcheck:
        parser.print_help()
        return 2
    problems = selfcheck()
    if problems:
        for p in problems:
            print(f"SELFCHECK FAIL: {p}", file=sys.stderr)
        return 1
    print("data selfcheck OK: convert + stream-train + quarantine "
          "drill + bitwise resume + byte-identical manifest resume + "
          "live-append gate (torn publish never read, stale "
          "generation refused, bitwise live resume)")
    return 0

"""Data loading: dense CSV / libsvm datasets, synthetic fixtures, converters."""

from dpsvm_tpu.data.loader import (load_csv, load_libsvm, load_dataset,
                                   sniff_format, csv_shape)
from dpsvm_tpu.data.synthetic import make_blobs, make_xor, make_mnist_like

__all__ = ["load_csv", "load_libsvm", "load_dataset", "sniff_format",
           "csv_shape", "make_blobs", "make_xor", "make_mnist_like"]

"""Data loading: dense CSV datasets, synthetic fixtures, format converters."""

from dpsvm_tpu.data.loader import load_csv, csv_shape
from dpsvm_tpu.data.synthetic import make_blobs, make_xor, make_mnist_like

__all__ = ["load_csv", "csv_shape", "make_blobs", "make_xor", "make_mnist_like"]

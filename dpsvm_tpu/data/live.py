"""Live shard logs: crash-safe appends + the watcher that admits them.

The PR 9 shard format (append-ordered CRC-manifested shards + a
manifest) is already a log; this module adds the protocol that makes
it safe to APPEND to while readers are training and serving from it —
the workload a production deployment actually has ("Parallel SVMs in
Practice", arXiv:1404.1066: data never stops arriving). The fault
model comes first, as everywhere in this repo:

* **Publish protocol** — a writer lands the shard file with the
  existing atomic write (tmp + rename), then PUBLISHES it by swapping
  in a new ``manifest.json`` whose ``generation`` is strictly
  incremented and whose bytes carry a self-CRC (``manifest_crc`` over
  the canonical serialization). The swap is atomic too, and the
  previous good manifest is kept at ``manifest.json.prev`` so a
  writer restarted over a torn manifest (non-atomic filesystem,
  kill -9 mid-write — the ``DPSVM_FAULT_LIVE_TORN_PUBLISH`` model)
  recovers WITHOUT reconstructing state: readers never consult
  ``.prev`` (that would be a generation regression), only writers do.
* **Reader rules** — a reader only ever advances on a manifest that
  (a) parses, (b) passes its self-CRC, and (c) carries a generation
  STRICTLY greater than the reader's current one, and (d) purely
  EXTENDS the admitted shard list (the common prefix byte-identical).
  Anything else — a torn publish, a replayed stale generation, a
  rewritten prefix — leaves the reader's view untouched: a torn or
  partial publish is NEVER visible downstream.
* **ShardLogWatcher** — the polling reader: bounded transient-read
  retry/backoff (the ``DPSVM_IO_RETRIES`` semantics shard reads
  already use), quarantine of bad APPENDED shards under the existing
  ``on_bad_shard`` policy, and an ``append_admitted`` event per
  admitted shard naming shard + generation (live training wires the
  sink to the driver's pending-event queue so admissions land in the
  run trace, like ``quarantine``; a standalone watcher emits nowhere).

Consumers: ``approx/primal.fit_approx_stream(live=True)`` admits new
durable shards at sweep boundaries (docs/DATA.md "Live shard logs"),
and the continuous-learning serving loop
(``serving/lifecycle.ContinuousLearningLoop``) refreshes the served
model from the growing log (docs/SERVING.md "Continuous learning").

No jax at module level: append and watch must run on writer machines
with no accelerator.
"""

from __future__ import annotations

import json
import os
import sys
import time
import zlib
from typing import Callable, List, Optional

import numpy as np

from dpsvm_tpu.data.stream import (MANIFEST_NAME, ShardedDataset,
                                   StreamError, _write_json_atomic,
                                   _write_shard_atomic, payload_crc,
                                   shard_filename)
from dpsvm_tpu.resilience import faultinject

#: the writer's rolling backup of the last good manifest — consulted
#: ONLY by writers recovering from a torn publish; readers advancing
#: on it would regress the generation.
PREV_MANIFEST_NAME = MANIFEST_NAME + ".prev"


class TornPublishError(StreamError):
    """manifest.json exists but cannot be trusted: unparseable JSON or
    a failed self-CRC — a writer crashed mid-publish (or is mid-write
    on a non-atomic filesystem). Transient to readers (hold the last
    admitted view and retry); writers recover from the .prev backup."""


class WriterCrashError(StreamError):
    """Raised by the LIVE_* fault hooks at their configured crash
    point — the deterministic stand-in for a writer process dying."""


def _log(msg: str) -> None:
    print(f"LIVELOG: {msg}", file=sys.stderr, flush=True)


# ---------------------------------------------------------------------
# manifest self-CRC
# ---------------------------------------------------------------------

def manifest_crc(manifest: dict) -> int:
    """CRC32 over the canonical serialization of the manifest WITHOUT
    its ``manifest_crc`` key: a pure function of the content, so any
    torn / bit-rotted publish fails verification."""
    body = {k: v for k, v in manifest.items() if k != "manifest_crc"}
    raw = json.dumps(body, sort_keys=True,
                     separators=(",", ":")).encode()
    return zlib.crc32(raw)


def verify_manifest_crc(manifest: dict, where: str = "manifest") -> None:
    got = manifest_crc(manifest)
    want = int(manifest["manifest_crc"])
    if got != want:
        raise TornPublishError(
            f"{where}: manifest self-CRC mismatch (recorded {want}, "
            f"computed {got}) — a torn or bit-rotted publish; readers "
            "must hold their last admitted view")


def read_manifest_checked(directory: str) -> dict:
    """Parse + verify ``directory``'s manifest under the reader rules:
    raises ``TornPublishError`` on anything a mid-publish writer could
    have left (unparseable bytes, failed self-CRC) and ``StreamError``
    on a missing manifest. A manifest WITHOUT a self-CRC (a frozen
    converted directory that has never been appended to) passes — the
    append protocol is what introduces the CRC."""
    mpath = os.path.join(directory, MANIFEST_NAME)
    if not os.path.exists(mpath):
        raise StreamError(f"{directory}: no {MANIFEST_NAME} — not a "
                          "shard dataset")
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        raise TornPublishError(
            f"{mpath}: unparseable manifest ({e}) — a torn publish; "
            "readers must hold their last admitted view") from e
    if "manifest_crc" in manifest:
        verify_manifest_crc(manifest, where=mpath)
    return manifest


# ---------------------------------------------------------------------
# the writer side: crash-safe append
# ---------------------------------------------------------------------

def _read_writer_manifest(directory: str) -> dict:
    """The manifest a WRITER resumes from: the live one when intact,
    else the ``.prev`` backup (recovering a torn publish — the shard
    of the torn generation is orphaned on disk and will be re-written
    by the next append)."""
    try:
        return read_manifest_checked(directory)
    except TornPublishError as e:
        prev = os.path.join(directory, PREV_MANIFEST_NAME)
        if os.path.exists(prev):
            try:
                with open(prev) as fh:
                    manifest = json.load(fh)
                if "manifest_crc" in manifest:
                    verify_manifest_crc(manifest, where=prev)
                _log(f"recovering from torn publish via {prev} "
                     f"(generation {manifest.get('generation', 0)}); "
                     "re-publishing will repair the live manifest")
                return manifest
            except (OSError, json.JSONDecodeError, TornPublishError):
                pass
        raise StreamError(
            f"{directory}: manifest is torn and no intact "
            f"{PREV_MANIFEST_NAME} backup exists — {e}") from e


def append_shard(directory: str, x: np.ndarray, y: np.ndarray) -> dict:
    """Append one shard to a live log, crash-safely.

    Protocol (module docstring): atomic shard write -> atomic backup
    of the current manifest to ``.prev`` -> atomic publish of the new
    manifest with ``generation + 1``, the shard entry stamped with the
    generation that published it, and a fresh self-CRC. ``x`` may hold
    up to ``rows_per_shard`` rows (a partial final batch publishes as
    a partial shard — the reader's offsets are cumulative). Returns
    the published manifest. The ``DPSVM_FAULT_LIVE_*`` hooks fire at
    their documented points (faultinject module docstring)."""
    manifest = _read_writer_manifest(directory)
    x = np.ascontiguousarray(np.asarray(x, np.float32))
    ydt = (np.float32 if manifest.get("label_dtype") == "float32"
           else np.int32)
    y = np.ascontiguousarray(np.asarray(y, ydt))
    if x.ndim != 2 or x.shape[1] != int(manifest["d"]):
        raise ValueError(
            f"appended shard must be (rows, {manifest['d']}), got "
            f"{x.shape}")
    if y.shape != (x.shape[0],):
        raise ValueError(f"labels must be ({x.shape[0]},), got "
                         f"{y.shape}")
    rows = int(x.shape[0])
    rps = int(manifest["rows_per_shard"])
    if not (1 <= rows <= rps):
        raise ValueError(
            f"appended shard holds {rows} row(s); the log's geometry "
            f"admits 1..{rps} (rows_per_shard={rps} — fixed shapes "
            "are the zero-retrace contract)")
    if not np.isfinite(x).all():
        bad = np.argwhere(~np.isfinite(x))[0]
        raise ValueError(f"appended shard has a non-finite value at "
                         f"row {int(bad[0])}, column {int(bad[1])} — "
                         "rejected before it can poison the log")

    k = len(manifest["shards"])
    gen = int(manifest.get("generation", 0)) + 1
    fname = shard_filename(k)
    _write_shard_atomic(os.path.join(directory, fname), x, y)

    plan = faultinject.current()
    if plan is not None and plan.live_append_begin():
        # Writer died with the shard durable but un-published: the
        # orphan file is invisible to readers (not in any manifest)
        # and the next append overwrites it at the same index.
        raise WriterCrashError(
            f"writer crashed after shard {fname} was durable, before "
            "its publish (injected)")

    new = dict(manifest)
    new["shards"] = list(manifest["shards"]) + [{
        "file": fname, "rows": rows, "crc32": int(payload_crc(x, y)),
        "generation": gen,
    }]
    new["n"] = int(manifest["n"]) + rows
    new["generation"] = gen
    stats = dict(manifest.get("stats") or {})
    if stats.get("feature_min") is not None:
        fmin = np.minimum(np.asarray(stats["feature_min"], np.float32),
                          x.min(axis=0))
        fmax = np.maximum(np.asarray(stats["feature_max"], np.float32),
                          x.max(axis=0))
        stats["feature_min"] = [float(np.float32(v)) for v in fmin]
        stats["feature_max"] = [float(np.float32(v)) for v in fmax]
        stats["label_min"] = min(float(stats["label_min"]),
                                 float(y.min()))
        stats["label_max"] = max(float(stats["label_max"]),
                                 float(y.max()))
        new["stats"] = stats
    return publish_manifest(directory, new, previous=manifest)


def publish_manifest(directory: str, manifest: dict, *,
                     previous: Optional[dict] = None) -> dict:
    """The atomic generation swap: back the current good manifest up
    to ``.prev``, stamp the self-CRC, replace ``manifest.json``. The
    fault hooks simulate the two writer failure modes here: a TORN
    publish (half the bytes written in place, then crash — the
    non-atomic-filesystem model) and a STALE publish (CRC-valid bytes
    whose generation did not advance — a replayed/split-brain
    writer)."""
    mpath = os.path.join(directory, MANIFEST_NAME)
    if previous is not None:
        _write_json_atomic(os.path.join(directory, PREV_MANIFEST_NAME),
                           previous)
    plan = faultinject.current()
    mode = plan.live_publish_mode() if plan is not None else "clean"
    if mode == "stale":
        stale = dict(manifest)
        stale["generation"] = int(previous.get("generation", 0)
                                  if previous is not None else 0)
        stale["manifest_crc"] = manifest_crc(stale)
        _write_json_atomic(mpath, stale)
        return stale
    manifest = dict(manifest)
    manifest["manifest_crc"] = manifest_crc(manifest)
    if mode == "torn":
        raw = (json.dumps(manifest, sort_keys=True, indent=1)
               + "\n").encode()
        with open(mpath, "wb") as fh:      # deliberately NON-atomic
            fh.write(raw[: len(raw) // 2])
        raise WriterCrashError(
            "writer crashed mid-publish: manifest.json is torn "
            "(injected); readers hold their view, the restarted "
            "writer recovers from .prev")
    _write_json_atomic(mpath, manifest)
    return manifest


# ---------------------------------------------------------------------
# the reader side: the watcher
# ---------------------------------------------------------------------

class ShardLogWatcher:
    """Polling reader over a live shard log.

    Wraps a ``ShardedDataset`` handle (whose view it grows in place —
    every consumer holding the handle sees the admitted shards) and
    enforces the reader rules of the module docstring. ``poll()`` is
    pure host I/O: one manifest read per call, shard payloads only
    touched when ``verify_appends`` asks for an integrity read of the
    newly admitted shards.

    Counters: ``torn_observed`` / ``stale_observed`` count the
    publishes this reader REFUSED (the drill's assertion surface);
    ``admitted_shards``/``admitted_rows`` total what it accepted.
    """

    def __init__(self, ds: ShardedDataset, *,
                 on_bad_shard: str = "raise",
                 allow_nonfinite: bool = False,
                 on_event: Optional[Callable[..., None]] = None,
                 verify_appends: bool = True):
        self.ds = ds
        self.on_bad_shard = on_bad_shard
        self.allow_nonfinite = allow_nonfinite
        self.verify_appends = verify_appends
        self._on_event = on_event
        self.torn_observed = 0
        self.stale_observed = 0
        self.admitted_shards = 0
        self.admitted_rows = 0

    @property
    def generation(self) -> int:
        return self.ds.generation

    def _emit(self, event: str, **extra) -> None:
        # No default sink: a standalone watcher (doctor probes, tests,
        # ad-hoc polling) must NOT feed the training driver's global
        # pending-event queue — its events would leak into whatever
        # trace the process opens next. Consumers that want the events
        # pass a sink: live training wires queue_trace_event, the
        # drill wires its serving trace.
        if self._on_event is not None:
            self._on_event(event, **extra)

    def _read_manifest_retrying(self) -> Optional[dict]:
        from dpsvm_tpu.data.stream import (DEFAULT_IO_BACKOFF_S,
                                           DEFAULT_IO_RETRIES)
        retries = int(os.environ.get("DPSVM_IO_RETRIES",
                                     str(DEFAULT_IO_RETRIES)))
        backoff = float(os.environ.get("DPSVM_IO_RETRY_BACKOFF_S",
                                       str(DEFAULT_IO_BACKOFF_S)))
        for attempt in range(retries + 1):
            try:
                return read_manifest_checked(self.ds.directory)
            except TornPublishError:
                # A torn manifest is a writer mid-crash (or mid-write):
                # hold the admitted view. No retry loop here — the next
                # poll is the retry, at the caller's cadence.
                self.torn_observed += 1
                _log(f"{self.ds.directory}: torn publish observed "
                     f"(#{self.torn_observed}); holding generation "
                     f"{self.ds.generation}")
                return None
            except (OSError, StreamError) as e:
                if attempt >= retries or not isinstance(e, OSError):
                    raise
                wait = backoff * (2.0 ** attempt)
                _log(f"transient manifest read failure ({e}); retry "
                     f"{attempt + 1}/{retries} in {wait:g}s")
                time.sleep(wait)
        return None

    def poll(self) -> List[int]:
        """One watch cycle. Returns the newly admitted shard indices
        (empty when the log did not durably advance). Emits one
        ``append_admitted`` event per admitted shard (shard,
        generation, rows — the schema-required keys)."""
        manifest = self._read_manifest_retrying()
        if manifest is None:
            return []
        gen = int(manifest.get("generation", 0))
        if gen < self.ds.generation:
            # A replayed (stale) generation: never regress. Note it
            # and hold — a split-brain writer's publish must not
            # un-admit data training already consumed.
            self.stale_observed += 1
            _log(f"{self.ds.directory}: manifest generation {gen} < "
                 f"admitted {self.ds.generation}; refusing to regress "
                 f"(#{self.stale_observed})")
            return []
        if gen == self.ds.generation:
            if len(manifest["shards"]) != len(self.ds.shards):
                # Same generation, different content — the stale-
                # generation writer bug: CRC-valid bytes that changed
                # the log without advancing the counter.
                self.stale_observed += 1
                _log(f"{self.ds.directory}: generation {gen} manifest "
                     f"holds {len(manifest['shards'])} shard(s) vs "
                     f"the admitted {len(self.ds.shards)} at the SAME "
                     "generation; refusing a non-advancing publish "
                     f"(#{self.stale_observed})")
            return []
        # Cross-host admission barrier (resilience/hostgroup.py,
        # docs/DISTRIBUTED.md "Multi-host"): publish the durably
        # OBSERVED generation, commit only at the minimum the whole
        # group has published. Identity outside a host group. A peer
        # that has not yet observed `gen` — straggler, still
        # compiling, dead — pins the commit to the group floor, so no
        # host ever trains on rows another host has not admitted (the
        # per-host divisor/step-size math would silently desync).
        from dpsvm_tpu.resilience import hostgroup
        commit = hostgroup.admission_barrier(gen, self.ds.generation)
        if commit <= self.ds.generation:
            return []
        if commit < gen:
            from dpsvm_tpu.data.stream import pin_manifest_generation
            manifest = pin_manifest_generation(manifest, commit)
            gen = commit
        admitted = self.ds.admit_manifest(manifest)
        for k in admitted:
            meta = self.ds.shards[k]
            if self.verify_appends:
                got = self.ds.read_shard_checked(
                    k, on_bad_shard=self.on_bad_shard,
                    allow_nonfinite=self.allow_nonfinite)
                if got is None:          # quarantined under the policy
                    continue
            self.admitted_shards += 1
            self.admitted_rows += int(meta["rows"])
            self._emit("append_admitted", shard=int(k),
                       generation=int(meta.get("generation", gen)),
                       rows=int(meta["rows"]))
        return admitted

    def wait_for_generation(self, generation: int, *,
                            timeout_s: float = 30.0,
                            interval_s: float = 0.02) -> bool:
        """Poll until the admitted generation reaches ``generation``
        (True) or the deadline passes (False) — the drill's writer/
        reader rendezvous."""
        deadline = time.monotonic() + timeout_s
        while self.ds.generation < generation:
            if time.monotonic() > deadline:
                return False
            self.poll()
            if self.ds.generation < generation:
                time.sleep(interval_s)
        return True


# ---------------------------------------------------------------------
# subprocess writer (the concurrent writer/reader tests + the drill)
# ---------------------------------------------------------------------

def writer_main(argv: Optional[List[str]] = None) -> int:
    """``python -m dpsvm_tpu.data.live DIR --append N --rows R`` — a
    real writer process appending synthetic blob shards to a live log
    (the concurrent writer/reader interleaving tests SIGKILL it
    mid-stream; the ``DPSVM_FAULT_LIVE_*`` env knobs apply). Prints
    one ``APPENDED k generation g`` line per publish."""
    import argparse

    p = argparse.ArgumentParser(prog="python -m dpsvm_tpu.data.live")
    p.add_argument("directory")
    p.add_argument("--append", type=int, default=4,
                   help="how many shards to append")
    p.add_argument("--rows", type=int, default=0,
                   help="rows per appended shard (0 = the log's "
                        "rows_per_shard)")
    p.add_argument("--seed", type=int, default=100)
    p.add_argument("--d", type=int, default=0,
                   help="feature width (0 = the log's)")
    p.add_argument("--interval-ms", type=float, default=0.0)
    p.add_argument("--shift", type=float, default=0.0,
                   help="mean shift applied to shards the "
                        "LIVE_SHIFT_AT_SHARD hook selects (or all, "
                        "when --shift-all)")
    p.add_argument("--shift-all", action="store_true")
    args = p.parse_args(argv)

    manifest = _read_writer_manifest(args.directory)
    d = args.d or int(manifest["d"])
    rows = args.rows or int(manifest["rows_per_shard"])
    rng = np.random.default_rng(args.seed)
    plan = faultinject.current()
    for i in range(args.append):
        x = rng.standard_normal((rows, d)).astype(np.float32)
        y = np.where(x[:, 0] + 0.25 * x[:, 1] > 0, 1, -1)
        shifted = (args.shift_all
                   or (plan is not None and plan.live_shift_now(i)))
        if shifted and args.shift:
            x = x + np.float32(args.shift)
            # The shifted world keeps its labels consistent with the
            # shifted inputs (concept stays, covariates move) — what a
            # retrain can actually recover from.
            y = np.where((x[:, 0] - args.shift)
                         + 0.25 * (x[:, 1] - args.shift) > 0, 1, -1)
        m = append_shard(args.directory, x, y)
        print(f"APPENDED {len(m['shards']) - 1} generation "
              f"{m['generation']}", flush=True)
        if args.interval_ms:
            time.sleep(args.interval_ms / 1000.0)
    return 0


if __name__ == "__main__":
    sys.exit(writer_main())

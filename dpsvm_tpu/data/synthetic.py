"""Synthetic dataset fixtures.

The reference repo's datasets (adult/a9a, MNIST even-odd, covtype — see
``Makefile:74-86``) were stripped from the snapshot (``.MISSING_LARGE_BLOBS``),
so tests and benchmarks here run on deterministic synthetic data instead:
Gaussian blobs (linearly separable-ish), XOR (needs the RBF kernel), and an
MNIST-shaped generator for benchmarking at the reference's headline scale
(60000 x 784, ``README.md:23``).

All generators return (x: (n, d) float32, y: (n,) int32 in {+1, -1}).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def make_blobs(n: int = 200, d: int = 4, seed: int = 0,
               separation: float = 2.0) -> Tuple[np.ndarray, np.ndarray]:
    """Two Gaussian clusters at +/- separation/2 along each axis."""
    rng = np.random.default_rng(seed)
    n_pos = n // 2
    n_neg = n - n_pos
    center = np.full((d,), separation / 2.0, dtype=np.float32)
    xp = rng.normal(loc=center, scale=1.0, size=(n_pos, d))
    xn = rng.normal(loc=-center, scale=1.0, size=(n_neg, d))
    x = np.concatenate([xp, xn]).astype(np.float32)
    y = np.concatenate([np.ones(n_pos), -np.ones(n_neg)]).astype(np.int32)
    perm = rng.permutation(n)
    return x[perm], y[perm]


def make_xor(n: int = 200, seed: int = 0,
             noise: float = 0.15) -> Tuple[np.ndarray, np.ndarray]:
    """2-D XOR: not linearly separable, exercises the RBF kernel."""
    rng = np.random.default_rng(seed)
    signs = rng.integers(0, 2, size=(n, 2)) * 2 - 1
    x = signs + rng.normal(scale=noise, size=(n, 2))
    y = (signs[:, 0] * signs[:, 1]).astype(np.int32)
    return x.astype(np.float32), y


def make_mnist_like(n: int = 60_000, d: int = 784, seed: int = 0,
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """MNIST-shaped benchmark data: sparse-ish [0, 1] features, two classes.

    Statistically shaped like /255-scaled MNIST pixels (most entries zero,
    the rest in (0, 1]) with a class-dependent mean shift so the problem is
    learnable but keeps a nontrivial SV set — good for timing SMO iterations
    at the reference benchmark scale (README.md:23).
    """
    rng = np.random.default_rng(seed)
    y = (rng.integers(0, 2, size=n) * 2 - 1).astype(np.int32)
    x = np.zeros((n, d), dtype=np.float32)
    # ~20% nonzero pixels, like centered digit images.
    mask = rng.random((n, d)) < 0.2
    vals = rng.random((n, d), dtype=np.float32)
    x[mask] = vals[mask]
    # Class signal on a fixed feature subset — chosen independently of
    # `seed` so differently-seeded draws (train/test splits) come from the
    # SAME underlying problem and generalization is measurable.
    sig = np.random.default_rng(777).choice(d, size=max(1, d // 16),
                                            replace=False)
    x[:, sig] += 0.25 * y[:, None].astype(np.float32)
    np.clip(x, 0.0, 1.0, out=x)
    return x, y


def make_planted(n: int, d: int, gamma: float, seed: int = 0,
                 noise: float = 0.02, latent_dim: int = 16,
                 clusters_per_class: int = 8,
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Benchmark data with a planted decision boundary tuned to gamma.

    ``make_mnist_like`` draws i.i.d.-ish features, and in high dimension
    every pair of such points is nearly equidistant — at the reference's
    benchmark gammas that makes K approximately the identity matrix, so
    SMO's global progress stalls and some configs cannot converge at all
    (the round-2 verdict's "benchmark fidelity" finding). Real data is
    nothing like that: it lives near a low-dimensional manifold, so
    kernel values span (0, 1).

    This generator plants that structure deliberately, scaled to the
    gamma it will be trained with:

      * points live on a ``latent_dim``-dimensional subspace embedded in
        d dims by a random orthonormal map (so d only adds cost, not
        distance — exactly like pixel space),
      * each class is a mixture of ``clusters_per_class`` Gaussians;
        the latent scale is chosen so typical WITHIN-cluster squared
        distance is about 1/gamma (kernel values ~e^-1) and
        between-cluster distances are a few times that — K has real
        off-diagonal mass and the problem has geometry worth learning,
      * a ``noise`` fraction of labels is flipped uniformly; those
        points become bounded SVs (alpha = C), giving the optimizer the
        same bounded/free SV mix real benchmarks have. SV fraction is
        therefore controllable: about noise + the margin population.

    Every returned dataset is convergent at its own (gamma, reasonable
    C): asserted at CI scale by tests/test_data.py and measured at the
    reference shapes in docs/PERF.md.
    """
    x, assign, rng = _planted_latent(n, d, gamma, 2 * clusters_per_class,
                                     latent_dim, seed)
    y = np.where(assign < clusters_per_class, 1, -1).astype(np.int32)
    flip = rng.random(n) < noise
    y = np.where(flip, -y, y).astype(np.int32)
    return x, y


def _planted_latent(n: int, d: int, gamma: float, n_clusters: int,
                    latent_dim: int, seed: int):
    """(x, cluster assignment, rng) — the gamma-calibrated latent
    cluster geometry shared by the binary and multiclass planted
    generators. The calibration lives HERE, once: cluster centers on a
    latent sphere of radius r_c, cluster noise sigma, tuned against
    REAL image data (sklearn digits at its benchmark gamma:
    off-diagonal K has median ~0.3, p99 ~0.76) via within-cluster
    E||xi-xj||^2 = 2*latent_dim*sigma^2 := 0.7/gamma (K ~ 0.5) and
    cross-cluster ~ 1.5/gamma (K ~ 0.22); asserted against digits by
    tests/test_data.py::TestPlantedCalibration. The returned rng has
    consumed the generation draws, so callers' label-noise draws stay
    reproducible per (shape, seed)."""
    if latent_dim > d:
        latent_dim = d
    rng = np.random.default_rng(seed)
    sigma = float(np.sqrt(0.35 / (latent_dim * gamma)))
    r_c = float(np.sqrt(0.4 / gamma))
    centers = rng.normal(size=(n_clusters, latent_dim))
    centers *= r_c / np.linalg.norm(centers, axis=1, keepdims=True)
    assign = rng.integers(0, n_clusters, size=n)
    z = centers[assign] + sigma * rng.normal(size=(n, latent_dim))
    # Embed isometrically: random orthonormal rows (QR of a Gaussian).
    basis, _ = np.linalg.qr(rng.normal(size=(d, latent_dim)))
    x = (z @ basis.T).astype(np.float32)
    return x, assign, rng


def make_planted_multiclass(n: int, d: int, gamma: float, k: int = 10,
                            seed: int = 0, noise: float = 0.02,
                            latent_dim: int = 16,
                            clusters_per_class: int = 4,
                            ) -> Tuple[np.ndarray, np.ndarray]:
    """K-class variant of ``make_planted``: the same gamma-calibrated
    latent cluster geometry, with ``clusters_per_class`` clusters per
    class and integer labels 0..k-1. ``noise`` flips a fraction of
    labels to a uniformly random OTHER class (the multiclass analog of
    the binary flip — those points become bounded SVs of their pairs).
    Used by the OvO benchmarks (benchmarks/ovo_bench.py)."""
    x, assign, rng = _planted_latent(n, d, gamma, k * clusters_per_class,
                                     latent_dim, seed)
    y = (assign // clusters_per_class).astype(np.int32)
    flip = rng.random(n) < noise
    shift = rng.integers(1, k, size=n)
    y = np.where(flip, (y + shift) % k, y).astype(np.int32)
    return x, y


def save_csv(path: str, x: np.ndarray, y: np.ndarray) -> None:
    """Write (x, y) in the reference's dense CSV format (parse.cpp).
    Integer labels write as ints (reference parity); float labels
    (regression targets) keep their value."""
    int_labels = np.issubdtype(np.asarray(y).dtype, np.integer)
    with open(path, "w") as f:
        for i in range(x.shape[0]):
            row = ",".join(repr(float(v)) for v in x[i])
            lab = int(y[i]) if int_labels else repr(float(y[i]))
            f.write(f"{lab},{row}\n")

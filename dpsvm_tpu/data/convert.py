"""Dataset format converters.

Python-3 equivalents of the reference's Py2 scripts:

* ``libsvm_to_dense_csv`` — ``scripts/convert_adult.py:23-33``: libsvm
  sparse lines ``<label> idx:val ...`` (1-based indices) to the dense
  ``label,f1,...,fd`` CSV the loaders expect, labels normalized to +/-1.
* ``mnist_to_odd_even_csv`` — ``scripts/convert_mnist_to_odd_even.py:23-29``:
  a ``digit,p1,...,p784`` CSV to an even/odd +/-1 problem with pixels
  scaled into [0, 1] by /255.
"""

from __future__ import annotations

from typing import Optional


def libsvm_to_dense_csv(src: str, dst: str,
                        num_attributes: Optional[int] = None) -> int:
    """Convert a libsvm sparse file to dense CSV. Returns rows written.

    When num_attributes is None it is inferred as the max feature index
    seen in the file (the adult/a9a converter hard-codes 123). Labels
    are normalized to +/-1 by sign, exactly like the reference script
    (``convert_adult.py:23``); loading without that normalization is
    what ``loader.load_libsvm`` (the shared parser used here) is for.
    """
    import numpy as np

    from dpsvm_tpu.data.loader import load_libsvm

    x, y = load_libsvm(src, num_attributes=num_attributes)
    y = np.where(y > 0, 1, -1)
    with open(dst, "w") as out:
        for label, row in zip(y, x):
            out.write(f"{int(label)}," + ",".join(map(str, row)) + "\n")
    return len(y)


def mnist_to_odd_even_csv(src: str, dst: str, scale: float = 255.0,
                          has_header: bool = False) -> int:
    """Convert a digit-labelled CSV to the even(+1)/odd(-1) binary problem."""
    n = 0
    with open(src) as f, open(dst, "w") as out:
        for i, line in enumerate(f):
            if has_header and i == 0:
                continue
            parts = line.strip().split(",")
            if len(parts) < 2:
                continue
            digit = int(float(parts[0]))
            label = 1 if digit % 2 == 0 else -1
            pixels = (repr(float(p) / scale) for p in parts[1:])
            out.write(f"{label}," + ",".join(pixels) + "\n")
            n += 1
    return n

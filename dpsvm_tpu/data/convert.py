"""Dataset format converters.

Python-3 equivalents of the reference's Py2 scripts:

* ``libsvm_to_dense_csv`` — ``scripts/convert_adult.py:23-33``: libsvm
  sparse lines ``<label> idx:val ...`` (1-based indices) to the dense
  ``label,f1,...,fd`` CSV the loaders expect, labels normalized to +/-1.
* ``mnist_to_odd_even_csv`` — ``scripts/convert_mnist_to_odd_even.py:23-29``:
  a ``digit,p1,...,p784`` CSV to an even/odd +/-1 problem with pixels
  scaled into [0, 1] by /255.
"""

from __future__ import annotations

from typing import Optional


def libsvm_to_dense_csv(src: str, dst: str,
                        num_attributes: Optional[int] = None) -> int:
    """Convert a libsvm sparse file to dense CSV. Returns rows written.

    When num_attributes is None it is inferred as the max feature index
    seen in the file (the adult/a9a converter hard-codes 123).
    """
    rows = []
    max_idx = 0
    with open(src) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            label = 1 if float(parts[0]) > 0 else -1
            feats = {}
            for tok in parts[1:]:
                idx_s, val_s = tok.split(":")
                idx = int(idx_s)
                feats[idx] = float(val_s)
                max_idx = max(max_idx, idx)
            rows.append((label, feats))
    d = num_attributes if num_attributes is not None else max_idx
    with open(dst, "w") as out:
        for label, feats in rows:
            dense = (repr(feats.get(j, 0.0)) for j in range(1, d + 1))
            out.write(f"{label}," + ",".join(dense) + "\n")
    return len(rows)


def mnist_to_odd_even_csv(src: str, dst: str, scale: float = 255.0,
                          has_header: bool = False) -> int:
    """Convert a digit-labelled CSV to the even(+1)/odd(-1) binary problem."""
    n = 0
    with open(src) as f, open(dst, "w") as out:
        for i, line in enumerate(f):
            if has_header and i == 0:
                continue
            parts = line.strip().split(",")
            if len(parts) < 2:
                continue
            digit = int(float(parts[0]))
            label = 1 if digit % 2 == 0 else -1
            pixels = (repr(float(p) / scale) for p in parts[1:])
            out.write(f"{label}," + ",".join(pixels) + "\n")
            n += 1
    return n

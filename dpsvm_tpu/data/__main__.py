"""``python -m dpsvm_tpu.data`` — the streaming-data selfcheck CI gate
(sibling of ``python -m dpsvm_tpu.telemetry``, ``-m
dpsvm_tpu.resilience``, ``-m dpsvm_tpu.serving`` and ``-m
dpsvm_tpu.approx``)."""

import sys

from dpsvm_tpu.data import main

sys.exit(main())

"""Out-of-core datasets: integrity-manifested streaming shards.

Everything upstream of this module assumes X fits in host RAM at once —
the binding constraint at scale ("Recipe for Fast Large-scale SVM
Training", arXiv:2207.01016) and the failure domain practical
deployments actually die in ("Parallel SVMs in Practice",
arXiv:1404.1066: a truncated file, a corrupt row, a transient NFS
hiccup, an OOM an hour in). This module is the data layer's fault
model, built on the same integrity pattern ``utils/checkpoint.py``
uses for solver state:

* **Shard format** — a dataset is a DIRECTORY of fixed-shape ``.npz``
  chunk shards (``shard-00000.npz`` holding ``x`` (rows, d) float32
  and ``y`` (rows,) int32/float32) plus one ``manifest.json`` carrying
  per-shard payload CRC32s, row counts, dtype/width, and running
  scaling stats (per-feature min/max — what ``dpsvm scale`` fits).
  Fixed ``rows_per_shard`` means every consumer runs ONE compiled
  program shape over every shard — zero retraces in steady state.
* **Resumable conversion** — ``convert_to_shards`` (CLI ``dpsvm
  convert shards``) streams any loader-supported file (dense CSV /
  libsvm, sniffed) row-by-row into shards, never materializing the
  dataset, and checkpoints its cursor (``convert.cursor.json``,
  atomic) after every durable shard: a killed multi-hour conversion
  resumes at the last durable shard and lands a byte-identical
  manifest (no timestamps in the manifest — it is a pure function of
  the source bytes and the shard geometry).
* **Quarantine-and-continue ingest** — every shard read verifies the
  manifest CRC and row finiteness. A bad shard either raises
  (``on_bad_shard="raise"``, the default) or is QUARANTINED
  (``"quarantine"``): recorded on the handle, skipped by every later
  pass, surfaced as a ``quarantine`` trace event naming the shard and
  reason, and bounded by ``max_bad_fraction`` — losing a quarter of
  the dataset is an abort, not a silently weaker model. Transient
  ``OSError`` reads get bounded retry-with-backoff
  (``DPSVM_IO_RETRIES`` / ``DPSVM_IO_RETRY_BACKOFF_S``). All of it is
  CI-testable on CPU via the deterministic ``DPSVM_FAULT_IO_*`` hooks
  (resilience/faultinject.py).
* **Memory-budget guards** — ``check_materialize_budget`` /
  ``check_stream_budget`` refuse UP FRONT, naming the shard-count
  math (how many rows the budget admits, what ``rows_per_shard``
  would fit), instead of OOMing an hour into a run. The train/test
  CLIs expose them as ``--mem-budget-mb``.

``loader.load_dataset`` recognizes a shard directory, so CV, ``dpsvm
test`` and serving warmup all read shard sets through the ONE source
API they already use; training on data that never fully materializes
is ``approx/primal.fit_approx_stream`` (docs/DATA.md, docs/APPROX.md).

Ingest metrics (``dpsvm_data_*`` series: shards read / quarantined,
retries, ingest seconds, rows) feed the process metric registry
host-side — zero extra device transfers, the same economics as the
training driver's packed-stats polls. No jax import at module level:
conversion and integrity checking must run on a machine with no
accelerator.
"""

from __future__ import annotations

import io
import json
import os
import sys
import time
import zlib
from typing import Callable, Iterator, List, Optional, Tuple

import numpy as np

from dpsvm_tpu.resilience import faultinject

MANIFEST_NAME = "manifest.json"
CURSOR_NAME = "convert.cursor.json"
SHARD_FORMAT_VERSION = 1
DEFAULT_ROWS_PER_SHARD = 4096
#: abort threshold for quarantine-and-continue: once more than this
#: fraction of the dataset's rows sit in quarantined shards the ingest
#: aborts — a run that silently lost a quarter of its data is a worse
#: outcome than a loud failure.
MAX_BAD_FRACTION = 0.25
#: transient-read retry policy (env-overridable; the CI default keeps
#: drills fast while real deployments can afford longer backoff)
DEFAULT_IO_RETRIES = 3
DEFAULT_IO_BACKOFF_S = 0.05


class StreamError(Exception):
    """Base of every shard-dataset failure this module raises."""


class ShardCorruptError(StreamError):
    """A shard file exists but its payload cannot be trusted:
    unreadable/truncated .npz, wrong shapes, or a manifest CRC32
    mismatch. Names the shard and the reason."""

    def __init__(self, shard: int, reason: str):
        self.shard = int(shard)
        self.reason = str(reason)
        super().__init__(f"shard {shard}: {reason}")


class IngestAbortError(StreamError):
    """Quarantine-and-continue crossed the bounded bad fraction (or
    lost every shard): continuing would train on too little data."""


class MemBudgetError(StreamError):
    """An admission guard refused a load that would exceed the memory
    budget — raised BEFORE any allocation, with the shard math."""


def _log(msg: str) -> None:
    print(f"INGEST: {msg}", file=sys.stderr, flush=True)


def _metrics():
    from dpsvm_tpu.observability.metrics import DataMetrics
    return DataMetrics()


# ---------------------------------------------------------------------
# manifest / shard primitives
# ---------------------------------------------------------------------

def shard_filename(k: int) -> str:
    return f"shard-{k:05d}.npz"


def payload_crc(x: np.ndarray, y: np.ndarray) -> int:
    """CRC32 over the shard's array payloads (the checkpoint module's
    pattern): container-independent, so a re-written .npz with
    identical rows verifies identically."""
    crc = zlib.crc32(np.ascontiguousarray(x).tobytes())
    return zlib.crc32(np.ascontiguousarray(y).tobytes(), crc)


def is_shard_dir(path: str) -> bool:
    """True when ``path`` is a converted shard-dataset directory."""
    return (os.path.isdir(path)
            and os.path.exists(os.path.join(path, MANIFEST_NAME)))


def pin_manifest_generation(manifest: dict, generation: int) -> dict:
    """The manifest's view AS OF ``generation``: only shards whose
    entry generation (0 for converted seed shards, which predate the
    append protocol) is <= the target survive, and ``n``/``generation``
    shrink to match. Appends are strictly ordered, so this is exactly
    the shard set a reader at that generation had admitted — the
    resume contract of live streaming training (data/live.py)."""
    generation = int(generation)
    current = int(manifest.get("generation", 0))
    if generation >= current:
        return manifest
    kept = [s for s in manifest["shards"]
            if int(s.get("generation", 0)) <= generation]
    pinned = dict(manifest)
    pinned["shards"] = kept
    pinned["n"] = sum(int(s["rows"]) for s in kept)
    pinned["generation"] = generation
    pinned.pop("manifest_crc", None)     # the pinned view is derived,
    return pinned                        # not published bytes


def _write_json_atomic(path: str, obj: dict) -> None:
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as fh:
        # sort_keys + fixed separators: the manifest must be a pure
        # function of its content so a resumed conversion lands
        # byte-identical to an uninterrupted one.
        json.dump(obj, fh, sort_keys=True, indent=1)
        fh.write("\n")
    os.replace(tmp, path)


def _write_shard_atomic(path: str, x: np.ndarray, y: np.ndarray) -> None:
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as fh:
        np.savez(fh, x=x, y=y)
    os.replace(tmp, path)


class ShardedDataset:
    """Handle to one converted shard directory.

    Integrity state (the quarantine set) lives on the handle: a shard
    that failed its CRC once is skipped by every later pass in this
    process, and the bounded bad-fraction abort is evaluated against
    the manifest's total row count.
    """

    def __init__(self, directory: str, manifest: dict):
        self.directory = directory
        self.manifest = manifest
        self.n = int(manifest["n"])
        self.d = int(manifest["d"])
        self.rows_per_shard = int(manifest["rows_per_shard"])
        self.shards = list(manifest["shards"])
        self.float_labels = manifest.get("label_dtype") == "float32"
        self.quarantined: dict = {}          # shard idx -> reason
        self.max_bad_fraction = MAX_BAD_FRACTION
        #: live-log generation this handle's view corresponds to
        #: (docs/DATA.md "Live shard logs"); 0 on a frozen converted
        #: directory whose manifest predates the append protocol.
        self.generation = int(manifest.get("generation", 0))
        self._rebuild_offsets()

    def _rebuild_offsets(self) -> None:
        # Cumulative row offsets: converted directories only ever have
        # a short final shard, but a live log may hold partial shards
        # mid-stream (each append publishes whatever rows it has), so
        # the global index of shard k's first row is the running sum.
        off = 0
        self._offsets: List[int] = []
        for s in self.shards:
            self._offsets.append(off)
            off += int(s["rows"])

    # -- opening -------------------------------------------------------

    @classmethod
    def open(cls, directory: str,
             at_generation: Optional[int] = None) -> "ShardedDataset":
        mpath = os.path.join(directory, MANIFEST_NAME)
        if not os.path.exists(mpath):
            raise FileNotFoundError(
                f"{directory}: not a shard dataset (no {MANIFEST_NAME}; "
                "convert one with `dpsvm convert shards SRC DIR`)")
        try:
            with open(mpath) as fh:
                manifest = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            if os.path.exists(mpath + ".prev"):
                # The torn-publish signature of a LIVE log (a frozen
                # conversion has no .prev backup): a writer crashed
                # mid-publish. Readers hold their admitted view; the
                # restarted writer repairs (data/live.py).
                from dpsvm_tpu.data.live import TornPublishError
                raise TornPublishError(
                    f"{mpath}: unparseable manifest ({e}) beside a "
                    ".prev backup — a torn live-log publish; the "
                    "restarted writer repairs it on its next append"
                ) from e
            raise StreamError(f"{mpath}: unreadable manifest ({e}); "
                              "re-run the conversion") from e
        for key in ("format", "version", "n", "d", "rows_per_shard",
                    "shards"):
            if key not in manifest:
                raise StreamError(f"{mpath}: manifest missing {key!r}")
        if manifest["format"] != "dpsvm-shards":
            raise StreamError(f"{mpath}: format {manifest['format']!r} "
                              "is not 'dpsvm-shards'")
        if int(manifest["version"]) > SHARD_FORMAT_VERSION:
            raise StreamError(
                f"{mpath}: manifest version {manifest['version']} is "
                f"newer than this reader ({SHARD_FORMAT_VERSION})")
        rows = sum(int(s["rows"]) for s in manifest["shards"])
        if rows != int(manifest["n"]):
            raise StreamError(
                f"{mpath}: shard rows sum to {rows} but manifest says "
                f"n={manifest['n']} — truncated conversion? (a killed "
                "convert leaves a cursor, not a manifest)")
        if "manifest_crc" in manifest:
            # Live-log manifests carry a self-CRC (data/live.py): a
            # torn publish on a non-atomic filesystem must never be
            # mistaken for a dataset.
            from dpsvm_tpu.data.live import verify_manifest_crc
            verify_manifest_crc(manifest, where=mpath)
        if at_generation is not None:
            # Pin the view to the shards durable at (or before) that
            # generation — the resume path's exact re-admission
            # (docs/DATA.md "Live shard logs"): a checkpoint names the
            # generation it had consumed, and the resumed run must
            # start from the identical shard set before the watcher
            # re-admits anything newer.
            manifest = pin_manifest_generation(manifest, at_generation)
        return cls(directory, manifest)

    def admit_manifest(self, manifest: dict) -> List[int]:
        """Grow this handle's view to ``manifest`` (a strictly newer
        generation of the same log). The new manifest must EXTEND the
        current one — the common shard prefix byte-identical in
        file/rows/crc — because appends only ever add shards; a
        rewritten prefix is a corrupted (or foreign) log, not an
        append. Returns the newly admitted shard indices."""
        gen = int(manifest.get("generation", 0))
        if gen <= self.generation:
            raise StreamError(
                f"{self.directory}: admit_manifest generation {gen} "
                f"does not advance the current {self.generation}")
        new_shards = list(manifest["shards"])
        if len(new_shards) < len(self.shards):
            raise StreamError(
                f"{self.directory}: generation {gen} manifest holds "
                f"{len(new_shards)} shard(s), fewer than the admitted "
                f"{len(self.shards)} — a log never shrinks")
        for k, (old, new) in enumerate(zip(self.shards, new_shards)):
            if (old["file"] != new["file"]
                    or int(old["rows"]) != int(new["rows"])
                    or int(old["crc32"]) != int(new["crc32"])):
                raise StreamError(
                    f"{self.directory}: generation {gen} manifest "
                    f"REWROTE shard {k} ({old['file']}) — appends only "
                    "extend the log; refusing the admitted view")
        admitted = list(range(len(self.shards), len(new_shards)))
        self.manifest = manifest
        self.shards = new_shards
        self.n = int(manifest["n"])
        self.generation = gen
        self._rebuild_offsets()
        return admitted

    @property
    def n_shards(self) -> int:
        return len(self.shards)

    def shard_rows(self, k: int) -> int:
        return int(self.shards[k]["rows"])

    def shard_path(self, k: int) -> str:
        return os.path.join(self.directory, self.shards[k]["file"])

    def row_offset(self, k: int) -> int:
        """Global index of shard k's first row (shards are contiguous
        in append order; a live log may hold partial shards mid-
        stream, so this is the running sum, not k * rows_per_shard)."""
        return self._offsets[k]

    # -- reading -------------------------------------------------------

    def _read_shard_raw(self, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """One verified shard read: fault hooks -> npz load -> shape +
        dtype + CRC checks. Raises OSError on transient I/O trouble
        (retried by the caller) and ShardCorruptError on anything the
        manifest contract rejects."""
        meta = self.shards[k]
        path = self.shard_path(k)
        plan = faultinject.current()
        if plan is not None:
            plan.io_read_begin(k)          # slow-read + transient fail
        with open(path, "rb") as fh:
            raw = fh.read()
        if plan is not None and plan.io_truncate_now(k):
            raw = raw[: len(raw) // 2]
        try:
            with np.load(io.BytesIO(raw)) as npz:
                x = np.asarray(npz["x"])
                y = np.asarray(npz["y"])
        except Exception as e:
            raise ShardCorruptError(
                k, f"unreadable npz ({type(e).__name__}: {e}) — "
                   "truncated or damaged file") from e
        if plan is not None and plan.io_corrupt_now(k):
            x = x.copy()
            x.view(np.uint8)[0] ^= 1       # one flipped payload byte
        rows = int(meta["rows"])
        if x.shape != (rows, self.d) or y.shape != (rows,):
            raise ShardCorruptError(
                k, f"shape {x.shape}/{y.shape} does not match the "
                   f"manifest's ({rows}, {self.d})")
        if x.dtype != np.float32:
            raise ShardCorruptError(k, f"x dtype {x.dtype} != float32")
        got = payload_crc(x, y)
        if got != int(meta["crc32"]):
            raise ShardCorruptError(
                k, f"payload CRC mismatch (manifest {meta['crc32']}, "
                   f"file {got}) — bit rot or a torn write")
        return x, y

    def read_shard(self, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """Read + verify shard k with bounded transient-I/O retry.
        Raises ShardCorruptError / OSError; policy handling (quarantine
        vs raise) is ``read_shard_checked``."""
        retries = int(os.environ.get("DPSVM_IO_RETRIES",
                                     str(DEFAULT_IO_RETRIES)))
        backoff = float(os.environ.get("DPSVM_IO_RETRY_BACKOFF_S",
                                       str(DEFAULT_IO_BACKOFF_S)))
        metrics = _metrics()
        t0 = time.perf_counter()
        try:
            for attempt in range(retries + 1):
                try:
                    x, y = self._read_shard_raw(k)
                    metrics.on_read(rows=len(y))
                    return x, y
                except OSError as e:
                    if attempt >= retries:
                        raise
                    metrics.on_retry()
                    wait = backoff * (2.0 ** attempt)
                    _log(f"transient read failure on shard {k} "
                         f"({e}); retry {attempt + 1}/{retries} in "
                         f"{wait:g}s")
                    time.sleep(wait)
        finally:
            metrics.on_ingest_seconds(time.perf_counter() - t0)
        raise AssertionError("unreachable")

    def _check_finite(self, k: int, x: np.ndarray,
                      allow_nonfinite: bool) -> None:
        # Reduction-based fast path (no (rows, d) mask allocation):
        # min/max are finite iff every element is — NaN propagates
        # through min, inf survives max.
        if np.isfinite(x.min()) and np.isfinite(x.max()):
            return
        bad = np.argwhere(~np.isfinite(x))[0]
        row, col = int(bad[0]), int(bad[1])
        msg = (f"non-finite value at shard row {row}, column {col} "
               f"(dataset row {self.row_offset(k) + row})")
        if allow_nonfinite:
            _log(f"WARNING: shard {k}: {msg}; loading anyway "
                 "(--allow-nonfinite)")
            return
        raise ShardCorruptError(k, msg + " — rejected; pass "
                                "--allow-nonfinite to load anyway")

    def read_shard_checked(
            self, k: int, *, on_bad_shard: str = "raise",
            allow_nonfinite: bool = False,
            on_quarantine: Optional[Callable[[int, str], None]] = None,
    ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Policy-wrapped shard read: the one entry point training and
        materialization loop over.

        Returns ``(x, y)``, or None when the shard is (or becomes)
        quarantined under ``on_bad_shard="quarantine"``. A fresh
        quarantine is recorded on the handle, reported through
        ``on_quarantine`` (default: a ``quarantine`` trace event via
        the driver's pending-event queue + the metric registry), and
        checked against ``max_bad_fraction`` — crossing it raises
        ``IngestAbortError`` rather than training on a sliver."""
        if on_bad_shard not in ("raise", "quarantine"):
            raise ValueError(f"on_bad_shard must be 'raise' or "
                             f"'quarantine', got {on_bad_shard!r}")
        if k in self.quarantined:
            return None
        try:
            x, y = self.read_shard(k)
            self._check_finite(k, x, allow_nonfinite)
            return x, y
        except (ShardCorruptError, OSError) as e:
            reason = (e.reason if isinstance(e, ShardCorruptError)
                      else f"I/O error after retries: {e}")
            if on_bad_shard == "raise":
                if isinstance(e, ShardCorruptError):
                    raise
                raise ShardCorruptError(k, reason) from e
            self._note_quarantine(k, reason, on_quarantine)
            return None

    def _note_quarantine(self, k: int, reason: str,
                         on_quarantine=None) -> None:
        self.quarantined[k] = reason
        _metrics().on_quarantine()
        _log(f"QUARANTINED shard {k} ({self.shards[k]['file']}): "
             f"{reason}")
        if on_quarantine is not None:
            on_quarantine(k, reason)
        else:
            # Default consumer: the training driver's pending-event
            # queue, drained into the run trace at the next poll
            # boundary (or right after the manifest when queued before
            # the run starts).
            from dpsvm_tpu.solver.driver import queue_trace_event
            queue_trace_event("quarantine", shard=int(k),
                              reason=reason,
                              rows=self.shard_rows(k))
        bad_rows = sum(self.shard_rows(i) for i in self.quarantined)
        if bad_rows > self.max_bad_fraction * self.n:
            raise IngestAbortError(
                f"{len(self.quarantined)} quarantined shard(s) hold "
                f"{bad_rows}/{self.n} rows — past the "
                f"{self.max_bad_fraction:.0%} bad-fraction bound; "
                "refusing to continue on a sliver of the dataset "
                f"(quarantined: {sorted(self.quarantined)})")

    def iter_shards(self, *, on_bad_shard: str = "raise",
                    allow_nonfinite: bool = False
                    ) -> Iterator[Tuple[int, np.ndarray, np.ndarray]]:
        """One pass over every non-quarantined shard, policy applied."""
        for k in range(self.n_shards):
            got = self.read_shard_checked(
                k, on_bad_shard=on_bad_shard,
                allow_nonfinite=allow_nonfinite)
            if got is not None:
                yield k, got[0], got[1]

    def gather_rows(self, indices: np.ndarray) -> np.ndarray:
        """Rows at sorted global ``indices`` (the Nystrom landmark
        fetch): reads only the shards that hold them, strict policy —
        a landmark inside a corrupt shard is a hard error, because the
        feature map must be rebuildable bit-identically forever."""
        indices = np.asarray(indices, np.int64)
        out = np.empty((len(indices), self.d), np.float32)
        offsets = np.asarray(self._offsets, np.int64)
        by_shard: dict = {}
        for pos, gi in enumerate(indices):
            k = int(np.searchsorted(offsets, int(gi),
                                    side="right")) - 1
            by_shard.setdefault(k, []).append(pos)
        for k in sorted(by_shard):
            x, _ = self.read_shard(k)
            base = self.row_offset(k)
            for pos in by_shard[k]:
                out[pos] = x[int(indices[pos]) - base]
        return out

    def materialize(self, *, mem_budget_mb: Optional[float] = None,
                    on_bad_shard: str = "raise",
                    allow_nonfinite: bool = False
                    ) -> Tuple[np.ndarray, np.ndarray]:
        """Assemble the full (x, y) through the integrity path — the
        shard-directory branch of ``loader.load_dataset``, for
        consumers that genuinely need arrays (CV folds, the exact dual
        solvers, test evaluation). Budget-guarded up front; rows of
        quarantined shards are DROPPED from the result (count on
        stderr + quarantine events), bounded by ``max_bad_fraction``
        like every other pass."""
        check_materialize_budget(mem_budget_mb, n=self.n, d=self.d,
                                 what=self.directory)
        ydt = np.float32 if self.float_labels else np.int32
        xs: List[np.ndarray] = []
        ys: List[np.ndarray] = []
        for _k, x, y in self.iter_shards(on_bad_shard=on_bad_shard,
                                         allow_nonfinite=allow_nonfinite):
            xs.append(x)
            ys.append(np.asarray(y, ydt))
        if not xs:
            raise IngestAbortError(
                f"{self.directory}: every shard is quarantined")
        dropped = self.n - sum(len(y) for y in ys)
        if dropped:
            _log(f"materialized {self.directory} minus {dropped} "
                 f"row(s) in {len(self.quarantined)} quarantined "
                 f"shard(s)")
        return np.concatenate(xs), np.concatenate(ys)

    def verify(self, spot: Optional[int] = None) -> List[str]:
        """Integrity sweep for `dpsvm doctor`: CRC-verify ``spot``
        shards (first / middle / last; None = all). Returns problem
        strings (empty = healthy) without mutating quarantine state."""
        if spot is None or self.n_shards <= spot:
            picks = list(range(self.n_shards))
        else:
            picks = sorted({0, self.n_shards // 2, self.n_shards - 1})
        problems = []
        for k in picks:
            try:
                self._read_shard_raw(k)
            except (ShardCorruptError, OSError) as e:
                problems.append(f"shard {k} "
                                f"({self.shards[k]['file']}): {e}")
        return problems


# ---------------------------------------------------------------------
# memory-budget admission guards
# ---------------------------------------------------------------------

def _mb(nbytes: float) -> float:
    return nbytes / (1024.0 * 1024.0)


def _fmt_mb(nbytes: float) -> str:
    """MiB with enough precision that tiny datasets never render as
    '0.0 MiB' in a refusal message."""
    mb = _mb(nbytes)
    return f"{mb:.1f} MiB" if mb >= 0.95 else f"{mb:.3g} MiB"


def materialize_bytes(n: int, d: int) -> int:
    """Host bytes a fully materialized (x, y) costs: the f32 matrix
    plus a 4-byte label lane."""
    return n * d * 4 + n * 4


def stream_peak_bytes(rows_per_shard: int, d: int,
                      feat_dim: int = 0) -> int:
    """Peak host bytes of the streaming train path: one raw shard
    block beside its featurized block (+ label/weight lanes). The
    feature block lives on device too, but host peak is what the
    admission guard bounds."""
    return rows_per_shard * (d + feat_dim) * 4 + rows_per_shard * 8


def budget_admit_rows(budget_mb: float, d: int) -> int:
    """How many d-wide rows a ``budget_mb`` materialization admits —
    the inverse of ``materialize_bytes``, shared by the refusal math
    below and the cascade's auto screen-cap (solver/cascade.py: the
    screened subproblem must be a materialization that fits)."""
    return max(int(budget_mb * 1024 * 1024 / (d * 4 + 4)), 1)


def check_materialize_budget(budget_mb: Optional[float], *, n: int,
                             d: int, what: str = "dataset") -> None:
    """Refuse a full materialization that cannot fit ``budget_mb`` —
    up front, naming the shard-count math that WOULD fit."""
    if not budget_mb:
        return
    need = materialize_bytes(n, d)
    if _mb(need) <= float(budget_mb):
        return
    admits = budget_admit_rows(budget_mb, d)
    rps = max(min(DEFAULT_ROWS_PER_SHARD, admits // 4), 1)
    n_shards = -(-n // rps)
    raise MemBudgetError(
        f"{what}: materializing {n} rows x {d} f32 needs "
        f"{_fmt_mb(need)} but --mem-budget-mb {budget_mb:g} admits "
        f"~{admits} rows. Stream it instead: `dpsvm convert shards SRC "
        f"DIR --rows-per-shard {rps}` -> {n_shards} shards "
        f"(ceil({n}/{rps})), then train --solver approx-rff (or "
        f"--solver cascade for exact-quality decisions) on the shard "
        f"directory (per-shard peak "
        f"~{_fmt_mb(stream_peak_bytes(rps, d))})")


def check_stream_budget(budget_mb: Optional[float], *, n: int, d: int,
                        rows_per_shard: int, feat_dim: int = 0,
                        what: str = "dataset") -> None:
    """Admission guard for the streaming train path: the PER-SHARD
    working set must fit the budget; the refusal names the
    rows_per_shard that would."""
    if not budget_mb:
        return
    need = stream_peak_bytes(rows_per_shard, d, feat_dim)
    if _mb(need) <= float(budget_mb):
        return
    per_row = (d + feat_dim) * 4 + 8
    fit_rows = max(int(budget_mb * 1024 * 1024 / per_row), 1)
    raise MemBudgetError(
        f"{what}: streaming at rows_per_shard={rows_per_shard} peaks "
        f"at {_fmt_mb(need)} per shard block ({rows_per_shard} "
        f"rows x ({d} raw + {feat_dim} feature) f32 columns) — over "
        f"--mem-budget-mb {budget_mb:g}. Re-convert with "
        f"--rows-per-shard <= {fit_rows} "
        f"(-> ceil({n}/{fit_rows}) = {-(-n // fit_rows)} shards), or "
        "lower --approx-dim")


# ---------------------------------------------------------------------
# streaming source readers (conversion input)
# ---------------------------------------------------------------------

def _iter_csv_rows(path: str, d: int) -> Iterator[Tuple[float,
                                                        np.ndarray]]:
    with open(path, "r") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            parts = line.split(",")
            if len(parts) < d + 1:
                raise ValueError(f"{path}:{lineno}: expected {d + 1} "
                                 f"fields, got {len(parts)}")
            yield (float(parts[0]),
                   np.asarray(parts[1:d + 1], dtype=np.float32))


def _iter_libsvm_rows(path: str, d: int) -> Iterator[Tuple[float,
                                                           np.ndarray]]:
    with open(path, "r") as fh:
        for lineno, line in enumerate(fh, 1):
            parts = line.split()
            if not parts or parts[0].startswith("#"):
                continue
            try:
                lab = float(parts[0])
            except ValueError as e:
                raise ValueError(f"{path}:{lineno}: bad label "
                                 f"{parts[0]!r}") from e
            row = np.zeros((d,), np.float32)
            for tok in parts[1:]:
                try:
                    idx_s, val_s = tok.split(":", 1)
                    idx = int(idx_s)
                    val = float(val_s)
                except ValueError as e:
                    raise ValueError(f"{path}:{lineno}: bad feature "
                                     f"token {tok!r}") from e
                if idx < 1:
                    raise ValueError(f"{path}:{lineno}: feature "
                                     "indices are 1-based")
                if idx <= d:        # loader's column-narrowing rule
                    row[idx - 1] = val
            yield lab, row


def source_shape(path: str) -> Tuple[int, int, str]:
    """(rows, width, format) of a loader-supported file, discovered by
    a streaming scan — never materializing the data (the native helper
    accelerates both formats when present)."""
    from dpsvm_tpu.data.loader import csv_shape, sniff_format
    fmt = sniff_format(path)
    if fmt == "csv":
        n, d = csv_shape(path)
        return n, d, fmt
    from dpsvm_tpu.native import load_native_lib
    lib = load_native_lib()
    if lib is not None:
        import ctypes
        max_idx = ctypes.c_long(0)
        n_found = lib.dpsvm_libsvm_stats(path.encode(), np.int64(0),
                                         ctypes.byref(max_idx))
        if n_found > 0:
            return int(n_found), int(max_idx.value), fmt
    n = 0
    max_idx = 0
    with open(path, "r") as fh:
        for line in fh:
            parts = line.split()
            if not parts or parts[0].startswith("#"):
                continue
            n += 1
            for tok in parts[1:]:
                idx_s = tok.split(":", 1)[0]
                try:
                    max_idx = max(max_idx, int(idx_s))
                except ValueError:
                    pass                  # the fill pass owns the error
    return n, max_idx, fmt


# ---------------------------------------------------------------------
# resumable conversion
# ---------------------------------------------------------------------

def _round_stat(v: float) -> float:
    """Stats enter the manifest as exact float32 values so a resumed
    conversion reproduces them bit-for-bit."""
    return float(np.float32(v))


def convert_to_shards(src: str, out_dir: str, *,
                      rows_per_shard: int = DEFAULT_ROWS_PER_SHARD,
                      num_attributes: Optional[int] = None,
                      float_labels: bool = False,
                      allow_nonfinite: bool = False,
                      resume: bool = True,
                      _stop_after_shards: Optional[int] = None) -> dict:
    """Convert any loader-supported file into a shard directory,
    checkpointing the cursor after every durable shard.

    Returns the manifest dict (written to ``manifest.json``). A killed
    conversion leaves ``convert.cursor.json`` + the durable shards; the
    next call with ``resume=True`` (the default, and the CLI's
    behavior) picks up at the last durable shard and produces a
    manifest byte-identical to an uninterrupted conversion — the
    manifest is a pure function of the source bytes and the shard
    geometry (no timestamps). ``_stop_after_shards`` is the test seam
    for the kill: stop (cursor intact, no manifest) after writing that
    many NEW shards.
    """
    if rows_per_shard < 1:
        raise ValueError(f"rows_per_shard must be >= 1, got "
                         f"{rows_per_shard}")
    if not os.path.exists(src):
        raise FileNotFoundError(src)
    os.makedirs(out_dir, exist_ok=True)
    mpath = os.path.join(out_dir, MANIFEST_NAME)
    if os.path.exists(mpath):
        raise StreamError(
            f"{out_dir}: already holds a completed shard dataset "
            f"({MANIFEST_NAME} exists); convert into a fresh directory")

    n_total, d_file, fmt = source_shape(src)
    d = int(num_attributes) if num_attributes else d_file
    if n_total <= 0 or d <= 0:
        raise ValueError(f"empty dataset: {src!r} scans as "
                         f"({n_total}, {d})")

    cursor_path = os.path.join(out_dir, CURSOR_NAME)
    state = {
        "source": os.path.abspath(src),
        "source_size": os.path.getsize(src),
        "rows_per_shard": int(rows_per_shard),
        "d": d,
        "float_labels": bool(float_labels),
        "rows_done": 0,
        "shards": [],
        "stats": None,
    }
    if resume and os.path.exists(cursor_path):
        try:
            with open(cursor_path) as fh:
                prev = json.load(fh)
        except (OSError, json.JSONDecodeError):
            prev = None
        if (prev is not None
                and prev.get("source_size") == state["source_size"]
                and prev.get("rows_per_shard") == rows_per_shard
                and prev.get("d") == d
                and prev.get("float_labels") == bool(float_labels)):
            state = prev
            _log(f"resuming conversion of {src} at row "
                 f"{state['rows_done']} (shard "
                 f"{len(state['shards'])} of "
                 f"{-(-n_total // rows_per_shard)})")
        elif prev is not None:
            _log("cursor does not match this source/geometry; "
                 "restarting the conversion from scratch")

    stats = state["stats"] or {
        "feature_min": None, "feature_max": None,
        "label_min": None, "label_max": None,
        "rows_nonfinite": 0,
    }
    ydt = np.float32 if float_labels else np.int32
    rows_iter = (_iter_csv_rows(src, d) if fmt == "csv"
                 else _iter_libsvm_rows(src, d))

    buf_x = np.empty((rows_per_shard, d), np.float32)
    buf_y = np.empty((rows_per_shard,), ydt)
    fill = 0
    row_idx = 0
    written_now = 0
    fmin = (np.asarray(stats["feature_min"], np.float32)
            if stats["feature_min"] is not None else None)
    fmax = (np.asarray(stats["feature_max"], np.float32)
            if stats["feature_max"] is not None else None)

    def flush() -> None:
        nonlocal fill, fmin, fmax, written_now
        if fill == 0:
            return
        x = np.ascontiguousarray(buf_x[:fill])
        y = np.ascontiguousarray(buf_y[:fill])
        k = len(state["shards"])
        fname = shard_filename(k)
        _write_shard_atomic(os.path.join(out_dir, fname), x, y)
        state["shards"].append({"file": fname, "rows": int(fill),
                                "crc32": int(payload_crc(x, y))})
        fmin = x.min(axis=0) if fmin is None else np.minimum(fmin,
                                                             x.min(axis=0))
        fmax = x.max(axis=0) if fmax is None else np.maximum(fmax,
                                                             x.max(axis=0))
        lo, hi = float(y.min()), float(y.max())
        stats["label_min"] = (lo if stats["label_min"] is None
                              else min(stats["label_min"], lo))
        stats["label_max"] = (hi if stats["label_max"] is None
                              else max(stats["label_max"], hi))
        stats["feature_min"] = [_round_stat(v) for v in fmin]
        stats["feature_max"] = [_round_stat(v) for v in fmax]
        state["rows_done"] += fill
        state["stats"] = stats
        fill = 0
        written_now += 1
        # The cursor is only written AFTER the shard is durable, so a
        # crash between the two re-writes one (deterministic) shard.
        _write_json_atomic(cursor_path, state)

    for lab, row in rows_iter:
        if row_idx < state["rows_done"]:
            row_idx += 1                 # resume: skip durable rows
            continue
        if not np.isfinite(row).all() or not np.isfinite(lab):
            bad = (np.argwhere(~np.isfinite(row))[0]
                   if not np.isfinite(row).all() else [-1])
            col = int(bad[0])
            where = (f"row {row_idx}, column {col}" if col >= 0
                     else f"row {row_idx} label")
            if not allow_nonfinite:
                raise ValueError(
                    f"{src}: non-finite value at {where} — rejected at "
                    "conversion; pass --allow-nonfinite to shard it "
                    "anyway (the streaming reader will quarantine or "
                    "re-flag it)")
            stats["rows_nonfinite"] = int(stats["rows_nonfinite"]) + 1
        if not float_labels and int(lab) != lab:
            raise ValueError(
                f"{src}: non-integer label {lab!r} at row {row_idx} "
                "(classification shards store int32 labels; convert "
                "regression targets with --float-labels)")
        buf_x[fill] = row
        buf_y[fill] = lab if float_labels else int(lab)
        fill += 1
        row_idx += 1
        if fill == rows_per_shard:
            flush()
            if (_stop_after_shards is not None
                    and written_now >= _stop_after_shards):
                _log(f"stopping after {written_now} shard(s) "
                     "(test seam); cursor left for resume")
                return dict(state)
    flush()
    if row_idx != n_total:
        raise ValueError(f"{src}: scan said {n_total} rows but the "
                         f"fill pass saw {row_idx}")

    manifest = {
        "format": "dpsvm-shards",
        "version": SHARD_FORMAT_VERSION,
        "n": int(state["rows_done"]),
        "d": d,
        "rows_per_shard": int(rows_per_shard),
        "dtype": "float32",
        "label_dtype": "float32" if float_labels else "int32",
        "source_format": fmt,
        "shards": state["shards"],
        "stats": stats,
    }
    _write_json_atomic(mpath, manifest)
    try:
        os.unlink(cursor_path)
    except OSError:
        pass
    return manifest

"""Feature scaling with LIBSVM-compatible range files (svm-scale).

The reference repo has no scaling tool, but its workflow assumes one:
RBF kernels are scale-sensitive and the LIBSVM guide's first
preprocessing step is ``svm-scale -l -1 -u 1 -s train.range``. This is
that tool for the formats the loaders accept (dense CSV or libsvm),
writing/reading LIBSVM's own ``.range`` file format so parameter files
interoperate with stock svm-scale:

    x
    <lower> <upper>
    <index> <feature_min> <feature_max>        (1-based, one per feature)

Stock svm-scale's semantics are matched exactly where they are
observable: features with min == max (constant at train time) scale to
0 — svm-scale.c's output() skips them, i.e. emits value 0 — and its
range files may OMIT such features entirely, which the loader accepts
(restoring them as constant) given the data's feature count. Labels are
preserved verbatim (svm-scale never touches the label field).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class ScaleParams:
    """Per-feature affine scaling to [lower, upper]."""

    def __init__(self, lower: float, upper: float,
                 fmin: np.ndarray, fmax: np.ndarray):
        self.lower = float(lower)
        self.upper = float(upper)
        self.fmin = np.asarray(fmin, np.float32)
        self.fmax = np.asarray(fmax, np.float32)

    @classmethod
    def fit(cls, x: np.ndarray, lower: float = -1.0,
            upper: float = 1.0) -> "ScaleParams":
        if lower >= upper:
            raise ValueError(f"need lower < upper, got [{lower}, {upper}]")
        x = np.asarray(x, np.float32)
        return cls(lower, upper, x.min(axis=0), x.max(axis=0))

    def transform(self, x: np.ndarray) -> np.ndarray:
        """Affine map; constant features scale to 0 (svm-scale.c's
        output() skips them, i.e. emits the value 0); test values
        outside the train range extrapolate beyond [lower, upper], as
        in stock svm-scale."""
        x = np.asarray(x, np.float32)
        if x.shape[1] != len(self.fmin):
            raise ValueError(f"data has {x.shape[1]} features, scaling "
                             f"params have {len(self.fmin)}")
        span = self.fmax - self.fmin
        safe = np.where(span > 0, span, 1.0)
        out = self.lower + (self.upper - self.lower) * (x - self.fmin) / safe
        return np.where(span > 0, out, np.float32(0.0)).astype(np.float32)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write("x\n")
            f.write(f"{self.lower:.9g} {self.upper:.9g}\n")
            for j, (lo, hi) in enumerate(zip(self.fmin, self.fmax), 1):
                f.write(f"{j} {lo:.9g} {hi:.9g}\n")

    @classmethod
    def load(cls, path: str,
             num_features: Optional[int] = None) -> "ScaleParams":
        """Read a range file. Stock svm-scale OMITS constant features
        from its files, so the true feature count is not always
        recoverable from the file alone — pass ``num_features`` (the
        data's width) to restore omitted columns as constants (they
        scale to 0, stock behavior). Omitted-index lines without a
        ``num_features`` hint error rather than guessing."""
        with open(path) as f:
            lines = [ln.strip() for ln in f if ln.strip()]
        if not lines or lines[0] != "x":
            raise ValueError(f"{path}: not a svm-scale range file "
                             "(first line must be 'x'; y-scaling files "
                             "are not supported)")
        if len(lines) < 2:
            raise ValueError(f"{path}: truncated range file (missing "
                             "the lower/upper line)")
        try:
            lower, upper = (float(v) for v in lines[1].split())
        except ValueError as e:
            raise ValueError(f"{path}: bad lower/upper line "
                             f"{lines[1]!r}") from e
        idx, mins, maxs = [], [], []
        for ln in lines[2:]:
            parts = ln.split()
            if len(parts) != 3:
                raise ValueError(f"{path}: bad range line {ln!r}")
            idx.append(int(parts[0]))
            mins.append(float(parts[1]))
            maxs.append(float(parts[2]))
        max_idx = max(idx) if idx else 0
        d = num_features if num_features is not None else max_idx
        if max_idx > d:
            raise ValueError(f"{path}: range file has feature index "
                             f"{max_idx}, data has {d} features")
        if num_features is None and idx != list(range(1, max_idx + 1)):
            raise ValueError(
                f"{path}: range file omits some feature indices (stock "
                "svm-scale drops constant features); the data's feature "
                "count is needed to restore them — load with "
                "num_features, or use scale_file which passes it")
        # omitted features restore as constants (min == max -> scale
        # to 0, stock behavior)
        fmin = np.zeros(d, np.float32)
        fmax = np.zeros(d, np.float32)
        for i, lo, hi in zip(idx, mins, maxs):
            fmin[i - 1] = lo
            fmax[i - 1] = hi
        return cls(lower, upper, fmin, fmax)


def scale_file(src: str, dst: str, *,
               lower: float = -1.0, upper: float = 1.0,
               save_params: Optional[str] = None,
               restore_params: Optional[str] = None) -> Tuple[int, int]:
    """svm-scale for one file: fit (or restore) params, write a scaled
    dense CSV. Returns (rows, features).

    Labels are preserved verbatim like stock svm-scale: they load as
    floats and write back as ints when integral (so classification
    files keep the reference's integer-label format and regression
    targets survive untruncated)."""
    from dpsvm_tpu.data.loader import load_dataset
    from dpsvm_tpu.data.synthetic import save_csv

    if save_params and restore_params:
        raise ValueError("pass save_params or restore_params, not both "
                         "(svm-scale -s vs -r)")
    x, y = load_dataset(src, float_labels=True)
    if np.all(y == np.round(y)):
        y = y.astype(np.int32)
    if restore_params:
        params = ScaleParams.load(restore_params,
                                  num_features=x.shape[1])
    else:
        params = ScaleParams.fit(x, lower, upper)
    if save_params:
        params.save(save_params)
    save_csv(dst, params.transform(x), y)
    return x.shape

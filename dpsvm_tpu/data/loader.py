"""Dense-CSV dataset loader.

Equivalent of the reference's ``populate_data`` (``parse.cpp:10-43``): a
file of lines ``label,f1,...,fd`` with labels in {+1, -1} becomes a
row-major float32 matrix ``x`` of shape (n, d) and an int32 label vector
``y``. Improvements over the reference:

* shape is discovered from the file (the reference requires ``-a``/``-x``
  flags and trusts them blindly);
* missing files raise instead of ``exit(-1)`` (``parse.cpp:17``);
* the hot parse runs in native C++ via ctypes (``native/csv_loader.cpp``)
  with a pure-NumPy fallback, instead of ``std::getline``+``strtof``
  per cell in-process.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

from dpsvm_tpu.native import load_native_lib


def csv_shape(path: str) -> Tuple[int, int]:
    """Return (num_examples, num_attributes) for a dense CSV dataset.

    num_attributes excludes the label column.
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    lib = load_native_lib()
    if lib is not None:
        rows = ctypes.c_long()
        cols = ctypes.c_long()
        rc = lib.dpsvm_csv_shape(path.encode(), ctypes.byref(rows),
                                 ctypes.byref(cols))
        if rc == 0:
            return int(rows.value), max(0, int(cols.value) - 1)
    n = 0
    d = 0
    with open(path, "r") as f:
        for line in f:
            if not line.strip():
                continue
            if n == 0:
                d = line.count(",")
            n += 1
    return n, d


def load_csv(
    path: str,
    num_examples: Optional[int] = None,
    num_attributes: Optional[int] = None,
    float_labels: bool = False,
    allow_nonfinite: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Load a dense ``label,f1,...,fd`` CSV into (x, y) NumPy arrays.

    x: (n, d) float32, y: (n,) int32 with values +/-1. When the explicit
    shape arguments are given (reference ``-a``/``-x`` flag parity), only
    that many rows/columns are read. ``float_labels=True`` keeps y as
    float32 (regression targets; the pure-Python parse path — the native
    fast path emits int labels). NaN/Inf feature values are rejected
    with an error naming the offending row (the solver would silently
    never converge on them); ``allow_nonfinite=True`` is the explicit
    escape hatch (warns, loads anyway — CLI ``--allow-nonfinite``).
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    if num_examples is None or num_attributes is None:
        n_file, d_file = csv_shape(path)
        n = num_examples if num_examples is not None else n_file
        d = num_attributes if num_attributes is not None else d_file
    else:
        n, d = num_examples, num_attributes
    if n <= 0 or d <= 0:
        raise ValueError(f"empty dataset: {path!r} has shape ({n}, {d})")

    lib = None if float_labels else load_native_lib()
    if lib is not None:
        x = np.empty((n, d), dtype=np.float32)
        y = np.empty((n,), dtype=np.int32)
        got = lib.dpsvm_parse_csv(
            path.encode(),
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            n, d,
        )
        if got == n:
            return _check_finite(x, path, allow_nonfinite), y
        # Malformed / short file: fall through to the Python parser for a
        # readable error.

    xs = np.empty((n, d), dtype=np.float32)
    ys = np.empty((n,), dtype=np.float32 if float_labels else np.int32)
    i = 0
    with open(path, "r") as f:
        for lineno, line in enumerate(f, 1):
            if i >= n:
                break
            line = line.strip()
            if not line:
                continue
            parts = line.split(",")
            if len(parts) < d + 1:
                raise ValueError(
                    f"{path}:{lineno}: expected {d + 1} fields, got {len(parts)}")
            lab = float(parts[0])
            ys[i] = lab if float_labels else int(lab)
            xs[i] = np.asarray(parts[1:d + 1], dtype=np.float32)
            i += 1
    if i < n:
        raise ValueError(f"{path}: expected {n} rows, found {i}")
    return _check_finite(xs, path, allow_nonfinite), ys


def _load_libsvm_native(lib, path, num_examples, num_attributes,
                        float_labels, allow_nonfinite=False):
    """C++ fast path for load_libsvm; None = fall back to Python (both
    for hard parse errors, so the user sees the line-numbered message,
    and for validation failures the scalar return code cannot carry)."""
    if num_examples is not None and num_attributes is not None:
        # Both shapes known: skip the stats scan (the fill pass's
        # row-count check covers short files) — one pass, not two.
        n, d = num_examples, num_attributes
    else:
        max_idx = ctypes.c_long(0)
        n_found = lib.dpsvm_libsvm_stats(
            path.encode(), np.int64(num_examples or 0),
            ctypes.byref(max_idx))
        if n_found <= 0:
            # open/alloc/parse failure, or an actually-empty file: the
            # Python parser owns the error message.
            return None
        n = num_examples if num_examples is not None else int(n_found)
        if n_found < n:
            return None                  # short file: readable error below
        d = (num_attributes if num_attributes is not None
             else int(max_idx.value))
    if d <= 0:
        return None
    x = np.zeros((n, d), dtype=np.float32)
    y = np.empty((n,), dtype=np.float32)
    got = lib.dpsvm_parse_libsvm(
        path.encode(),
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n, d)
    if got != n:
        return None
    if np.any(np.abs(y) >= 2 ** 24):
        # float32 label transport stops being exact: Python path.
        return None
    if not float_labels:
        yi = y.astype(np.int32)
        if not np.array_equal(yi.astype(np.float32), y):
            return None                  # non-integer labels: Python error
        y = yi
    return _check_finite(x, path, allow_nonfinite), y


def load_libsvm(
    path: str,
    num_examples: Optional[int] = None,
    num_attributes: Optional[int] = None,
    float_labels: bool = False,
    allow_nonfinite: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Load a libsvm/svmlight sparse file ``<label> idx:val ...`` directly.

    The reference could only consume this format via an offline convert
    step (``scripts/convert_adult.py``); here the train/test CLIs accept
    it natively. Indices are 1-based; absent features are 0. Labels are
    preserved as integers, exactly like the CSV loader — so multiclass
    sets (labels 0..k) load faithfully and the binary trainer's own
    +/-1 validation still applies; non-integer labels (regression-format
    files) error loudly rather than being silently truncated. An explicit
    ``num_attributes`` fixes the feature count: wider pads with zeros (a
    test file whose max index is below the model's width loads at the
    model's width), narrower silently drops higher-indexed features —
    the same semantics as ``-a`` column narrowing on the CSV path and as
    the reference's converter (``convert_adult.py:31`` keeps only
    indices ≤ d). ``num_examples`` reads only that many rows and, like
    ``load_csv``, errors if the file is shorter.
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    if num_examples is not None and num_examples <= 0:
        raise ValueError(f"empty dataset: {path!r} "
                         f"(num_examples={num_examples})")

    lib = load_native_lib()
    if lib is not None:
        out = _load_libsvm_native(lib, path, num_examples, num_attributes,
                                  float_labels, allow_nonfinite)
        if out is not None:
            return out
        # Malformed input (or short file): fall through to the Python
        # parser, which produces line-numbered error messages.

    labels = []
    rows = []          # list of (idx_array, val_array), 1-based indices
    max_idx = 0
    with open(path, "r") as f:
        for lineno, line in enumerate(f, 1):
            if num_examples is not None and len(rows) >= num_examples:
                break
            parts = line.split()
            if not parts or parts[0].startswith("#"):
                continue
            try:
                lab_f = float(parts[0])
            except ValueError as e:
                raise ValueError(
                    f"{path}:{lineno}: bad label {parts[0]!r}") from e
            if float_labels:
                labels.append(lab_f)
            else:
                lab = int(lab_f)
                if lab != lab_f:
                    raise ValueError(
                        f"{path}:{lineno}: non-integer label {parts[0]!r} "
                        "(classification labels must be integers; "
                        "regression loads with float_labels=True)")
                labels.append(lab)
            idxs = np.empty(len(parts) - 1, dtype=np.int64)
            vals = np.empty(len(parts) - 1, dtype=np.float32)
            for k, tok in enumerate(parts[1:]):
                try:
                    idx_s, val_s = tok.split(":", 1)
                    idxs[k] = int(idx_s)
                    vals[k] = float(val_s)
                except ValueError as e:
                    raise ValueError(
                        f"{path}:{lineno}: bad feature token {tok!r}") from e
            if len(idxs) and idxs.min() < 1:
                raise ValueError(
                    f"{path}:{lineno}: feature indices are 1-based")
            if len(idxs):
                max_idx = max(max_idx, int(idxs.max()))
            rows.append((idxs, vals))
    n = len(rows)
    if n == 0:
        raise ValueError(f"empty dataset: {path!r}")
    if num_examples is not None and n < num_examples:
        raise ValueError(f"{path}: expected {num_examples} rows, found {n}")
    d = num_attributes if num_attributes is not None else max_idx
    if d <= 0:
        raise ValueError(f"{path}: no features found")
    x = np.zeros((n, d), dtype=np.float32)
    for i, (idxs, vals) in enumerate(rows):
        keep = idxs <= d
        x[i, idxs[keep] - 1] = vals[keep]
    return _check_finite(x, path, allow_nonfinite), np.asarray(
        labels, dtype=np.float32 if float_labels else np.int32)


def sniff_format(path: str) -> str:
    """Return "libsvm" or "csv" from the first non-empty data line.

    A dense-CSV data line always contains commas (label plus at least
    one feature); a libsvm line never does — it is whitespace-separated
    ``idx:val`` tokens, possibly zero of them (a label-only line is a
    legal all-zeros example).
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    with open(path, "r") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            return "csv" if "," in line else "libsvm"
    return "csv"


def load_dataset(
    path: str,
    num_examples: Optional[int] = None,
    num_attributes: Optional[int] = None,
    float_labels: bool = False,
    allow_nonfinite: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Load a dataset in either supported format (sniffed per file).

    Dense CSV ``label,f1,...,fd`` (the reference's format, parse.cpp:10)
    or libsvm sparse ``label idx:val ...`` (the format the reference's
    datasets ship in upstream). Returns (x float32 (n, d), y int32).
    Both paths honor the reference's explicit ``-x``/``-a`` shape
    overrides with identical semantics (short files error).
    """
    if sniff_format(path) == "libsvm":
        return load_libsvm(path, num_examples, num_attributes,
                           float_labels, allow_nonfinite)
    return load_csv(path, num_examples, num_attributes, float_labels,
                    allow_nonfinite)


def _check_finite(x: np.ndarray, path: str,
                  allow: bool = False) -> np.ndarray:
    """NaN/Inf features would silently poison f and never converge
    (the solver is exp/argmin-based); fail at load time instead,
    naming the offending row. ``allow=True`` (the ``--allow-nonfinite``
    escape hatch) degrades the rejection to a stderr warning for
    deliberately inspecting damaged datasets."""
    if not np.isfinite(x).all():
        bad = np.argwhere(~np.isfinite(x))[0]
        msg = (
            f"{path}: non-finite feature value at row {int(bad[0])}, "
            f"column {int(bad[1])} (x[{int(bad[0])},{int(bad[1])}] = "
            f"{x[bad[0], bad[1]]})")
        if not allow:
            raise ValueError(
                msg + " — rejected at load; pass --allow-nonfinite / "
                "allow_nonfinite=True to load anyway")
        import sys
        n_bad = int((~np.isfinite(x)).sum())
        print(f"WARNING: {msg}; loading anyway with {n_bad} "
              "non-finite value(s) (--allow-nonfinite)",
              file=sys.stderr, flush=True)
    return x

"""Dense-CSV dataset loader.

Equivalent of the reference's ``populate_data`` (``parse.cpp:10-43``): a
file of lines ``label,f1,...,fd`` with labels in {+1, -1} becomes a
row-major float32 matrix ``x`` of shape (n, d) and an int32 label vector
``y``. Improvements over the reference:

* shape is discovered from the file (the reference requires ``-a``/``-x``
  flags and trusts them blindly);
* missing files raise instead of ``exit(-1)`` (``parse.cpp:17``);
* the hot parse runs in native C++ via ctypes (``native/csv_loader.cpp``)
  with a pure-NumPy fallback, instead of ``std::getline``+``strtof``
  per cell in-process.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

from dpsvm_tpu.native import load_native_lib


def csv_shape(path: str) -> Tuple[int, int]:
    """Return (num_examples, num_attributes) for a dense CSV dataset.

    num_attributes excludes the label column.
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    lib = load_native_lib()
    if lib is not None:
        rows = ctypes.c_long()
        cols = ctypes.c_long()
        rc = lib.dpsvm_csv_shape(path.encode(), ctypes.byref(rows),
                                 ctypes.byref(cols))
        if rc == 0:
            return int(rows.value), max(0, int(cols.value) - 1)
    n = 0
    d = 0
    with open(path, "r") as f:
        for line in f:
            if not line.strip():
                continue
            if n == 0:
                d = line.count(",")
            n += 1
    return n, d


def load_csv(
    path: str,
    num_examples: Optional[int] = None,
    num_attributes: Optional[int] = None,
    float_labels: bool = False,
    allow_nonfinite: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Load a dense ``label,f1,...,fd`` CSV into (x, y) NumPy arrays.

    x: (n, d) float32, y: (n,) int32 with values +/-1. When the explicit
    shape arguments are given (reference ``-a``/``-x`` flag parity), only
    that many rows/columns are read. ``float_labels=True`` keeps y as
    float32 (regression targets; the pure-Python parse path — the native
    fast path emits int labels). NaN/Inf feature values are rejected
    with an error naming the offending row (the solver would silently
    never converge on them); ``allow_nonfinite=True`` is the explicit
    escape hatch (warns, loads anyway — CLI ``--allow-nonfinite``).
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    if num_examples is None or num_attributes is None:
        n_file, d_file = csv_shape(path)
        n = num_examples if num_examples is not None else n_file
        d = num_attributes if num_attributes is not None else d_file
    else:
        n, d = num_examples, num_attributes
    if n <= 0 or d <= 0:
        raise ValueError(f"empty dataset: {path!r} has shape ({n}, {d})")

    lib = None if float_labels else load_native_lib()
    if lib is not None:
        x = np.empty((n, d), dtype=np.float32)
        y = np.empty((n,), dtype=np.int32)
        got = lib.dpsvm_parse_csv(
            path.encode(),
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            n, d,
        )
        if got == n:
            return _check_finite(x, path, allow_nonfinite), y
        # Malformed / short file: fall through to the Python parser for a
        # readable error.

    xs = np.empty((n, d), dtype=np.float32)
    ys = np.empty((n,), dtype=np.float32 if float_labels else np.int32)
    i = 0
    with open(path, "r") as f:
        for lineno, line in enumerate(f, 1):
            if i >= n:
                break
            line = line.strip()
            if not line:
                continue
            parts = line.split(",")
            if len(parts) < d + 1:
                raise ValueError(
                    f"{path}:{lineno}: expected {d + 1} fields, got {len(parts)}")
            lab = float(parts[0])
            ys[i] = lab if float_labels else int(lab)
            xs[i] = np.asarray(parts[1:d + 1], dtype=np.float32)
            i += 1
    if i < n:
        raise ValueError(f"{path}: expected {n} rows, found {i}")
    return _check_finite(xs, path, allow_nonfinite), ys


def _load_libsvm_native(lib, path, num_examples, num_attributes,
                        float_labels, allow_nonfinite=False):
    """C++ fast path for load_libsvm; None = fall back to Python (both
    for hard parse errors, so the user sees the line-numbered message,
    and for validation failures the scalar return code cannot carry)."""
    if num_examples is not None and num_attributes is not None:
        # Both shapes known: skip the stats scan (the fill pass's
        # row-count check covers short files) — one pass, not two.
        n, d = num_examples, num_attributes
    else:
        max_idx = ctypes.c_long(0)
        n_found = lib.dpsvm_libsvm_stats(
            path.encode(), np.int64(num_examples or 0),
            ctypes.byref(max_idx))
        if n_found <= 0:
            # open/alloc/parse failure, or an actually-empty file: the
            # Python parser owns the error message.
            return None
        n = num_examples if num_examples is not None else int(n_found)
        if n_found < n:
            return None                  # short file: readable error below
        d = (num_attributes if num_attributes is not None
             else int(max_idx.value))
    if d <= 0:
        return None
    x = np.zeros((n, d), dtype=np.float32)
    y = np.empty((n,), dtype=np.float32)
    got = lib.dpsvm_parse_libsvm(
        path.encode(),
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n, d)
    if got != n:
        return None
    if np.any(np.abs(y) >= 2 ** 24):
        # float32 label transport stops being exact: Python path.
        return None
    if not float_labels:
        yi = y.astype(np.int32)
        if not np.array_equal(yi.astype(np.float32), y):
            return None                  # non-integer labels: Python error
        y = yi
    return _check_finite(x, path, allow_nonfinite), y


def load_libsvm(
    path: str,
    num_examples: Optional[int] = None,
    num_attributes: Optional[int] = None,
    float_labels: bool = False,
    allow_nonfinite: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Load a libsvm/svmlight sparse file ``<label> idx:val ...`` directly.

    The reference could only consume this format via an offline convert
    step (``scripts/convert_adult.py``); here the train/test CLIs accept
    it natively. Indices are 1-based; absent features are 0. Labels are
    preserved as integers, exactly like the CSV loader — so multiclass
    sets (labels 0..k) load faithfully and the binary trainer's own
    +/-1 validation still applies; non-integer labels (regression-format
    files) error loudly rather than being silently truncated. An explicit
    ``num_attributes`` fixes the feature count: wider pads with zeros (a
    test file whose max index is below the model's width loads at the
    model's width), narrower silently drops higher-indexed features —
    the same semantics as ``-a`` column narrowing on the CSV path and as
    the reference's converter (``convert_adult.py:31`` keeps only
    indices ≤ d). ``num_examples`` reads only that many rows and, like
    ``load_csv``, errors if the file is shorter.
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    if num_examples is not None and num_examples <= 0:
        raise ValueError(f"empty dataset: {path!r} "
                         f"(num_examples={num_examples})")

    lib = load_native_lib()
    if lib is not None:
        out = _load_libsvm_native(lib, path, num_examples, num_attributes,
                                  float_labels, allow_nonfinite)
        if out is not None:
            return out
        # Malformed input (or short file): fall through to the Python
        # parser, which produces line-numbered error messages.

    # Pure-Python parse, in the TARGET dtypes end-to-end: a cheap
    # text-only scan discovers the shape, then tokens stream straight
    # into the final (n, d) float32 matrix. The old implementation
    # staged every row as an (int64 indices, float32 values) pair and
    # kept ALL of them alive while filling x — 12+ bytes per nonzero
    # of intermediates beside the 4-byte target cell, i.e. peak host
    # RAM of the largest supported in-memory loads more than doubled
    # on near-dense files. Peak is pinned by test_data.py
    # (test_libsvm_python_peak_ram_is_final_matrix).
    n_rows = 0
    max_idx = 0
    if num_examples is None or num_attributes is None:
        with open(path, "r") as f:
            for lineno, line in enumerate(f, 1):
                if num_examples is not None and n_rows >= num_examples:
                    break
                parts = line.split()
                if not parts or parts[0].startswith("#"):
                    continue
                n_rows += 1
                if num_attributes is None:
                    for tok in parts[1:]:
                        try:
                            idx = int(tok.split(":", 1)[0])
                        except ValueError:
                            continue     # the fill pass owns the error
                        if idx < 1:
                            raise ValueError(
                                f"{path}:{lineno}: feature indices "
                                "are 1-based")
                        max_idx = max(max_idx, idx)
        if n_rows == 0:
            raise ValueError(f"empty dataset: {path!r}")
        if num_examples is not None and n_rows < num_examples:
            raise ValueError(f"{path}: expected {num_examples} rows, "
                             f"found {n_rows}")
        n = num_examples if num_examples is not None else n_rows
    else:
        n = num_examples
    d = num_attributes if num_attributes is not None else max_idx
    if d <= 0:
        raise ValueError(f"{path}: no features found")
    x = np.zeros((n, d), dtype=np.float32)
    ys = np.empty((n,), dtype=np.float32 if float_labels else np.int32)
    i = 0
    with open(path, "r") as f:
        for lineno, line in enumerate(f, 1):
            if i >= n:
                break
            parts = line.split()
            if not parts or parts[0].startswith("#"):
                continue
            try:
                lab_f = float(parts[0])
            except ValueError as e:
                raise ValueError(
                    f"{path}:{lineno}: bad label {parts[0]!r}") from e
            if float_labels:
                ys[i] = lab_f
            else:
                lab = int(lab_f)
                if lab != lab_f:
                    raise ValueError(
                        f"{path}:{lineno}: non-integer label {parts[0]!r} "
                        "(classification labels must be integers; "
                        "regression loads with float_labels=True)")
                ys[i] = lab
            for tok in parts[1:]:
                try:
                    idx_s, val_s = tok.split(":", 1)
                    idx = int(idx_s)
                    val = np.float32(val_s)
                except ValueError as e:
                    raise ValueError(
                        f"{path}:{lineno}: bad feature token {tok!r}") from e
                if idx < 1:
                    raise ValueError(
                        f"{path}:{lineno}: feature indices are 1-based")
                if idx <= d:     # -a narrowing drops higher indices
                    x[i, idx - 1] = val
            i += 1
    if i == 0:
        raise ValueError(f"empty dataset: {path!r}")
    if i < n:
        raise ValueError(f"{path}: expected {n} rows, found {i}")
    return _check_finite(x, path, allow_nonfinite), ys


def sniff_format(path: str) -> str:
    """Return "libsvm" or "csv" from the first non-empty data line.

    A dense-CSV data line always contains commas (label plus at least
    one feature); a libsvm line never does — it is whitespace-separated
    ``idx:val`` tokens, possibly zero of them (a label-only line is a
    legal all-zeros example).
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    with open(path, "r") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            return "csv" if "," in line else "libsvm"
    return "csv"


def load_dataset(
    path: str,
    num_examples: Optional[int] = None,
    num_attributes: Optional[int] = None,
    float_labels: bool = False,
    allow_nonfinite: bool = False,
    mem_budget_mb: Optional[float] = None,
    on_bad_shard: str = "raise",
) -> Tuple[np.ndarray, np.ndarray]:
    """Load a dataset in any supported form — THE source API every
    consumer (train, test, CV, serving warmup, loadgen) reads through.

    ``path`` may be a dense CSV ``label,f1,...,fd`` (the reference's
    format, parse.cpp:10), a libsvm sparse file ``label idx:val ...``
    (sniffed), or a converted SHARD DIRECTORY (``dpsvm convert
    shards`` — docs/DATA.md): shard reads go through the manifest-CRC
    integrity path with bounded retry and the ``on_bad_shard`` policy.
    Returns (x float32 (n, d), y int32/float32). File paths honor the
    reference's explicit ``-x``/``-a`` shape overrides with identical
    semantics (short files error).

    ``mem_budget_mb`` is the admission guard: a load whose
    materialized arrays would exceed it refuses UP FRONT (naming the
    shard-count math — ``stream.MemBudgetError``) instead of OOMing
    after minutes of parsing. Training on data that must NOT
    materialize is ``approx.fit_approx_stream`` over the shard
    directory itself.
    """
    from dpsvm_tpu.data import stream as streamlib
    if streamlib.is_shard_dir(path):
        ds = streamlib.ShardedDataset.open(path)
        if num_attributes is not None and num_attributes != ds.d:
            raise ValueError(
                f"{path}: shard dataset is {ds.d} wide; -a "
                f"{num_attributes} cannot re-shape fixed shards "
                "(re-convert the source instead)")
        x, y = ds.materialize(mem_budget_mb=mem_budget_mb,
                              on_bad_shard=on_bad_shard,
                              allow_nonfinite=allow_nonfinite)
        if num_examples is not None:
            if num_examples > len(y):
                raise ValueError(f"{path}: expected {num_examples} "
                                 f"rows, found {len(y)}")
            x, y = x[:num_examples], y[:num_examples]
        if float_labels:
            y = np.asarray(y, np.float32)
        return x, y
    if mem_budget_mb:
        n_est, d_est, _fmt = streamlib.source_shape(path)
        streamlib.check_materialize_budget(
            mem_budget_mb,
            n=num_examples if num_examples is not None else n_est,
            d=num_attributes if num_attributes is not None else d_est,
            what=path)
    if sniff_format(path) == "libsvm":
        return load_libsvm(path, num_examples, num_attributes,
                           float_labels, allow_nonfinite)
    return load_csv(path, num_examples, num_attributes, float_labels,
                    allow_nonfinite)


def _check_finite(x: np.ndarray, path: str,
                  allow: bool = False) -> np.ndarray:
    """NaN/Inf features would silently poison f and never converge
    (the solver is exp/argmin-based); fail at load time instead,
    naming the offending row. ``allow=True`` (the ``--allow-nonfinite``
    escape hatch) degrades the rejection to a stderr warning for
    deliberately inspecting damaged datasets.

    The clean path is a pair of reductions, not a mask: min/max are
    finite iff every element is (NaN propagates through min, +/-inf
    survives max), so the common case allocates NOTHING — the old
    ``np.isfinite(x)`` mask was a +25% peak-RAM spike on the largest
    in-memory loads. The mask is only built on the failure path, to
    name the offending cell."""
    if x.size and np.isfinite(x.min()) and np.isfinite(x.max()):
        return x
    if not np.isfinite(x).all():
        bad = np.argwhere(~np.isfinite(x))[0]
        msg = (
            f"{path}: non-finite feature value at row {int(bad[0])}, "
            f"column {int(bad[1])} (x[{int(bad[0])},{int(bad[1])}] = "
            f"{x[bad[0], bad[1]]})")
        if not allow:
            raise ValueError(
                msg + " — rejected at load; pass --allow-nonfinite / "
                "allow_nonfinite=True to load anyway")
        import sys
        n_bad = int((~np.isfinite(x)).sum())
        print(f"WARNING: {msg}; loading anyway with {n_bad} "
              "non-finite value(s) (--allow-nonfinite)",
              file=sys.stderr, flush=True)
    return x

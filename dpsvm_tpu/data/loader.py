"""Dense-CSV dataset loader.

Equivalent of the reference's ``populate_data`` (``parse.cpp:10-43``): a
file of lines ``label,f1,...,fd`` with labels in {+1, -1} becomes a
row-major float32 matrix ``x`` of shape (n, d) and an int32 label vector
``y``. Improvements over the reference:

* shape is discovered from the file (the reference requires ``-a``/``-x``
  flags and trusts them blindly);
* missing files raise instead of ``exit(-1)`` (``parse.cpp:17``);
* the hot parse runs in native C++ via ctypes (``native/csv_loader.cpp``)
  with a pure-NumPy fallback, instead of ``std::getline``+``strtof``
  per cell in-process.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

from dpsvm_tpu.native import load_native_lib


def csv_shape(path: str) -> Tuple[int, int]:
    """Return (num_examples, num_attributes) for a dense CSV dataset.

    num_attributes excludes the label column.
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    lib = load_native_lib()
    if lib is not None:
        rows = ctypes.c_long()
        cols = ctypes.c_long()
        rc = lib.dpsvm_csv_shape(path.encode(), ctypes.byref(rows),
                                 ctypes.byref(cols))
        if rc == 0:
            return int(rows.value), max(0, int(cols.value) - 1)
    n = 0
    d = 0
    with open(path, "r") as f:
        for line in f:
            if not line.strip():
                continue
            if n == 0:
                d = line.count(",")
            n += 1
    return n, d


def load_csv(
    path: str,
    num_examples: Optional[int] = None,
    num_attributes: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Load a dense ``label,f1,...,fd`` CSV into (x, y) NumPy arrays.

    x: (n, d) float32, y: (n,) int32 with values +/-1. When the explicit
    shape arguments are given (reference ``-a``/``-x`` flag parity), only
    that many rows/columns are read.
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    if num_examples is None or num_attributes is None:
        n_file, d_file = csv_shape(path)
        n = num_examples if num_examples is not None else n_file
        d = num_attributes if num_attributes is not None else d_file
    else:
        n, d = num_examples, num_attributes
    if n <= 0 or d <= 0:
        raise ValueError(f"empty dataset: {path!r} has shape ({n}, {d})")

    lib = load_native_lib()
    if lib is not None:
        x = np.empty((n, d), dtype=np.float32)
        y = np.empty((n,), dtype=np.int32)
        got = lib.dpsvm_parse_csv(
            path.encode(),
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            n, d,
        )
        if got == n:
            return _check_finite(x, path), y
        # Malformed / short file: fall through to the Python parser for a
        # readable error.

    xs = np.empty((n, d), dtype=np.float32)
    ys = np.empty((n,), dtype=np.int32)
    i = 0
    with open(path, "r") as f:
        for lineno, line in enumerate(f, 1):
            if i >= n:
                break
            line = line.strip()
            if not line:
                continue
            parts = line.split(",")
            if len(parts) < d + 1:
                raise ValueError(
                    f"{path}:{lineno}: expected {d + 1} fields, got {len(parts)}")
            ys[i] = int(float(parts[0]))
            xs[i] = np.asarray(parts[1:d + 1], dtype=np.float32)
            i += 1
    if i < n:
        raise ValueError(f"{path}: expected {n} rows, found {i}")
    return _check_finite(xs, path), ys


def _check_finite(x: np.ndarray, path: str) -> np.ndarray:
    """NaN/Inf features would silently poison f and never converge
    (the solver is exp/argmin-based); fail at load time instead."""
    if not np.isfinite(x).all():
        bad = np.argwhere(~np.isfinite(x))[0]
        raise ValueError(
            f"{path}: non-finite feature value at row {int(bad[0])}, "
            f"column {int(bad[1])} (x[{int(bad[0])},{int(bad[1])}] = "
            f"{x[bad[0], bad[1]]})")
    return x

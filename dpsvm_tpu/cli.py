"""Command-line entry points: ``svm-train`` and ``svm-test``.

Thin wrappers over the library API, honoring the reference's flag names
(``svmTrainMain.cpp:22-44``, ``seq_test.cpp:54-62``):

    -f/--input, -m/--model, -a/--num-att, -x/--num-ex, -c/--cost,
    -g/--gamma, -e/--epsilon, -n/--max-iter, -s/--cache-size

with two deliberate fixes (SURVEY §2d): ``-a``/``-x`` are OPTIONAL (shapes
are inferred from the file) and the default gamma is 1.0/d, not the
reference's integer-division zero. Extra flags cover the mesh
(``--shards`` replaces ``mpirun -np``) and layout (``--replicate-x``).

Usage:
    python -m dpsvm_tpu.cli train -f train.csv -m model.svm -c 10 -g 0.25
    python -m dpsvm_tpu.cli test  -f test.csv  -m model.svm
"""

from __future__ import annotations

import argparse
import math
import os
import sys
from typing import List, Optional

# stdlib-only modules — safe to import before the deferred jax imports.
from dpsvm_tpu.config import SCREEN_MARGIN_DEFAULT
from dpsvm_tpu.resilience.health import DivergenceError
from dpsvm_tpu.resilience.preempt import PREEMPT_EXIT_CODE, PreemptedError


def _add_backend_flags(p: argparse.ArgumentParser) -> None:
    """Backend selection + fail-fast init, for the device-using
    subcommands (train/test). Without these a dead tunneled TPU hangs
    the CLI inside the first jax device call with no diagnostic."""
    p.add_argument("--platform", default=None, metavar="NAME",
                   help="force the jax platform (e.g. 'cpu'); default: "
                        "the DPSVM_PLATFORM env var, else the ambient "
                        "backend. Applied before first device use — env "
                        "vars alone cannot switch it on images that "
                        "pre-import jax")
    p.add_argument("--backend-timeout", type=float, default=180.0,
                   metavar="S",
                   help="seconds to wait for backend initialization "
                        "before exiting with a clean error instead of "
                        "hanging (an unreachable tunneled TPU would "
                        "otherwise block forever)")


def _add_data_flags(p: argparse.ArgumentParser,
                    model_required: bool = True) -> None:
    p.add_argument("-f", "--input", required=True,
                   help="dataset: dense CSV 'label,f1,...', libsvm "
                        "sparse 'label idx:val ...' (format sniffed), "
                        "or a shard DIRECTORY from `dpsvm convert "
                        "shards` (integrity-checked streaming reads — "
                        "docs/DATA.md)")
    p.add_argument("--mem-budget-mb", type=float, default=None,
                   metavar="MB",
                   help="host-memory admission guard: refuse (up "
                        "front, with the shard-count math) any load "
                        "whose materialized arrays exceed this many "
                        "MiB, instead of OOMing mid-run; for shard "
                        "directories the streaming train path bounds "
                        "its per-shard working set by the same budget")
    p.add_argument("-m", "--model", required=model_required,
                   default=None, help="model file path"
                   + ("" if model_required
                      else " (unused in --cv mode)"))
    p.add_argument("-a", "--num-att", type=int, default=None,
                   help="attribute count (inferred when omitted)")
    p.add_argument("-x", "--num-ex", type=int, default=None,
                   help="example count (inferred when omitted)")
    p.add_argument("--allow-nonfinite", action="store_true",
                   help="escape hatch: load rows containing NaN/Inf "
                        "features instead of rejecting the file (the "
                        "solver will NOT converge on them — use only "
                        "to inspect a damaged dataset)")


def build_parser() -> argparse.ArgumentParser:
    root = argparse.ArgumentParser(prog="dpsvm_tpu")
    sub = root.add_subparsers(dest="command", required=True)

    tr = sub.add_parser("train", help="train a binary SVM (RBF default)")
    _add_data_flags(tr, model_required=False)
    _add_backend_flags(tr)
    tr.add_argument("-c", "--cost", type=float, default=1.0)
    tr.add_argument("-g", "--gamma", type=float, default=None,
                    help="kernel gamma (default 1/num_attributes)")
    tr.add_argument("-t", "--kernel", default="rbf",
                    type=_kernel_name,
                    help="kernel: linear | poly | rbf | sigmoid | "
                         "precomputed, or the LIBSVM -t integer 0..4 "
                         "(default rbf — the reference's only kernel; "
                         "-t 4 trains on a (n, n) kernel matrix CSV and "
                         "tests on K(test, train) rows)")
    tr.add_argument("-d", "--degree", type=int, default=3,
                    help="poly kernel degree (LIBSVM -d)")
    tr.add_argument("-r", "--coef0", type=float, default=0.0,
                    help="poly/sigmoid coef0 (LIBSVM -r)")
    tr.add_argument("-e", "--epsilon", type=float, default=0.001)
    tr.add_argument("-n", "--max-iter", type=int, default=150_000)
    tr.add_argument("-s", "--cache-size", type=int, default=None,
                    help="kernel-row cache lines (0 = fused matmul, no "
                         "cache; default: the backend's tuned profile "
                         "when one is active, else 0 — docs/PERF.md "
                         "'Autotuning')")
    tr.add_argument("--chunk-iters", type=int, default=None, metavar="I",
                    help="host poll cadence: iterations per compiled "
                         "chunk between convergence polls (default: "
                         "the backend's tuned profile when one is "
                         "active, else 512)")
    tr.add_argument("--no-tuned", action="store_true",
                    help="ignore the tuned per-backend profile "
                         "(`dpsvm tune`): knobs left at their defaults "
                         "stay at the built-in defaults "
                         "(DPSVM_NO_TUNED=1 is the env equivalent; "
                         "explicit flags always win either way)")
    tr.add_argument("--shards", type=int, default=1,
                    help="devices along the data axis (replaces mpirun -np)")
    tr.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                    help="multi-host training (docs/DISTRIBUTED.md "
                         "'Multi-host'): join a cross-process group "
                         "via this jax.distributed coordinator — one "
                         "`dpsvm train` per host, same flags plus "
                         "--num-hosts/--host-id; the data mesh then "
                         "spans every host's devices. Omitted = "
                         "single-host, bit-identical to before the "
                         "flag existed (on Cloud TPU pods the group "
                         "is metadata-discovered; this flag is for "
                         "explicit/localhost groups)")
    tr.add_argument("--num-hosts", type=int, default=None, metavar="N",
                    help="process count of the multi-host group "
                         "(requires --coordinator)")
    tr.add_argument("--host-id", type=int, default=None, metavar="K",
                    help="this process's rank, 0..N-1 (requires "
                         "--coordinator)")
    tr.add_argument("--backend", default="xla", choices=["xla", "numpy"],
                    help="'numpy' runs the golden-reference CPU solver "
                         "(the reference's seq binary equivalent)")
    tr.add_argument("--replicate-x", action="store_true",
                    help="replicate X on every shard (reference layout)")
    tr.add_argument("--checkpoint", default=None,
                    help="solver-state .npz path for periodic checkpoints")
    tr.add_argument("--checkpoint-every", type=int, default=0,
                    help="iterations between checkpoints (0 = off)")
    tr.add_argument("--checkpoint-keep", type=int, default=2,
                    metavar="N",
                    help="rotation slots kept (state.npz, state.1.npz, "
                         "...): a corrupt newest file still leaves an "
                         "intact older state to resume; 1 = no rotation")
    tr.add_argument("--resume", default=None, type=_existing_checkpoint,
                    help="resume training from a checkpoint file "
                         "(validated at parse time; a corrupt file "
                         "falls back to its newest intact rotation slot)")
    tr.add_argument("--on-divergence", default="raise",
                    choices=["raise", "rollback", "ignore"],
                    help="poll-loop health policy for a sick run "
                         "(non-finite gap, stagnation, SV collapse): "
                         "'rollback' restores the newest intact "
                         "checkpoint and halves the poll chunk "
                         "(needs --checkpoint)")
    tr.add_argument("--on-bad-shard", default="raise",
                    choices=["raise", "quarantine"],
                    help="streaming-ingest policy when a shard fails "
                         "its manifest CRC or finiteness check "
                         "(shard-directory inputs): 'quarantine' "
                         "drops the shard — traced as a `quarantine` "
                         "event naming shard + reason, bounded by the "
                         "bad-fraction abort (docs/DATA.md)")
    tr.add_argument("--live", action="store_true",
                    help="treat a shard-directory input as a LIVE "
                         "append log (docs/DATA.md 'Live shard "
                         "logs'): streaming approx training polls the "
                         "manifest at sweep boundaries and admits "
                         "newly durable shards mid-run (traced as "
                         "append_admitted/ingest_grow; checkpoints "
                         "carry the consumed generation)")
    tr.add_argument("--health-window", type=int, default=0, metavar="I",
                    help="iterations without best-gap improvement "
                         "before the stagnation guard trips (0 = off)")
    tr.add_argument("--retries", type=int, default=0, metavar="N",
                    help="supervise training in a child process and "
                         "re-launch up to N times after transient "
                         "deaths (preemption exit 75, stall/timeout "
                         "124, SIGTERM/SIGKILL), resuming from the "
                         "newest intact checkpoint (docs/ROBUSTNESS.md)")
    tr.add_argument("--retry-backoff", type=float, default=5.0,
                    metavar="S",
                    help="base of the exponential retry backoff: "
                         "attempt k waits S * 2^k seconds (default 5)")
    tr.add_argument("--profile-dir", default=None,
                    help="write an auto-windowed jax.profiler trace "
                         "here (warmup compiles skipped, K steady-state "
                         "polls captured, phases annotated) plus a "
                         "profile_summary.json sidecar — render with "
                         "`dpsvm profile summarize DIR` "
                         "(docs/OBSERVABILITY.md)")
    tr.add_argument("--metrics-port", type=int, default=None,
                    metavar="N",
                    help="opt-in read-only metrics sidecar: serve the "
                         "live metric registry on this port (0 = OS-"
                         "assigned; bound port printed to stderr) as "
                         "/metricsz JSON and /metricsz?format="
                         "prometheus, torn down at run end — fed from "
                         "the existing packed-stats polls, zero extra "
                         "device transfers")
    tr.add_argument("--metrics-out", default=None, metavar="FILE",
                    help="scrape-less CI: rewrite FILE with the "
                         "Prometheus text exposition at every poll "
                         "(atomic replace)")
    tr.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write a run-telemetry JSONL here (manifest + "
                         "per-chunk gap/SV-count/cache-counter records "
                         "+ summary — zero extra device polls; render "
                         "with `dpsvm report PATH`, schema in "
                         "docs/OBSERVABILITY.md)")
    tr.add_argument("--watch-rules", default=None, metavar="FILE",
                    help="alert-rules JSON for the continuous watch "
                         "(gap stagnation, compile storm, heartbeat "
                         "age, roofline drop vs the perf-ledger "
                         "median; default rules when only "
                         "--bundle-dir is given — "
                         "docs/OBSERVABILITY.md 'Watch & alerts')")
    tr.add_argument("--bundle-dir", default=None, metavar="DIR",
                    help="arm the black-box flight recorder: a firing "
                         "watch rule or tripped divergence guard "
                         "dumps a self-contained incident bundle here "
                         "(ring trace + metrics + doctor + tuned "
                         "profile + ledger context; render with "
                         "`dpsvm bundle DIR`) — zero extra device "
                         "transfers")
    tr.add_argument("--debug-nans", action="store_true",
                    help="enable jax_debug_nans during training")
    tr.add_argument("--precision", default="highest",
                    choices=["highest", "high", "default"],
                    help="MXU matmul precision: 'highest'=exact f32 "
                         "(reference parity), 'default'=bf16-multiply "
                         "(~5x faster, same model quality in A/B runs)")
    tr.add_argument("--model-format", default="reference",
                    choices=["reference", "libsvm"],
                    help="model file layout: 'reference' (the MPI "
                         "trainer's CSV-ish format) or 'libsvm' "
                         "(svm-train .model text, readable by LIBSVM/"
                         "sklearn tooling); the test command "
                         "auto-detects either format")
    tr.add_argument("--polish", action="store_true",
                    help="two-phase precision schedule: fast bf16 bulk "
                         "solve, then an exact-f32 warm-start refinement "
                         "to the same epsilon — exact-arithmetic final "
                         "KKT at near-bf16 wall-clock")
    tr.add_argument("--weight-pos", type=_finite_weight, default=1.0,
                    help="cost weight for y=+1 examples (box bound "
                         "C*weight; LIBSVM -w1)")
    tr.add_argument("--weight-neg", type=_finite_weight, default=1.0,
                    help="cost weight for y=-1 examples (LIBSVM -w-1)")
    tr.add_argument("--clip", default=None,
                    choices=["independent", "pairwise"],
                    help="alpha-step clip rule: 'independent' = the "
                         "reference's (both alphas clipped separately; "
                         "lets sum(alpha*y) drift — noticeably at "
                         "strongly asymmetric class weights), "
                         "'pairwise' = the textbook/LIBSVM joint box "
                         "(conserves the equality constraint exactly)")
    tr.add_argument("--weight", action="append", default=[],
                    metavar="LABEL:W",
                    help="per-label cost weight for --multiclass "
                         "(repeatable; LIBSVM -wi for any label set): "
                         "each OvO pair trains with C*W on that "
                         "label's examples; unlisted labels weigh 1")
    tr.add_argument("--solver", default="exact",
                    choices=["exact", "approx-rff", "approx-nystrom",
                             "cascade"],
                    help="'exact' = the dual SMO/decomposition paths "
                         "(reference parity). 'approx-rff'/'approx-"
                         "nystrom' = explicit feature map + primal "
                         "linear solver: O(n*D) matmul work instead of "
                         "O(n^2) kernel work — the million-row path; "
                         "the model file is a .npz with no support "
                         "vectors (docs/APPROX.md). 'cascade' = approx "
                         "warm-start -> margin-band SV screening -> "
                         "exact dual polish on the screened subproblem "
                         "with KKT re-admission repair: exact-quality "
                         "decisions at a fraction of the exact cost, "
                         "out-of-core capable (docs/APPROX.md "
                         "\"Cascade\"); writes an ordinary SV model")
    tr.add_argument("--screen-margin", type=float,
                    default=SCREEN_MARGIN_DEFAULT,
                    metavar="DELTA",
                    help="cascade stage 2: margin-band safety delta — "
                         "a row survives screening when its approx "
                         "margin y*f(x) <= 1 + DELTA (bigger = safer "
                         "band, bigger exact subproblem; the KKT "
                         "repair loop re-admits anything the band "
                         "missed)")
    tr.add_argument("--screen-cap", type=int, default=0, metavar="N",
                    help="cascade stage 2: hard cap on the screened "
                         "subproblem's rows (0 = auto: derived from "
                         "--mem-budget-mb when set, else uncapped); "
                         "over-cap rows drop best-margin-first")
    tr.add_argument("--approx-dim", type=int, default=1024, metavar="D",
                    help="approx solvers: feature-map dimension "
                         "(accuracy-vs-cost knob; RFF needs it even)")
    tr.add_argument("--approx-seed", type=int, default=0,
                    help="approx solvers: deterministic feature-map "
                         "seed (persisted with the model)")
    tr.add_argument("--selection", default="first-order",
                    choices=["first-order", "second-order"],
                    help="working-set rule: 'first-order' = reference "
                         "parity; 'second-order' = LIBSVM WSS2 (usually "
                         "far fewer iterations)")
    tr.add_argument("--working-set", type=int, default=2, metavar="Q",
                    # 0 = auto (shape-resolved); kept out of the help
                    # line until the chip-measured table lands.
                    help="violators optimized per kernel fetch: 2 = the "
                         "reference's SMO pair; even Q > 2 = large-"
                         "working-set decomposition (one (Q,d)@(d,n) "
                         "MXU pass per outer round + an inner subsolve "
                         "— usually much faster to convergence on TPU)")
    tr.add_argument("--inner-iters", type=int, default=0,
                    help="decomposition inner-step cap per round "
                         "(0 = auto: Q/4; only with --working-set > 2)")
    tr.add_argument("--grow-working-set", action="store_true",
                    help="adaptive decomposition: grow Q (recompile, "
                         "same state) when the SV count approaches it "
                         "— applies the measured q-selection rule "
                         "(Q must stay above ~1.3x the SV count) "
                         "without knowing the SV count up front; "
                         "start with a modest --working-set")
    tr.add_argument("--shrinking", nargs="?", const=True, default=False,
                    type=_shrinking_value, metavar="{0,1,auto}",
                    help="LIBSVM -h analog: active-set training — "
                         "periodically drop rows that are provably "
                         "stuck at their bound, validate on the full "
                         "problem at the end (big win when few rows "
                         "are SVs). Bare flag = on; '--shrinking 0' "
                         "forces off")
    tr.add_argument("--select-impl", default="argminmax",
                    choices=["argminmax", "packed"],
                    help="first-order selection lowering: 'packed' = one "
                         "4-operand lax.reduce (bit-identical results; "
                         "see benchmarks/selection_ab.py)")
    tr.add_argument("--pallas", default="auto",
                    choices=["auto", "on", "off"],
                    help="fused Pallas iteration kernel: 'on' forces it; "
                         "'auto' currently prefers the XLA path (faster "
                         "on measured hardware, see experimental/fused.py)")
    tr.add_argument("-v", "--cv", type=int, default=0, metavar="K",
                    help="k-fold cross-validation mode (LIBSVM -v): "
                         "report pooled held-out accuracy (or MSE for "
                         "--svr) instead of writing a model")
    tr.add_argument("--one-class", action="store_true",
                    help="one-class SVM / novelty detection on unlabeled "
                         "rows (LIBSVM svm-train -s 2 analog; the label "
                         "column is ignored)")
    tr.add_argument("--nu", type=float, default=0.5,
                    help="one-class outlier-fraction bound (LIBSVM -n)")
    tr.add_argument("--nu-svc", action="store_true",
                    help="nu-SVC (LIBSVM -s 1): --nu replaces -c; nu "
                         "lower-bounds the SV fraction and upper-bounds "
                         "the margin-error fraction")
    tr.add_argument("--nu-svr", action="store_true",
                    help="nu-SVR (LIBSVM -s 4): the epsilon tube width "
                         "is learned; --nu bounds the outside-tube "
                         "fraction, -c is the usual cost")
    tr.add_argument("--svr", action="store_true",
                    help="epsilon-SVR regression (float targets; LIBSVM "
                         "svm-train -s 3 analog)")
    tr.add_argument("-p", "--svr-epsilon", type=float, default=0.1,
                    help="SVR tube half-width (LIBSVM -p, default 0.1)")
    tr.add_argument("--multiclass", action="store_true",
                    help="one-vs-one multi-class training (labels may be "
                         "any integers; -m becomes a model DIRECTORY)")
    tr.add_argument("--c-sweep", default=None, metavar="C1,C2,...",
                    help="with --cv: evaluate CV accuracy at every C of "
                         "the comma list in ONE batched program (all "
                         "folds x all C points — LIBSVM grid.py's inner "
                         "loop as a single compiled batch; binary "
                         "classification only) and report the best C")
    tr.add_argument("--gamma-sweep", default=None, metavar="G1,G2,...",
                    help="with --cv --c-sweep: extend the sweep to the "
                         "full C x gamma grid, still one batched "
                         "program (gamma only enters the kernel "
                         "epilogue; the dot products are shared)")
    tr.add_argument("--batched", action="store_true",
                    help="train independent subproblems in ONE compiled "
                         "batched program — all one-vs-one pairs with "
                         "--multiclass, all folds with --cv (folds x "
                         "pairs for multiclass CV). Shared X stream, "
                         "per-step latency amortized across "
                         "subproblems; plain first-order single-device "
                         "path only — incompatible options are "
                         "rejected")
    tr.add_argument("-b", "--probability", action="store_true",
                    help="LIBSVM -b 1 analog: fit Platt-scaled "
                         "probabilities on the training decision values "
                         "— a <model>.platt.json sidecar for binary "
                         "models; per-pair sigmoids in the model "
                         "directory's index.json with --multiclass "
                         "(pairwise-coupled at test time)")
    tr.add_argument("--probability-cv", action="store_true",
                    help="like -b, but fit the sigmoid on 5-fold "
                         "held-out decision values — LIBSVM's actual "
                         "-b 1 procedure (5 extra trainings; better-"
                         "calibrated probabilities)")
    tr.add_argument("--check-kkt", action="store_true",
                    help="post-train optimality report: dual/primal "
                         "objective, duality gap, and the KKT residual "
                         "recomputed from scratch (bounds the solver's "
                         "incremental-f drift)")
    tr.add_argument("-q", "--quiet", action="store_true")

    te = sub.add_parser("test", help="evaluate a saved model on a dataset")
    _add_data_flags(te)
    _add_backend_flags(te)
    te.add_argument("--no-b", action="store_true",
                    help="drop the intercept like seq_test.cpp:197")
    te.add_argument("--predictions", default=None, metavar="PATH",
                    help="also write one predicted label per line "
                         "(binary models: 'label,decision_value')")
    te.add_argument("--batch", type=int, default=0, metavar="N",
                    help="stream evaluation through the serving "
                         "engine's bucket ladder at up to N rows per "
                         "device pass instead of one monolithic (m, d) "
                         "pass — bounds host+device memory on large "
                         "test splits (0 = monolithic; "
                         "docs/SERVING.md)")
    te.add_argument("--proba", default=None, metavar="PATH",
                    help="binary model: write Platt-calibrated "
                         "P(y=+1|x) per line + Brier/log-loss (needs "
                         "the <model>.platt.json sidecar). Multiclass "
                         "model dir: write comma-separated per-class "
                         "probabilities (pairwise coupling) + log-loss, "
                         "and predict by the coupled argmax. Both need "
                         "train --probability")

    cv = sub.add_parser(
        "convert", help="dataset converters (the reference's scripts/ "
                        "+ the out-of-core shard format, docs/DATA.md)")
    cv.add_argument("format", choices=["libsvm", "mnist-odd-even",
                                       "shards"],
                    help="libsvm: sparse 'label idx:val ...' -> dense CSV "
                         "(scripts/convert_adult.py); mnist-odd-even: "
                         "'digit,p1,...' -> +/-1 even/odd with /255 pixels "
                         "(scripts/convert_mnist_to_odd_even.py); "
                         "shards: any loader-supported file -> a "
                         "directory of fixed-shape .npz shards + a "
                         "CRC-carrying manifest, streamed row-by-row "
                         "(never materialized) and RESUMABLE — a "
                         "killed conversion picks up at the last "
                         "durable shard and lands a byte-identical "
                         "manifest")
    cv.add_argument("src", help="input file")
    cv.add_argument("dst", help="output CSV (or, for shards, the "
                                "output DIRECTORY)")
    cv.add_argument("-a", "--num-att", type=int, default=None,
                    help="libsvm/shards: force the dense width "
                         "(default: max feature index seen)")
    cv.add_argument("--rows-per-shard", type=int, default=4096,
                    metavar="R",
                    help="shards: rows per fixed-shape chunk shard "
                         "(the streaming train path's compiled block "
                         "shape AND its per-shard memory peak; "
                         "default 4096)")
    cv.add_argument("--float-labels", action="store_true",
                    help="shards: store float32 labels (regression "
                         "targets); default int32 classification "
                         "labels, non-integer labels rejected")
    cv.add_argument("--allow-nonfinite", action="store_true",
                    help="shards: shard rows containing NaN/Inf "
                         "instead of rejecting the conversion (the "
                         "streaming reader will re-flag or quarantine "
                         "them)")
    cv.add_argument("--no-resume", dest="resume", action="store_false",
                    default=True,
                    help="shards: ignore a previous conversion's "
                         "cursor and restart from row 0")

    sc = sub.add_parser(
        "scale", help="feature scaling (svm-scale analog; LIBSVM-"
                      "compatible .range parameter files)")
    sc.add_argument("src", help="input dataset (CSV or libsvm)")
    sc.add_argument("dst", help="output CSV (scaled)")
    sc.add_argument("-l", "--lower", type=float, default=-1.0)
    sc.add_argument("-u", "--upper", type=float, default=1.0)
    sc.add_argument("-s", "--save-range", default=None, metavar="PATH",
                    help="write fitted scaling params (svm-scale -s)")
    sc.add_argument("-r", "--restore-range", default=None, metavar="PATH",
                    help="apply previously saved params (svm-scale -r; "
                         "use for test files)")
    inf = sub.add_parser(
        "info", help="environment diagnostics: backend, devices, "
                     "native helper, compile cache")
    inf.add_argument("--timeout", type=float, default=20.0,
                     help="seconds to wait for backend initialization "
                          "before reporting it unreachable (a tunneled "
                          "TPU that is down would otherwise hang here)")

    dr = sub.add_parser(
        "doctor", help="distributed-training preflight: device/mesh "
                       "topology, a timed tiny shard_map collective "
                       "probe, checkpoint-dir writability + "
                       "newest-slot integrity; exits non-zero with a "
                       "one-line diagnosis (docs/DISTRIBUTED.md "
                       "'Elastic training')")
    dr.add_argument("--shards", type=int, default=0,
                    help="mesh size to probe (0 = every visible "
                         "device)")
    dr.add_argument("--checkpoint", default=None, metavar="PATH",
                    help="checkpoint path a run would use: the doctor "
                         "checks the directory is writable and the "
                         "newest rotation slot is intact (reporting "
                         "its recorded mesh/iteration)")
    dr.add_argument("--data", default=None, metavar="DIR",
                    help="shard-dataset directory to probe: manifest "
                         "parse + shard CRC spot-check (first/middle/"
                         "last), free disk space, and a one-shard "
                         "timed read (docs/DATA.md); distinct exit "
                         "codes 7 (integrity) / 8 (disk space)")
    dr.add_argument("--timeout", type=float, default=60.0,
                    help="bounded wait for backend init AND for the "
                         "collective probe (a hung interconnect "
                         "surfaces here in seconds, not an hour into "
                         "a run)")
    dr.add_argument("--serving-url", default=None, metavar="URL",
                    help="also probe a live `dpsvm serve` process: "
                         "reports the tenant label budget, live "
                         "per-tenant series count, evictions and "
                         "overflow, plus the model-cache residency/"
                         "fault/eviction state when the fleet cache "
                         "is armed, plus the front-end kind with its "
                         "open-connection count and per-tenant fair-"
                         "queue lane depths (async front door) — "
                         "warning near saturation of any budget or "
                         "the connection cap (docs/OBSERVABILITY.md "
                         "'Per-tenant attribution', docs/SERVING.md "
                         "'Model fleet', 'Front door'); reporting-"
                         "only, never changes the exit code")
    dr.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                    help="multi-host preflight: deadline-bounded TCP "
                         "reachability check of the jax.distributed "
                         "coordinator (a pure socket probe — the "
                         "doctor NEVER initializes a distributed "
                         "backend); exit 9 = host group degraded")
    dr.add_argument("--hosts-dir", default=None, metavar="DIR",
                    help="host-group heartbeat directory "
                         "(DPSVM_HOST_HEARTBEAT_DIR of a supervised "
                         "run): reports each host's last-beat age, "
                         "iteration and admitted live generation; "
                         "exit 9 when a host is missing or stale "
                         "(docs/DISTRIBUTED.md 'Multi-host')")
    dr.add_argument("--num-hosts", type=int, default=0, metavar="N",
                    help="expected host-group size for --hosts-dir "
                         "(0 = whatever heartbeats exist; nonzero "
                         "makes a MISSING host a degradation, not "
                         "just a stale one)")
    dr.add_argument("--heartbeat-max-age", type=float, default=60.0,
                    metavar="S",
                    help="heartbeat age beyond which a host counts as "
                         "stale for --hosts-dir (default 60)")

    rp = sub.add_parser(
        "report", help="render a run-telemetry trace (train "
                       "--trace-out): convergence curve, phase "
                       "breakdown, cache hit rate, compile/HBM/FLOP "
                       "facts")
    rp.add_argument("trace", help="trace JSONL written by --trace-out "
                                  "(or BENCH_TRACE_OUT), or a directory "
                                  "of traces — the newest *.jsonl is "
                                  "picked (the burst runner archives "
                                  "under <results>/traces/)")
    rp.add_argument("--json", action="store_true",
                    help="machine-readable digest instead of the human "
                         "rendering")
    rp.add_argument("--width", type=int, default=60,
                    help="plot width in columns")
    rp.add_argument("--follow", action="store_true",
                    help="live mode: tail an in-flight trace and "
                         "refresh the report until a terminal record "
                         "(summary / stall / preempt) or a stall "
                         "timeout — makes tunneled chip runs watchable")
    rp.add_argument("--interval", type=float, default=1.0, metavar="S",
                    help="--follow refresh poll interval (default 1 s)")
    rp.add_argument("--stall-timeout", type=float, default=300.0,
                    metavar="S",
                    help="--follow exits 3 when the trace file stops "
                         "growing for this long (default 300 s; a run "
                         "killed too hard to stamp its own terminal "
                         "event)")

    cp = sub.add_parser(
        "compare", help="delta table + regression gate between two "
                        "run-telemetry traces (it/s, gap trajectory at "
                        "matched iteration marks, phase split, cache "
                        "hit rate, compile count/seconds, HBM peak)")
    cp.add_argument("a", help="baseline trace JSONL (or a directory — "
                              "newest *.jsonl)")
    cp.add_argument("b", help="candidate trace JSONL (or a directory)")
    cp.add_argument("--json", action="store_true",
                    help="machine-readable comparison")
    cp.add_argument("--fail-on-regress", type=float, default=None,
                    metavar="PCT",
                    help="exit 1 when the candidate regresses past "
                         "PCT%% on a gated metric (it/s drop, HBM-peak "
                         "growth, compile-seconds growth) — the "
                         "mechanical perf gate for benches and CI")
    cp.add_argument("--marks", type=int, default=4,
                    help="iteration marks for the gap-trajectory "
                         "comparison (default 4)")

    pf = sub.add_parser(
        "perf", help="persistent perf ledger: per-case measurement "
                     "history and the historical regression gate "
                     "(median-of-last-N baseline) that catches drift "
                     "accumulating across individually-passing PRs "
                     "(docs/OBSERVABILITY.md 'Perf ledger')")
    pf.add_argument("action", nargs="?", default="history",
                    choices=["history", "gate"],
                    help="history (default): render per-case trends; "
                         "gate: fail on a historical regression")
    pf.add_argument("--ledger", default=None, metavar="PATH",
                    help="ledger JSONL (default: $DPSVM_PERF_LEDGER, "
                         "else benchmarks/results/perf_ledger.jsonl)")
    pf.add_argument("--case", default=None,
                    help="restrict to one case tag (default: all)")
    pf.add_argument("--metric", default="value",
                    help="reading to plot/gate: 'value' (the row's "
                         "headline) or any numeric key of the "
                         "record's metrics dict")
    pf.add_argument("--window", type=int, default=5, metavar="N",
                    help="gate baseline: median of the last N records "
                         "before the newest (default 5)")
    pf.add_argument("--fail-on-regress", type=float, default=10.0,
                    metavar="PCT",
                    help="gate threshold percent (direction-aware "
                         "like `dpsvm compare`; default 10)")
    pf.add_argument("--last", type=int, default=12,
                    help="history rows rendered per case (default 12)")
    pf.add_argument("--json", action="store_true",
                    help="machine-readable output")

    pr = sub.add_parser(
        "profile", help="render the reconciliation sidecar of a "
                        "`train --profile-dir` capture: phase-"
                        "attributed host wall split next to the run "
                        "trace's phase_counts, plus the device-trace "
                        "artifact inventory (docs/OBSERVABILITY.md "
                        "'Profiling')")
    pr.add_argument("action", choices=["summarize"],
                    help="summarize: the one reconciliation table")
    pr.add_argument("dir", help="the --profile-dir directory")
    pr.add_argument("--trace", default=None, metavar="PATH",
                    help="run-telemetry trace (or directory) to "
                         "reconcile against: its phase_counts are "
                         "printed next to the profile's phases and "
                         "the match is verified")
    pr.add_argument("--json", action="store_true",
                    help="machine-readable summary")

    wt = sub.add_parser(
        "watch", help="continuous SLO watch: tail a live /metricsz "
                      "endpoint, a --metrics-out snapshot file or an "
                      "in-flight run trace, evaluate the alert rules "
                      "and exit with a distinct code per severity "
                      "(0 = clean, 4 = warn fired, 5 = page fired, "
                      "3 = source stale/unreachable) so cron/CI can "
                      "gate on it (docs/OBSERVABILITY.md 'Watch & "
                      "alerts')")
    src = wt.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", default=None,
                     help="base URL (or full /metricsz URL) of a live "
                          "`dpsvm serve` / `train --metrics-port` "
                          "process to poll")
    src.add_argument("--metrics-file", default=None, metavar="FILE",
                     help="a `train --metrics-out` snapshot file to "
                          "tail (the seq header detects missed/"
                          "duplicate snapshots)")
    src.add_argument("--trace", default=None, metavar="PATH",
                     help="a run-telemetry trace (or directory — "
                          "newest *.jsonl) to tail; chunk records "
                          "become training watch samples")
    wt.add_argument("--rules", default=None, metavar="FILE",
                    help="alert-rules JSON (default: the built-in "
                         "serving rules for --url/--metrics-file, the "
                         "training rules for --trace)")
    wt.add_argument("--interval", type=float, default=2.0, metavar="S",
                    help="poll interval (default 2 s)")
    wt.add_argument("--for", dest="duration", type=float, default=0.0,
                    metavar="S",
                    help="watch this long then exit (0 = until the "
                         "source ends: trace summary/terminal event, "
                         "or stale timeout)")
    wt.add_argument("--once", action="store_true",
                    help="evaluate one sample and exit (CI gate mode)")
    wt.add_argument("--stale-timeout", type=float, default=60.0,
                    metavar="S",
                    help="exit 3 when the source stops updating for "
                         "this long (default 60 s)")
    wt.add_argument("--bundle-dir", default=None, metavar="DIR",
                    help="dump an incident bundle here when a rule "
                         "fires (the watch-side black box: recent "
                         "samples + alert history)")
    wt.add_argument("--json", action="store_true",
                    help="machine-readable final state instead of the "
                         "live rendering")
    wt.add_argument("-q", "--quiet", action="store_true")

    bd = sub.add_parser(
        "bundle", help="render + validate an incident bundle dumped "
                       "by the flight recorder (`--bundle-dir`): "
                       "incident manifest, embedded-trace report, "
                       "schema/exposition validation; exit 0 valid / "
                       "1 invalid (docs/OBSERVABILITY.md 'Incident "
                       "bundles')")
    bd.add_argument("dir", help="a bundle directory (incident-*) or a "
                                "parent --bundle-dir (newest bundle "
                                "wins)")
    bd.add_argument("--json", action="store_true",
                    help="machine-readable manifest + verdict")

    sv = sub.add_parser(
        "serve", help="online prediction server: micro-batched "
                      "/v1/predict over any saved model (or several), "
                      "pre-compiled shape buckets, /healthz, /metricsz, "
                      "hot reload, SIGTERM graceful drain "
                      "(docs/SERVING.md)")
    sv.add_argument("-m", "--model", action="append", required=True,
                    metavar="[NAME=]PATH",
                    help="model file or multiclass directory to serve "
                         "(repeatable; an unnamed first model is "
                         "registered as 'default')")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8317,
                    help="listen port (0 = OS-assigned; the bound port "
                         "is printed on the ready line)")
    sv.add_argument("--max-batch", type=int, default=None,
                    help="top rung of the compile-warmed bucket ladder "
                         "AND the micro-batcher's coalescing cap "
                         "(default: the backend's tuned profile when "
                         "one is active, else 256 — docs/PERF.md "
                         "'Autotuning')")
    sv.add_argument("--precision", default="highest",
                    choices=["highest", "high", "default"],
                    help="MXU precision of the decision ladder: "
                         "'highest' = exact f32 (the default and the "
                         "bitwise decision_function-parity path), "
                         "'default' = bf16 multiplies with f32 "
                         "accumulation (~the training headline's MXU "
                         "speedup at a pinned decision tolerance — "
                         "docs/SERVING.md)")
    sv.add_argument("--no-tuned", action="store_true",
                    help="ignore the tuned per-backend profile for "
                         "serving knobs left at their defaults "
                         "(DPSVM_NO_TUNED=1 is the env equivalent)")
    sv.add_argument("--max-delay-ms", type=float, default=2.0,
                    help="micro-batching deadline: a batch closes after "
                         "this long even if not full (idle-server "
                         "latency floor)")
    sv.add_argument("--max-queue", type=int, default=4096,
                    help="admission bound in ROWS; a full queue "
                         "fast-rejects with HTTP 429 instead of "
                         "queueing unboundedly")
    sv.add_argument("--no-b", action="store_true",
                    help="serve intercept-free decisions like "
                         "test --no-b")
    sv.add_argument("--port-file", default=None, metavar="PATH",
                    help="write the bound port here once listening "
                         "(for harnesses that pass --port 0)")
    sv.add_argument("--replicas", type=int, default=1,
                    help="engine replicas per model: a wedged or "
                         "NaN-poisoned replica is ejected (circuit "
                         "breaker) and rebuilt in the background while "
                         "the rest keep serving (docs/SERVING.md "
                         "Resilience)")
    sv.add_argument("--deadline-ms", type=float, default=30000.0,
                    help="server-wide request deadline budget; a "
                         "blown budget answers 504 + Retry-After. "
                         "Clients may ask for LESS via timeout_ms / "
                         "X-Deadline-Ms")
    sv.add_argument("--hedge-ms", default="off", metavar="MS|auto|off",
                    help="re-dispatch a still-unanswered request to a "
                         "second replica after this delay ('auto' = "
                         "p99-based); needs --replicas >= 2")
    sv.add_argument("--no-degrade", dest="degrade",
                    action="store_false", default=True,
                    help="disable the overload shed ladder "
                         "(proba->decision, then the sibling model) — "
                         "queue-full 429 only")
    sv.add_argument("--degrade-to", action="append", default=[],
                    metavar="NAME=SIBLING",
                    help="tier-2 shed target: under deep overload "
                         "NAME's requests are served by SIBLING (a "
                         "registered, width-compatible model — e.g. "
                         "an approx twin); repeatable")
    sv.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write a serving trace (JSONL): manifest, "
                         "eject/rebuild/shed/hedge events, per-request "
                         "span trees for sampled requests "
                         "(--trace-sample-rate), summary at drain")
    sv.add_argument("--trace-sample-rate", type=float, default=1.0,
                    metavar="R",
                    help="fraction of requests whose span tree (queue "
                         "wait / batch formation / device dispatch / "
                         "...) is recorded into --trace-out (0..1, "
                         "deterministic stride; default 1.0 — sample "
                         "down under sustained load to bound the "
                         "steady-state overhead, "
                         "docs/OBSERVABILITY.md 'Spans')")
    sv.add_argument("--watch-rules", default=None, metavar="FILE",
                    help="alert-rules JSON for the serving watchtower "
                         "(default: the built-in multi-window "
                         "availability burn-rate + queue-saturation "
                         "rules — docs/OBSERVABILITY.md 'Watch & "
                         "alerts'); alert states ride /metricsz and "
                         "the events ring")
    sv.add_argument("--bundle-dir", default=None, metavar="DIR",
                    help="dump a self-contained incident bundle here "
                         "when a watch rule fires (flight-recorder "
                         "trace + metrics + doctor facts; render with "
                         "`dpsvm bundle DIR`)")
    sv.add_argument("--no-watch", dest="watch", action="store_false",
                    default=True,
                    help="disable the continuous SLO watchtower")
    sv.add_argument("--tenant-budget", type=int, default=None,
                    metavar="K",
                    help="per-tenant metric label budget: at most K "
                         "tenants get their own /metricsz series and "
                         "cost ledger rows; the long tail folds into "
                         "the mandatory 'other' bucket (LRU-of-"
                         "activity eviction; default 32 — "
                         "docs/OBSERVABILITY.md 'Per-tenant "
                         "attribution')")
    sv.add_argument("--model-cache-budget", type=int, default=None,
                    metavar="K",
                    help="arm the HBM model cache: at most K models "
                         "resident on device at once; the rest are "
                         "registered lazily (manifest-only) and "
                         "hydrate on first request (counted "
                         "model_fault, second-touch admission + "
                         "LRU-of-activity eviction). Same-spec "
                         "residents share ONE batched decision "
                         "program (docs/SERVING.md 'Model fleet')")
    sv.add_argument("--front-end", choices=["threaded", "async"],
                    default="threaded",
                    help="HTTP transport: 'threaded' (stdlib thread-"
                         "per-connection, the default) or 'async' (one "
                         "asyncio event loop holds every connection — "
                         "10k+ keep-alive clients without 10k threads, "
                         "bitwise-identical responses, same drain "
                         "contract; adds the weighted-fair per-tenant "
                         "admission queue — docs/SERVING.md 'Front "
                         "door')")
    sv.add_argument("--tenant-weight", action="append", default=[],
                    metavar="NAME=W",
                    help="DRR weight for a tenant's fair-queue lane on "
                         "the async front end (repeatable; default 1; "
                         "an 8-weight lane gets 8x the service of a "
                         "1-weight lane under contention; the 'other' "
                         "long-tail bucket shares one lane)")
    sv.add_argument("--max-connections", type=int, default=10000,
                    help="async front end only: open-connection cap — "
                         "beyond it new connections get an immediate "
                         "503 + close (doctor WARNs at 80%%)")
    sv.add_argument("--hbm-budget-mb", type=float, default=None,
                    metavar="MB",
                    help="per-device budget for a model's packed "
                         "buffers: a model whose estimated resident "
                         "bytes exceed it is served through the mesh-"
                         "sharded decision path instead (SV axis for "
                         "dual models, feature-block axis for approx "
                         "models, psum-reduced over the local devices; "
                         "bitwise == the unsharded blocked reference "
                         "— docs/SERVING.md 'Front door')")
    sv.add_argument("-q", "--quiet", action="store_true")
    _add_backend_flags(sv)

    lg = sub.add_parser(
        "loadgen", help="open/closed-loop load generator against a "
                        "running `dpsvm serve`; prints ONE JSON row "
                        "with throughput + p50/p95/p99 latency and the "
                        "sequential batch-1 baseline (docs/SERVING.md)")
    lg.add_argument("--url", default="http://127.0.0.1:8317",
                    help="server base URL")
    lg.add_argument("--model", default="default",
                    help="registered model name to target")
    lg.add_argument("-f", "--input", default=None,
                    help="dataset whose feature rows become request "
                         "payloads (labels ignored); synthetic rows at "
                         "the model's width when omitted")
    lg.add_argument("--mode", choices=["closed", "open"],
                    default="closed",
                    help="closed = each worker fires on completion "
                         "(saturation probe, exercises coalescing); "
                         "open = fixed-schedule arrivals at --rps")
    lg.add_argument("--requests", type=int, default=200)
    lg.add_argument("--batch", type=int, default=1,
                    help="rows per request")
    lg.add_argument("--concurrency", type=int, default=8)
    lg.add_argument("--rps", type=float, default=100.0,
                    help="open-loop target arrival rate")
    lg.add_argument("--return", dest="want", default="labels",
                    metavar="K1,K2",
                    help="comma list of outputs to request: labels, "
                         "decision, proba")
    lg.add_argument("--timeout", type=float, default=30.0)
    lg.add_argument("--trace", default=None, metavar="PATH",
                    help="provenance trace pointer carried in the "
                         "result row (the serving side's --trace-out "
                         "artifact, or an archived copy) — the same "
                         "field burst-runner rows carry, so serving "
                         "SLO rows are ledger- and compare-traceable "
                         "like training rows (default: "
                         "$BENCH_TRACE_OUT when set)")
    lg.add_argument("--no-ledger", dest="ledger", action="store_false",
                    default=True,
                    help="skip the perf-ledger append "
                         "(docs/OBSERVABILITY.md 'Perf ledger')")
    lg.add_argument("--no-compare-sequential", dest="compare_sequential",
                    action="store_false", default=True,
                    help="skip the batch-1 single-worker baseline pass "
                         "(halves runtime; drops the coalesce_speedup "
                         "fields from the row)")
    lg.add_argument("--chaos", action="store_true",
                    help="chaos-drill report: arm DPSVM_FAULT_SERVE_* "
                         "on the serve process, run this, and the row "
                         "carries availability of accepted requests + "
                         "the /metricsz robustness-counter deltas "
                         "(ejections, rebuilds, hedges, sheds)")
    lg.add_argument("--saturate", action="store_true",
                    help="drive-to-saturation instead: step open-loop "
                         "RPS by --rps-factor until p99 exceeds "
                         "--p99-target-ms and print ONE SLO row (max "
                         "sustained throughput at p99 < target + "
                         "availability)")
    lg.add_argument("--p99-target-ms", type=float, default=50.0)
    lg.add_argument("--start-rps", type=float, default=25.0)
    lg.add_argument("--rps-factor", type=float, default=2.0)
    lg.add_argument("--max-steps", type=int, default=8)
    lg.add_argument("--step-requests", type=int, default=100,
                    help="requests per saturation step")
    lg.add_argument("--tenants", type=int, default=0, metavar="N",
                    help="stamp requests with N synthetic tenant "
                         "labels t0..t{N-1} (body 'tenant' field); "
                         "the row gains per-tenant request counts and "
                         "p50/p99, and a tenant_isolation perf-ledger "
                         "row when combined with --hot-tenant-skew")
    lg.add_argument("--hot-tenant-skew", type=float, default=0.0,
                    metavar="S",
                    help="fraction (0..1) of requests sent by the "
                         "single hot tenant t0; the rest round-robin "
                         "the cold tenants — the noisy-neighbour "
                         "drill shape (docs/OBSERVABILITY.md "
                         "'Per-tenant attribution')")
    lg.add_argument("--models", type=int, default=0, metavar="N",
                    help="spread requests over the first N models "
                         "from the server's /v1/models list (sorted; "
                         "--model is forced to the front as the hot "
                         "model) — the model-fleet drill. The row "
                         "gains per-model p50/p99 sub-rows and "
                         "cold_start_p99_ms (p99 over each model's "
                         "FIRST-request latency — the number the HBM "
                         "model cache bounds; docs/SERVING.md "
                         "'Model fleet')")
    lg.add_argument("--model-skew", type=float, default=0.0,
                    metavar="S",
                    help="fraction (0..1) of requests sent to the "
                         "single hot model (--model); the rest "
                         "round-robin the remaining N-1 — same "
                         "deterministic stride as --hot-tenant-skew. "
                         "0 round-robins all N (the cache-thrash "
                         "worst case when N exceeds the cache budget)")
    lg.add_argument("--connections", type=int, default=0, metavar="N",
                    help="pre-open and HOLD N keep-alive connections "
                         "for the whole run; the first --concurrency "
                         "carry the traffic, the rest sit idle-open — "
                         "the front-door drill shape (thousands of "
                         "mostly-idle sockets; docs/SERVING.md 'Front "
                         "door'). The row gains open_connections")

    gd = sub.add_parser(
        "grid", help="mesh-parallel C×gamma grid trainer: the whole "
                     "grid runs as batched programs spread over the "
                     "local devices (one compile per device, not one "
                     "per cell), per-cell held-out accuracy, optional "
                     "cascade polish of the winner; prints ONE JSON "
                     "row and can promote the winner into a serving "
                     "artifact atomically (docs/SERVING.md 'Model "
                     "fleet')")
    _add_data_flags(gd, model_required=False)
    _add_backend_flags(gd)
    gd.add_argument("--cs", default="0.25,1,4,16", metavar="C1,C2",
                    help="comma list of C values — the grid rows "
                         "(default 0.25,1,4,16)")
    gd.add_argument("--gammas", default=None, metavar="G1,G2",
                    help="comma list of gamma values — the grid "
                         "columns (default: one column at the 1/d "
                         "default)")
    gd.add_argument("-k", "--kernel", default="rbf",
                    choices=["rbf", "linear", "poly", "sigmoid"])
    gd.add_argument("-d", "--degree", type=int, default=3)
    gd.add_argument("--coef0", type=float, default=0.0)
    gd.add_argument("--max-iter", type=int, default=None,
                    help="per-cell iteration cap (default: the "
                         "config default)")
    gd.add_argument("--holdout-frac", type=float, default=0.2,
                    help="fraction of rows held out for per-cell "
                         "scoring (seeded shuffle split; the winner "
                         "is the best held-out accuracy, row-major "
                         "first-wins tie-break)")
    gd.add_argument("--seed", type=int, default=0,
                    help="holdout-split shuffle seed (replayable)")
    gd.add_argument("--polish", action="store_true",
                    help="re-fit the winning cell with the cascade "
                         "solver on ALL rows (train+holdout) before "
                         "saving/promoting — the production-artifact "
                         "finish")
    gd.add_argument("--compare-sequential", action="store_true",
                    help="also fit every cell sequentially (one "
                         "program each, the no-batching baseline) and "
                         "report + ledger the grid_vs_sequential "
                         "speedup (docs/PERF.md)")
    gd.add_argument("--out", default=None, metavar="PATH",
                    help="save the winning model here (atomic "
                         "tmp+rename in the target directory)")
    gd.add_argument("--promote", default=None, metavar="PATH",
                    help="promote the winner onto this serving "
                         "artifact path via the registry's atomic "
                         "promote_file (os.replace + validating "
                         "reload) — a `dpsvm serve` hot-reload of the "
                         "same path picks it up (docs/SERVING.md "
                         "'Continuous learning')")
    gd.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write the grid training trace here "
                         "(solver='grid': one grid_cell event per "
                         "cell, grid_winner, summary) — the "
                         "provenance pointer the ledger rows carry")
    gd.add_argument("--no-ledger", dest="ledger", action="store_false",
                    default=True,
                    help="skip the perf-ledger append")
    gd.add_argument("--json", action="store_true",
                    help="print the full result row as JSON instead "
                         "of the per-cell table")
    gd.add_argument("-q", "--quiet", action="store_true")

    tns = sub.add_parser(
        "tenants", help="per-tenant cost attribution table: who "
                        "spends the fleet's device compute, rows and "
                        "queue time — from a serving trace's span "
                        "records or a live /metricsz endpoint "
                        "(docs/OBSERVABILITY.md 'Per-tenant "
                        "attribution')")
    tsrc = tns.add_mutually_exclusive_group(required=True)
    tsrc.add_argument("--url", default=None,
                      help="base URL (or full /metricsz URL) of a "
                           "live `dpsvm serve` process: renders its "
                           "tenant cost ledger")
    tsrc.add_argument("trace", nargs="?", default=None,
                      help="serving trace JSONL (serve --trace-out), "
                           "or a directory — newest *.jsonl; costs "
                           "are attributed from sampled span trees")
    tns.add_argument("--top", type=int, default=None, metavar="K",
                     help="show only the K most expensive tenants by "
                          "attributed wall time (default: all)")
    tns.add_argument("--json", action="store_true",
                     help="machine-readable rows instead of the table")
    tns.add_argument("--timeout", type=float, default=10.0,
                     help="--url fetch timeout seconds")

    fl = sub.add_parser(
        "fleet", help="multi-host metrics federation: fold N hosts' "
                      "`train --metrics-out` snapshot files and/or "
                      "live /metricsz URLs into ONE fleet table + "
                      "Prometheus exposition (counters summed, ages "
                      "maxed, group iteration min'ed, per-host lanes "
                      "under a bounded `host` label) "
                      "(docs/OBSERVABILITY.md 'Fleet')")
    fl.add_argument("sources", nargs="+", metavar="SRC",
                    help="per-host sources: metrics snapshot files "
                         "(metrics_h0.prom ...) and/or base URLs of "
                         "live `train --metrics-port` / `dpsvm "
                         "serve` processes; host ids parse from the "
                         "names (h0/host-1/...), else positional")
    fl.add_argument("--hosts-dir", default=None, metavar="DIR",
                    help="hostgroup heartbeat directory (--coordinator "
                         "runs write host-K.json there): joins "
                         "generation/seq liveness into the table")
    fl.add_argument("--out", default=None, metavar="FILE",
                    help="also write the federated Prometheus "
                         "exposition here (the fleet-level "
                         "--metrics-out; '-' = stdout)")
    fl.add_argument("--watch", action="store_true",
                    help="evaluate the fleet alert rules (default: "
                         "the built-in fleet set — heartbeat-stale "
                         "page, reform-storm page, iteration-skew "
                         "warn) against one federated sample and use "
                         "the watch exit codes (4 warn / 5 page)")
    fl.add_argument("--rules", default=None, metavar="FILE",
                    help="alert-rules JSON for --watch (default: the "
                         "built-in fleet rules)")
    fl.add_argument("--json", action="store_true",
                    help="machine-readable fleet snapshot instead of "
                         "the table")
    fl.add_argument("--timeout", type=float, default=5.0,
                    help="per-URL fetch timeout seconds (default 5)")

    tn = sub.add_parser(
        "tune", help="measure this backend's throughput-critical "
                     "knobs (successive-halving probes through the "
                     "real driver/serving plumbing, deadline-bounded) "
                     "and persist a per-backend tuned profile that "
                     "train/serve consult for knobs left at their "
                     "defaults (docs/PERF.md 'Autotuning')")
    _add_backend_flags(tn)
    tn.add_argument("-f", "--input", default=None,
                    help="dataset whose rows drive the probes "
                         "(synthetic planted data at --n x --d when "
                         "omitted — probes measure throughput, not "
                         "model quality)")
    tn.add_argument("--n", type=int, default=8192,
                    help="synthetic probe rows (ignored with -f)")
    tn.add_argument("--d", type=int, default=64,
                    help="synthetic probe features (ignored with -f)")
    tn.add_argument("-c", "--cost", type=float, default=10.0,
                    help="probe-problem cost (harder problems sustain "
                         "longer measurement windows)")
    tn.add_argument("-g", "--gamma", type=float, default=None)
    tn.add_argument("--knobs",
                    default="chunk_iters,cache_lines,serve_max_batch",
                    help="comma list of knobs to probe (chunk_iters | "
                         "cache_lines | serve_max_batch)")
    tn.add_argument("--grid", action="append", default=[],
                    metavar="KNOB=V1,V2,...",
                    help="override one knob's candidate grid "
                         "(repeatable); the built-in default value is "
                         "always added so the comparison stays "
                         "anchored")
    tn.add_argument("--probe-iters", type=int, default=2000,
                    metavar="I",
                    help="iteration budget of the FIRST halving rung "
                         "(each later rung doubles it)")
    tn.add_argument("--rungs", type=int, default=3,
                    help="successive-halving rungs (default 3)")
    tn.add_argument("--deadline-s", type=float, default=300.0,
                    help="wall deadline for the whole tune run: "
                         "finished knobs keep their verdicts, "
                         "unfinished knobs keep their defaults")
    tn.add_argument("--min-win-pct", type=float, default=2.0,
                    help="a candidate must beat the measured default "
                         "by this percent at the final rung or the "
                         "default is kept (default 2)")
    tn.add_argument("--out", default=None, metavar="PATH",
                    help="profile file (default: "
                         "$DPSVM_TUNED_PROFILE, else benchmarks/"
                         "results/tuned_profile.json)")
    tn.add_argument("--trace-dir", default=None, metavar="DIR",
                    help="probe/A-B trace directory (default: "
                         "traces/tune next to the profile)")
    tn.add_argument("--no-ledger", dest="ledger",
                    action="store_false", default=True,
                    help="skip the perf-ledger appends")
    tn.add_argument("-q", "--quiet", action="store_true")
    return root


_KERNEL_BY_T = {"0": "linear", "1": "poly", "2": "rbf", "3": "sigmoid",
                "4": "precomputed"}


def _shrinking_value(v: str):
    """LIBSVM-style -h values plus the shape-resolved sentinel:
    0/off/false, 1/on/true, auto."""
    lv = v.strip().lower()
    if lv in ("0", "off", "false"):
        return False
    if lv in ("1", "on", "true"):
        return True
    if lv == "auto":
        return "auto"
    raise argparse.ArgumentTypeError(
        f"--shrinking takes 0, 1 or auto, got {v!r}")


def _finite_weight(v: str) -> float:
    """Class weights must be finite and > 0 — rejected at parse time,
    before the (possibly huge) dataset load. ``float`` alone accepts
    'nan'/'inf', and NaN sails through every downstream `<= 0`
    comparison (ADVICE r5)."""
    try:
        w = float(v)
    except ValueError:
        raise argparse.ArgumentTypeError(f"{v!r} is not a number")
    if not (math.isfinite(w) and w > 0):
        raise argparse.ArgumentTypeError(
            f"class weights must be finite and > 0, got {v}")
    return w


def _existing_checkpoint(v: str) -> str:
    """--resume paths are validated at parse time — before the backend
    probe and the (possibly huge) dataset load — so a typo'd path is a
    one-line error, not a deferred FileNotFoundError traceback (same
    policy as the non-finite class-weight rejection)."""
    if not os.path.isfile(v):
        raise argparse.ArgumentTypeError(
            f"no such checkpoint file: {v}")
    return v


def _kernel_name(v: str) -> str:
    """Accept LIBSVM -t integers as aliases for the kernel names; reject
    anything else at parse time (before the dataset is loaded)."""
    name = _KERNEL_BY_T.get(v, v)
    if name not in _KERNEL_BY_T.values():
        raise argparse.ArgumentTypeError(
            f"{v!r} is not a kernel (linear | poly | rbf | sigmoid | "
            "precomputed, or LIBSVM -t 0..4)")
    return name


def _train_streaming(args: argparse.Namespace, config) -> int:
    """Plain train on a shard directory: the out-of-core approx path
    (docs/DATA.md "Streaming training"). The data never materializes;
    training metrics come from a second streamed pass through the same
    integrity-checked reader (so a quarantined shard is excluded from
    the reported accuracy exactly as it was from the gradient)."""
    import numpy as np

    from dpsvm_tpu.approx.primal import fit_approx_stream
    from dpsvm_tpu.data.stream import ShardedDataset
    from dpsvm_tpu.models.io import save_model
    from dpsvm_tpu.models.svm import decision_function

    if args.probability_cv:
        print("error: --probability-cv refits on held-out folds, "
              "which needs the materialized dataset; use "
              "--probability (streamed decisions) or materialize",
              file=sys.stderr)
        return 2
    if args.num_ex is not None or args.num_att is not None:
        # No-silent-ignore: the manifest owns the shard geometry.
        print("error: -x/--num-ex and -a/--num-att do not apply to "
              "streaming shard training — the manifest fixes the "
              "shapes (re-convert to change them)", file=sys.stderr)
        return 2
    if args.check_kkt:
        print("error: --check-kkt recomputes the KKT residual over the "
              "materialized training set; streaming shard training "
              "never materializes it", file=sys.stderr)
        return 2
    ds = ShardedDataset.open(args.input)
    task = "svr" if args.svr else "svc"
    if config.solver == "cascade":
        # Out-of-core cascade (docs/APPROX.md "Cascade"): approx
        # warm-start + screening stream shard-by-shard; only the
        # screened exact subproblem materializes (budget-guarded).
        from dpsvm_tpu.solver.cascade import fit_cascade_stream
        model, result = fit_cascade_stream(
            ds, config, allow_nonfinite=args.allow_nonfinite)
        n_sv = save_model(model, args.model)
        print(f"Number of SVs: {n_sv}")
        print(f"Cascade: screened {result.n_total} -> {result.n_kept} "
              f"rows ({result.readmit_rounds} polish round(s), "
              f"{result.n_readmitted} re-admitted, "
              f"{result.kkt_violators} KKT violator(s); streamed from "
              f"{ds.n_shards} shard(s)"
              + (f", {len(ds.quarantined)} quarantined"
                 if ds.quarantined else "") + ")")
    else:
        model, result = fit_approx_stream(
            ds, config, task=task, allow_nonfinite=args.allow_nonfinite)
        save_model(model, args.model)
        print(f"Approx model: {model.model_kind} dim={model.fmap.dim} "
              f"(no SV set; streamed from {ds.n_shards} shard(s)"
              + (f", {len(ds.quarantined)} quarantined"
                 if ds.quarantined else "") + ")")
    print(f"b: {result.b:.6f}")
    print(f"Training iterations: {result.n_iter}"
          + ("" if result.converged
             else " (max-iter reached, NOT converged)"))
    decs = []
    labs = []
    for _k, xk, yk in ds.iter_shards(on_bad_shard=config.on_bad_shard,
                                     allow_nonfinite=args.allow_nonfinite):
        decs.append(np.asarray(decision_function(model, xk)))
        labs.append(np.asarray(yk))
    dec = np.concatenate(decs)
    lab = np.concatenate(labs)
    if task == "svc":
        pred = np.where(dec < 0, -1, 1)
        print(f"Training accuracy: "
              f"{float(np.mean(pred == lab.astype(np.int32))):.6f} "
              f"(streamed, {len(lab)} rows)")
    else:
        err = dec - lab.astype(np.float64)
        print(f"Training MSE: {float(np.mean(err ** 2)):.6f}  "
              f"MAE: {float(np.mean(np.abs(err))):.6f} "
              f"(streamed, {len(lab)} rows)")
    print(f"Training time: {result.train_seconds:.3f} s")
    if args.probability and task == "svc":
        from dpsvm_tpu.models.calibration import fit_platt, save_platt
        pa, pb = fit_platt(dec, lab)
        save_platt(args.model, pa, pb)
        print(f"Platt calibration: A={pa:.6f} B={pb:.6f} "
              f"(saved {args.model}.platt.json; fit on streamed "
              "decisions)")
    return 0


def cmd_train(args: argparse.Namespace) -> int:
    # Imports deferred so --help / arg errors don't pay the jax import.
    import numpy as np

    from dpsvm_tpu.api import fit
    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.data.loader import load_dataset
    from dpsvm_tpu.models.io import save_model
    from dpsvm_tpu.models.svm import evaluate

    if args.model_format == "libsvm":
        from dpsvm_tpu.models.libsvm_io import save_libsvm_model
        save_model = save_libsvm_model
        if args.multiclass:
            print("error: --model-format libsvm applies to binary "
                  "models; --multiclass writes a directory of "
                  "reference-format per-pair files", file=sys.stderr)
            return 2

    if args.gamma_sweep is not None and args.c_sweep is None:
        print("error: --gamma-sweep extends --c-sweep (pass both)",
              file=sys.stderr)
        return 2
    if args.solver != "exact":
        # Approx/cascade-solver conflicts detectable from args alone
        # (the config capability table rejects the solver-level ones).
        # The cascade's outputs are ordinary SV models with full-length
        # duals, so --check-kkt and --model-format libsvm stay valid
        # there; the batched sweep programs stay dual-solver-only.
        approx = args.solver.startswith("approx")
        for flag, on, hint in (
                ("--c-sweep", args.c_sweep is not None,
                 " (the batched sweep is a dual-solver program)"),
                ("--batched", args.batched,
                 " (the batched program solves the dual iteration)"),
                ("--check-kkt", approx and args.check_kkt,
                 " (KKT/duality-gap reporting is dual-specific; the "
                 "primal path reports its gradient-norm metric in the "
                 "run trace — --solver cascade supports it)"),
                ("--model-format libsvm",
                 approx and args.model_format == "libsvm",
                 " (approx models persist as .npz — no SV lines to "
                 "write; --solver cascade writes ordinary SV models)")):
            if on:
                print(f"error: {flag} does not apply to --solver "
                      f"{args.solver}{hint}", file=sys.stderr)
                return 2
    if args.c_sweep is not None and not args.cv:
        print("error: --c-sweep requires --cv K (it selects C by "
              "cross-validated accuracy)", file=sys.stderr)
        return 2
    if args.c_sweep is not None and (args.svr or args.multiclass):
        print("error: --c-sweep is binary-classification-only",
              file=sys.stderr)
        return 2
    if args.batched and not (args.multiclass or args.cv):
        print("error: --batched applies to --multiclass or --cv "
              "training", file=sys.stderr)
        return 2
    if args.batched and args.svr:
        print("error: batched CV is classification-only (SVR folds "
              "train on per-fold pseudo-examples)", file=sys.stderr)
        return 2
    if args.multiclass:
        # Flag conflicts are detectable from args alone — fail before
        # the (possibly huge) CSV parse.
        import os
        if args.model and os.path.isfile(args.model):
            print(f"error: -m {args.model} is an existing file; "
                  "--multiclass writes a model DIRECTORY",
                  file=sys.stderr)
            return 2
        if args.check_kkt:
            print("error: --check-kkt reports on a single binary "
                  "subproblem; it does not apply to --multiclass runs",
                  file=sys.stderr)
            return 2
        if args.checkpoint or args.resume:
            print("error: --checkpoint/--resume are single-model flags; "
                  "they cannot be shared across the pairwise multiclass "
                  "subproblems", file=sys.stderr)
            return 2
        if args.trace_out:
            print("error: --trace-out records ONE training run; the "
                  "pairwise multiclass subproblems would each overwrite "
                  "it", file=sys.stderr)
            return 2
        if args.weight_pos != 1.0 or args.weight_neg != 1.0:
            # In OvO, '+1' is just the lower-sorted label of each pair —
            # a +/-1 weight would attach to an arbitrary pseudo-label,
            # not to any actual data class (LIBSVM -wi maps by label).
            print("error: --weight-pos/--weight-neg are binary-problem "
                  "flags; weight multiclass classes by LABEL with "
                  "--weight LABEL:W instead", file=sys.stderr)
            return 2
        if args.weight and args.batched:
            print("error: --weight needs per-pair box bounds; the "
                  "batched program shares one weight pair across all "
                  "subproblems — drop --batched", file=sys.stderr)
            return 2
        if args.weight and args.clip == "independent":
            print("error: --weight trains each pair with the joint "
                  "(pairwise) alpha update — LIBSVM -wi semantics; "
                  "the independent clip drifts sum(alpha*y) at "
                  "asymmetric bounds. Drop --clip independent",
                  file=sys.stderr)
            return 2
    elif args.weight and not args.cv:
        print("error: --weight maps costs by class LABEL and applies "
              "to --multiclass or --cv training; use "
              "--weight-pos/--weight-neg for a plain binary problem",
              file=sys.stderr)
        return 2
    elif args.weight:
        # --cv: same scope rules as train_multiclass(class_weight=...)
        if args.batched:
            print("error: --weight needs per-pair box bounds; the "
                  "batched program shares one weight pair across all "
                  "subproblems — drop --batched", file=sys.stderr)
            return 2
        if args.svr:
            print("error: --weight is classification-only (SVR has no "
                  "classes)", file=sys.stderr)
            return 2
        if args.c_sweep is not None:
            print("error: --weight is not supported with --c-sweep "
                  "(the batched grid program shares one weight pair)",
                  file=sys.stderr)
            return 2
        if args.clip == "independent":
            print("error: --weight trains with the joint (pairwise) "
                  "alpha update — LIBSVM -wi semantics; drop "
                  "--clip independent", file=sys.stderr)
            return 2
    # Parse --weight specs HERE: a malformed spec is detectable from
    # args alone and must fail before the (possibly huge) CSV parse.
    class_weight = None
    if args.weight:
        class_weight = {}
        for spec in args.weight:
            label, sep, w = spec.partition(":")
            try:
                if not sep:
                    raise ValueError
                key = int(label) if "." not in label else float(label)
                wv = float(w)
                # same finite-and-positive contract as --weight-pos/-neg
                # (SVMConfig.validate would catch it per pair, but only
                # after the dataset parse and k-1 trainings)
                if not (math.isfinite(wv) and wv > 0):
                    print(f"error: --weight {spec!r}: weights must be "
                          "finite and > 0", file=sys.stderr)
                    return 2
                class_weight[key] = wv
            except ValueError:
                print(f"error: --weight {spec!r} is not LABEL:W "
                      "(e.g. --weight 3:5.0)", file=sys.stderr)
                return 2

    if not args.cv and not args.model:
        print("error: -m/--model is required (or pass --cv K for "
              "cross-validation)", file=sys.stderr)
        return 2
    if args.cv:
        if args.cv < 2:
            print(f"error: --cv needs K >= 2, got {args.cv}",
                  file=sys.stderr)
            return 2
        for flag, on, hint in (
                ("--one-class", args.one_class, ""),
                ("--probability-cv" if args.probability_cv
                 else "--probability",
                 args.probability or args.probability_cv, ""),
                ("--check-kkt", args.check_kkt, ""),
                ("--multiclass", args.multiclass,
                 " (CV dispatches to one-vs-one automatically when the "
                 "labels have more than two classes)"),
                ("--checkpoint/--resume",
                 bool(args.checkpoint or args.resume), ""),
                ("--trace-out", bool(args.trace_out),
                 " (it records one run; folds would overwrite it)")):
            if on:
                print(f"error: {flag} does not apply to --cv mode{hint}",
                      file=sys.stderr)
                return 2
    modes = [f for f, on in (("--svr", args.svr),
                             ("--one-class", args.one_class),
                             ("--nu-svc", args.nu_svc),
                             ("--nu-svr", args.nu_svr)) if on]
    if len(modes) > 1:
        print(f"error: {' and '.join(modes)} are mutually exclusive",
              file=sys.stderr)
        return 2
    if modes:
        # One conflict table for every restricted mode — a new flag
        # must be added exactly once.
        mode = modes[0]
        nu_mode = mode in ("--nu-svc", "--nu-svr")
        # nu-SVC composes with --multiclass (LIBSVM -s 1 is OvO for
        # >2 classes); every other restricted mode still conflicts.
        nu_multiclass = args.multiclass and mode == "--nu-svc"
        conflicts = [("--multiclass",
                      args.multiclass and mode != "--nu-svc"),
                     # one-class/nu duals live on equality constraints
                     # the primal squared-hinge objective does not have;
                     # approx SVC/SVR are the supported primal tasks
                     # (the cascade's screening band is a
                     # classification-margin rule: SVC only)
                     (f"--solver {args.solver}",
                      args.solver != "exact"
                      and (mode != "--svr"
                           or args.solver == "cascade")),
                     # nu-SVC multiclass supports --probability (sigmoid
                     # on training decisions); --probability-cv stays
                     # rejected (its held-out refits are C-SVC)
                     ("--probability-cv" if args.probability_cv
                      else "--probability",
                      (args.probability_cv or
                       (args.probability and not nu_multiclass))),
                     ("--check-kkt", args.check_kkt),
                     ("--polish", args.polish),
                     ("--pallas on", args.pallas == "on"),
                     ("--weight-pos/--weight-neg",
                      args.weight_pos != 1.0 or args.weight_neg != 1.0),
                     # these modes' duals live on an equality
                     # constraint whose VALUE is part of the model;
                     # they force the conserving pairwise rule
                     ("--clip independent", args.clip == "independent")]
        if nu_mode:
            conflicts += [("--cv", bool(args.cv)),
                          ("--checkpoint/--resume",
                           bool(args.checkpoint or args.resume))]
        for flag, on in conflicts:
            if on:
                print(f"error: {flag} does not apply to {mode}",
                      file=sys.stderr)
                return 2

    # Shard-directory inputs (docs/DATA.md): an approx-solver plain
    # train STREAMS the shards (the data never materializes —
    # approx/primal.fit_approx_stream); every other mode reads the
    # directory through load_dataset's materializing integrity path,
    # subject to the same --mem-budget-mb admission guard as files.
    from dpsvm_tpu.data import stream as streamlib
    stream_train = False
    if streamlib.is_shard_dir(args.input):
        restricted = (args.cv or args.multiclass or args.one_class
                      or args.nu_svc or args.nu_svr)
        if args.solver != "exact" and not restricted:
            stream_train = True
        elif args.solver != "exact":
            print("note: this mode materializes the shard directory "
                  "(streaming covers plain --solver approx-* "
                  "training); reads stay integrity-checked and "
                  "budget-guarded", file=sys.stderr)
    if getattr(args, "live", False) and not stream_train:
        # No-silent-ignore: live ingest IS the streaming train path.
        print("error: --live applies to streaming shard-directory "
              "training (--solver approx-* on a converted directory); "
              "this input/mode trains a frozen materialized view",
              file=sys.stderr)
        return 2
    if stream_train:
        x = y = None
    else:
        x, y = load_dataset(args.input, args.num_ex, args.num_att,
                            float_labels=(args.svr or args.one_class
                                          or args.nu_svr),
                            allow_nonfinite=args.allow_nonfinite,
                            mem_budget_mb=args.mem_budget_mb,
                            on_bad_shard=args.on_bad_shard)
    # Tunable-knob explicitness (docs/PERF.md "Autotuning"): these
    # flags default to None so an operator setting them — even TO the
    # built-in default — is distinguishable from leaving them alone,
    # and explicit values always beat a tuned profile.
    explicit_knobs = set()
    if args.cache_size is not None:
        explicit_knobs.add("cache_size")
    if args.chunk_iters is not None:
        explicit_knobs.add("chunk_iters")
    config = SVMConfig(
        c=args.cost, gamma=args.gamma, kernel=args.kernel,
        degree=args.degree, coef0=args.coef0, epsilon=args.epsilon,
        svr_epsilon=args.svr_epsilon,
        max_iter=args.max_iter,
        cache_size=(args.cache_size if args.cache_size is not None
                    else 0),
        chunk_iters=(args.chunk_iters if args.chunk_iters is not None
                     else 512),
        backend=args.backend,
        shards=args.shards, shard_x=not args.replicate_x,
        verbose=not args.quiet,
        checkpoint_path=args.checkpoint,
        checkpoint_every=args.checkpoint_every,
        checkpoint_keep=args.checkpoint_keep,
        resume_from=args.resume,
        on_divergence=args.on_divergence,
        health_window=args.health_window,
        profile_dir=args.profile_dir,
        metrics_port=args.metrics_port,
        metrics_out=args.metrics_out,
        trace_out=args.trace_out,
        watch_rules=args.watch_rules,
        bundle_dir=args.bundle_dir,
        debug_nans=args.debug_nans,
        matmul_precision=args.precision,
        polish=args.polish,
        use_pallas=args.pallas,
        selection=args.selection,
        select_impl=args.select_impl,
        working_set=args.working_set,
        inner_iters=args.inner_iters,
        grow_working_set=args.grow_working_set,
        shrinking=args.shrinking,
        weight_pos=args.weight_pos,
        weight_neg=args.weight_neg,
        clip=args.clip or "independent",
        solver=args.solver,
        approx_dim=args.approx_dim,
        approx_seed=args.approx_seed,
        screen_margin=args.screen_margin,
        screen_cap=args.screen_cap,
        mem_budget_mb=args.mem_budget_mb,
        on_bad_shard=args.on_bad_shard,
        live=getattr(args, "live", False),
    )
    # Tuned-profile resolution: explicit value > tuned profile >
    # built-in default (tuning/profile.py; opt out with --no-tuned /
    # DPSVM_NO_TUNED=1; `dpsvm doctor` reports the active entry).
    if not args.no_tuned:
        from dpsvm_tpu.tuning import profile as tuned_profile
        config, tuned_applied = tuned_profile.apply_tuned(
            config, explicit=explicit_knobs)
        if tuned_applied and not args.quiet:
            print("tuned profile: "
                  + ", ".join(f"{k}={v}" for k, v
                              in sorted(tuned_applied.items()))
                  + " (--no-tuned for built-in defaults)",
                  file=sys.stderr)
    if stream_train:
        return _train_streaming(args, config)
    if args.multiclass:
        from dpsvm_tpu.models.multiclass import (evaluate_multiclass,
                                                 save_multiclass,
                                                 train_multiclass)
        proba_mode = ("cv" if args.probability_cv
                      else args.probability)
        mc, results = train_multiclass(x, y, config,
                                       probability=proba_mode,
                                       batched=args.batched,
                                       class_weight=class_weight,
                                       nu=(args.nu if args.nu_svc
                                           else None))
        save_multiclass(mc, args.model)
        acc = evaluate_multiclass(mc, x, y)
        if proba_mode:
            print(f"Platt calibration: {len(mc.models)} per-pair "
                  "sigmoids"
                  + (" (5-fold held-out fit)" if proba_mode == "cv"
                     else "")
                  + " (pairwise-coupled at test time; LIBSVM -b)")
        print(f"Classes: {[int(c) for c in mc.classes]} "
              f"({len(mc.models)} pairwise models)")
        print(f"Training iterations: "
              f"{sum(r.n_iter for r in results)} total"
              + ("" if all(r.converged for r in results)
                 else " (some pairs NOT converged)"))
        print(f"Training accuracy: {acc:.6f}")
        print(f"Training time: "
              f"{sum(r.train_seconds for r in results):.3f} s")
        return 0

    if args.cv:
        from dpsvm_tpu.models.cv import cross_validate
        if args.c_sweep is not None:
            from dpsvm_tpu.models.cv import cross_validate_c_sweep
            try:
                cs = [float(t) for t in args.c_sweep.split(",") if t]
                gs = ([float(t) for t in args.gamma_sweep.split(",") if t]
                      if args.gamma_sweep is not None else None)
            except ValueError:
                print("error: --c-sweep/--gamma-sweep need comma lists "
                      "of numbers", file=sys.stderr)
                return 2
            r = cross_validate_c_sweep(x, y, args.cv, cs, config,
                                       gammas=gs)
            if gs is None:
                for c, a in zip(r["cs"], r["accuracies"]):
                    print(f"C={c:g}: Cross Validation Accuracy = "
                          f"{a * 100:.4f}%")
                print(f"Best: C={r['best_c']:g} "
                      f"({r['best_accuracy'] * 100:.4f}%)")
                return 0
            for i, c in enumerate(r["cs"]):
                for j, g in enumerate(r["gammas"]):
                    print(f"C={c:g} gamma={g:g}: Cross Validation "
                          f"Accuracy = "
                          f"{r['accuracies'][i, j] * 100:.4f}%")
            print(f"Best: C={r['best_c']:g} gamma={r['best_gamma']:g} "
                  f"({r['best_accuracy'] * 100:.4f}%)")
            return 0
        r = cross_validate(x, y, args.cv, config,
                           task="svr" if args.svr else "svc",
                           batched=args.batched,
                           class_weight=class_weight)
        if args.svr:
            print(f"Cross Validation ({args.cv}-fold) MSE: "
                  f"{r['mse']:.6f}  MAE: {r['mae']:.6f}  "
                  f"R^2: {r['r2']:.6f}")
        else:
            # LIBSVM's svm-train -v output shape
            print(f"Cross Validation Accuracy = "
                  f"{r['accuracy'] * 100:.4f}%")
        return 0

    if args.nu_svc:
        from dpsvm_tpu.models.nusvm import train_nusvc
        from dpsvm_tpu.models.svm import evaluate
        model, result = train_nusvc(x, np.asarray(y, np.int32), args.nu,
                                    config)
        n_sv = save_model(model, args.model)
        print(f"Number of SVs: {n_sv}")
        print(f"b: {result.b:.6f}")
        print(f"Training iterations: {result.n_iter}"
              + ("" if result.converged else " (NOT converged)"))
        print(f"Training accuracy: {evaluate(model, x, y):.6f} "
              f"(nu = {args.nu})")
        print(f"Training time: {result.train_seconds:.3f} s")
        return 0
    if args.nu_svr:
        from dpsvm_tpu.models.nusvm import train_nusvr
        from dpsvm_tpu.models.svr import evaluate_svr
        model, result = train_nusvr(x, y, args.nu, config)
        n_sv = save_model(model, args.model)
        m = evaluate_svr(model, x, y)
        print(f"Number of SVs: {n_sv}")
        print(f"b: {result.b:.6f}")
        print(f"epsilon: {result.learned_epsilon:.6f}")   # learned tube
        print(f"Training iterations: {result.n_iter}"
              + ("" if result.converged else " (NOT converged)"))
        print(f"Training MSE: {m['mse']:.6f}  R^2: {m['r2']:.6f} "
              f"(nu = {args.nu})")
        print(f"Training time: {result.train_seconds:.3f} s")
        return 0
    if args.one_class:
        from dpsvm_tpu.models.oneclass import predict_oneclass, train_oneclass
        model, result = train_oneclass(x, args.nu, config)
        n_sv = save_model(model, args.model)
        inlier = predict_oneclass(model, x)
        print(f"Number of SVs: {n_sv}")
        print(f"rho: {result.b:.6f}")
        print(f"Training iterations: {result.n_iter}"
              + ("" if result.converged else " (NOT converged)"))
        print(f"Training inlier fraction: {float(np.mean(inlier > 0)):.6f} "
              f"(nu = {args.nu})")
        print(f"Training time: {result.train_seconds:.3f} s")
        return 0

    if args.svr:
        from dpsvm_tpu.models.svr import evaluate_svr, train_svr
        model, result = train_svr(x, y, config)
        if model.n_sv == 0 and not getattr(model, "is_approx", False):
            print("error: the fitted tube contains every target "
                  f"(svr_epsilon={config.svr_epsilon}) — the model has no "
                  "support vectors and predicts the constant "
                  f"{-result.b:.6g}; decrease -p", file=sys.stderr)
            return 1
        n_sv = save_model(model, args.model)
        m = evaluate_svr(model, x, y)
        if getattr(model, "is_approx", False):
            print(f"Approx model: {model.model_kind} "
                  f"dim={model.fmap.dim} (no SV set)")
        else:
            print(f"Number of SVs: {n_sv}")
        print(f"b: {result.b:.6f}")
        print(f"Training iterations: {result.n_iter}"
              + ("" if result.converged else " (NOT converged)"))
        print(f"Training MSE: {m['mse']:.6f}  MAE: {m['mae']:.6f}  "
              f"R^2: {m['r2']:.6f}")
        print(f"Training time: {result.train_seconds:.3f} s")
        return 0

    model, result = fit(x, y, config)
    n_sv = save_model(model, args.model)
    acc = evaluate(model, x, y)
    # Same closing report the reference prints (svmTrainMain.cpp:313-336).
    if getattr(model, "is_approx", False):
        print(f"Approx model: {model.model_kind} dim={model.fmap.dim} "
              "(no SV set)")
    else:
        print(f"Number of SVs: {n_sv}")
    if hasattr(result, "n_kept"):
        print(f"Cascade: screened {result.n_total} -> {result.n_kept} "
              f"rows ({result.readmit_rounds} polish round(s), "
              f"{result.n_readmitted} re-admitted, "
              f"{result.kkt_violators} KKT violator(s))")
    print(f"b: {result.b:.6f}")
    print(f"Training iterations: {result.n_iter}"
          + ("" if result.converged else " (max-iter reached, NOT converged)"))
    print(f"Training accuracy: {acc:.6f}")
    print(f"Training time: {result.train_seconds:.3f} s")
    if args.probability or args.probability_cv:
        from dpsvm_tpu.models.calibration import (fit_platt,
                                                  fit_platt_cv,
                                                  save_platt)
        from dpsvm_tpu.models.svm import decision_function
        if args.probability_cv:
            pa, pb = fit_platt_cv(x, y, config)
        else:
            dec = np.asarray(decision_function(model, x))
            pa, pb = fit_platt(dec, y)
        save_platt(args.model, pa, pb)
        print(f"Platt calibration: A={pa:.6f} B={pb:.6f} "
              f"(saved {args.model}.platt.json)")
    if args.check_kkt:
        from dpsvm_tpu.ops.diagnostics import optimality_report
        # One streamed kernel pass yields every metric; box_bound gives
        # the same C_i the solver used when class weights are in play.
        rep = optimality_report(x, y, result.alpha,
                                config.kernel_spec(x.shape[1]),
                                config.box_bound(y), b=result.b)
        # The solver maintains f incrementally across every iteration;
        # kkt_residual recomputes the same b_lo - b_hi from scratch, so
        # the difference vs the solver's final gap bounds accumulated
        # drift.
        print(f"Dual objective: {rep.dual:.6f}")
        print(f"Primal objective: {rep.primal:.6f}")
        print(f"Duality gap: {rep.gap:.6f}")
        print(f"Equality residual sum(alpha*y): {rep.eq_residual:.6f} "
              "(nonzero = the reference's independent-clip drift)")
        print(f"KKT residual (recomputed): {rep.kkt_residual:.6f} "
              f"(solver's incremental gap: {result.gap:.6f}, "
              f"drift {abs(rep.kkt_residual - result.gap):.2e})")
    return 0


def cmd_test(args: argparse.Namespace) -> int:
    import os

    import numpy as np

    from dpsvm_tpu.data.loader import load_dataset, sniff_format
    from dpsvm_tpu.models.io import load_model

    def _width_hint(d_model):
        # libsvm files have no explicit width: a test split whose max
        # feature index is below the model's width (a9a.t is 122 vs
        # 123) must be loaded AT the model's width. CSV files carry
        # their width — and so do shard directories (the manifest) —
        # leave those alone so mismatches surface below.
        if (args.num_att is None and not os.path.isdir(args.input)
                and sniff_format(args.input) == "libsvm"):
            return d_model
        return args.num_att

    if args.batch < 0:
        print(f"error: --batch must be >= 0, got {args.batch}",
              file=sys.stderr)
        return 2

    def _engine(model, include_b=True):
        # --batch N: stream evaluation through the serving engine's
        # bucket ladder (full N-row passes + one padded remainder)
        # instead of one monolithic (m, d) device pass — same bits,
        # bounded memory (docs/SERVING.md "Chunked offline eval").
        from dpsvm_tpu.serving.engine import PredictionEngine
        return PredictionEngine(model, name="cmd-test",
                                max_batch=args.batch,
                                include_b=include_b)

    if os.path.isdir(args.model):
        from dpsvm_tpu.models.multiclass import load_multiclass
        mc = load_multiclass(args.model)
        if args.proba and mc.platt is None:
            print("error: this multiclass model was trained without "
                  "calibration — train with --multiclass --probability",
                  file=sys.stderr)
            return 2
        d_model = mc.models[0].num_attributes
        x, y = load_dataset(args.input, args.num_ex, _width_hint(d_model),
                            allow_nonfinite=args.allow_nonfinite,
                            mem_budget_mb=args.mem_budget_mb)
        if x.shape[1] != d_model:
            print(f"error: dataset has {x.shape[1]} attributes, model has "
                  f"{d_model}", file=sys.stderr)
            return 2
        from dpsvm_tpu.models.multiclass import (pairwise_decisions,
                                                 predict_multiclass,
                                                 predict_proba_multiclass)
        # One kernel-inference pass per pair, shared by everything
        # below (each pass is a full (m, d) @ (d, n_sv) evaluation).
        if args.batch:
            decisions = _engine(mc, include_b=not args.no_b).pairwise_list(x)
        else:
            decisions = pairwise_decisions(mc, x, include_b=not args.no_b)
        if args.proba:
            # The sigmoids were fit on intercept-included decisions;
            # with-b = intercept-free − b per pair, so no second
            # kernel-inference pass is ever paid.
            dec_b = ([d - np.float32(m.b)
                      for d, m in zip(decisions, mc.models)]
                     if args.no_b else decisions)
            proba = predict_proba_multiclass(mc, x, decisions=dec_b)
            if args.no_b:
                # --no-b asks for intercept-free decisions; the
                # sigmoids are only defined on intercept-included ones,
                # so honor the flag for predictions via the OvO vote
                # and let the proba file carry the (with-b) coupling.
                pred = predict_multiclass(mc, x, include_b=False,
                                          decisions=decisions)
            else:
                # LIBSVM -b 1 predicts by the COUPLED argmax (which
                # can differ from the OvO vote on ~1% of rows); keep
                # the written predictions consistent with the written
                # probabilities.
                pred = mc.classes[np.argmax(proba, axis=1)]
        else:
            proba = None
            pred = predict_multiclass(mc, x, include_b=not args.no_b,
                                      decisions=decisions)
        acc = float(np.mean(pred == y))
        if args.predictions:
            with open(args.predictions, "w") as f:
                f.writelines(f"{int(p)}\n" for p in pred)
        print(f"Classes: {[int(c) for c in mc.classes]}")
        print(f"Test accuracy: {acc:.6f}")
        if args.proba:
            with open(args.proba, "w") as f:
                f.writelines(",".join(f"{v:.6g}" for v in row) + "\n"
                             for row in proba)
            cls_index = {int(c): i for i, c in enumerate(mc.classes)}
            truth = np.asarray([cls_index.get(int(v), -1) for v in y])
            known = truth >= 0
            if known.any():
                pc = np.clip(proba[np.flatnonzero(known), truth[known]],
                             1e-12, None)
                print(f"Log-loss: {float(-np.mean(np.log(pc))):.6f} "
                      f"({int(known.sum())} examples)")
            else:
                print("Log-loss: n/a (no test label matches a training "
                      "class)")
        return 0

    model = load_model(args.model)
    # Load the data at its NATURAL width (no model-width hint: a hint
    # narrower than the data would silently truncate libsvm-format
    # rows), then reconcile. Both sparse formats mean "absent index ==
    # zero", so the narrower side widens with zero columns: libsvm test
    # splits can undershoot the model (a9a.t is 122 vs 123) and sparse
    # .model files underreport when trailing columns are zero in every
    # SV. Dense CSVs carry their true width — a mismatch there (or a
    # wider dataset against a reference-format model) is a real error.
    x, y = load_dataset(args.input, args.num_ex, args.num_att,
                        float_labels=model.task == "svr",
                        allow_nonfinite=args.allow_nonfinite,
                        mem_budget_mb=args.mem_budget_mb)
    if x.shape[1] != model.num_attributes:
        import dataclasses

        from dpsvm_tpu.models.io import is_libsvm_model
        data_is_libsvm = (args.num_att is None
                          and not os.path.isdir(args.input)
                          and sniff_format(args.input) == "libsvm")
        if x.shape[1] < model.num_attributes and data_is_libsvm:
            x = np.pad(x, ((0, 0),
                           (0, model.num_attributes - x.shape[1])))
        elif (x.shape[1] > model.num_attributes
                and not getattr(model, "is_approx", False)
                and is_libsvm_model(args.model)):
            if model.kernel == "precomputed":
                # LIBSVM stores no n_train; serials only bound it from
                # below. The data's K(test, train) width is the truth.
                model = dataclasses.replace(model, n_train=x.shape[1],
                                            n_train_exact=True)
            else:
                model = dataclasses.replace(model, x_sv=np.pad(
                    model.x_sv,
                    ((0, 0), (0, x.shape[1] - model.num_attributes))))
        else:
            print(f"error: dataset has {x.shape[1]} attributes, model "
                  f"has {model.num_attributes}", file=sys.stderr)
            return 2
    if model.task == "oneclass":
        if args.proba:
            print("error: --proba applies to classifiers only",
                  file=sys.stderr)
            return 2
        from dpsvm_tpu.models.oneclass import predict_oneclass
        if args.batch:
            # one-class decisions always include rho (predict_oneclass
            # hardcodes include_b=True; --no-b does not apply here)
            pred = _engine(model, include_b=True).predict(x)
        else:
            pred = predict_oneclass(model, x)
        if args.predictions:
            with open(args.predictions, "w") as f:
                f.writelines(f"{int(v)}\n" for v in pred)
        print(f"Number of SVs: {model.n_sv}")
        print(f"Inlier fraction: {float(np.mean(pred > 0)):.6f}")
        labs = np.asarray(y)
        if set(np.unique(labs.astype(np.int64))) <= {-1, 1}:
            acc = float(np.mean(pred == labs.astype(np.int32)))
            print(f"Test accuracy (+1 inlier / -1 outlier labels): "
                  f"{acc:.6f}")
        return 0
    if model.task == "svr":
        if args.proba:
            print("error: --proba applies to classifiers only",
                  file=sys.stderr)
            return 2
        from dpsvm_tpu.models.svr import regression_metrics, predict_svr
        if args.batch:
            pred = _engine(model, include_b=not args.no_b).predict(x)
        else:
            pred = predict_svr(model, x, include_b=not args.no_b)
        if args.predictions:
            with open(args.predictions, "w") as f:
                f.writelines(f"{float(v):.9g}\n" for v in pred)
        m = regression_metrics(pred, y)
        print(f"Number of SVs: {model.n_sv}")
        print(f"Test MSE: {m['mse']:.6f}  MAE: {m['mae']:.6f}  "
              f"R^2: {m['r2']:.6f}")
        return 0
    import time

    from dpsvm_tpu.models.svm import decision_function
    t_eval = time.perf_counter()
    if args.batch:
        dec = _engine(model, include_b=not args.no_b).decision_values(x)
    else:
        dec = decision_function(model, x, include_b=not args.no_b)
    t_eval = time.perf_counter() - t_eval
    pred = np.where(dec < 0, -1, 1)                    # svmTrain.cu:650-656
    acc = float(np.mean(pred == np.asarray(y, np.int32)))
    if args.predictions:
        with open(args.predictions, "w") as f:
            f.writelines(f"{int(p)},{v:.6g}\n" for p, v in zip(pred, dec))
    print(f"Number of SVs: {model.n_sv}")
    print(f"Test accuracy: {acc:.6f}")
    # One batched (m,d)@(d,n_sv) MXU pass — vs the reference's
    # per-example host loop (seq_test.cpp:187-210). Includes compile on
    # first use; benchmarks/inference_bench.py isolates steady state.
    print(f"Evaluation time: {t_eval:.3f} s "
          f"({len(pred)} examples, {len(pred) / t_eval:,.0f} ex/s)")
    if args.proba:
        from dpsvm_tpu.models.calibration import load_platt, sigmoid_proba
        try:
            pa, pb = load_platt(args.model)
        except FileNotFoundError:
            print(f"error: no Platt sidecar {args.model}.platt.json — "
                  "train with --probability first", file=sys.stderr)
            return 2
        # The sigmoid was fit on intercept-included decision values;
        # with-b = intercept-free − b, so --no-b costs no second
        # kernel-inference pass.
        dec_b = (np.asarray(dec) - np.float32(model.b)
                 if args.no_b else dec)
        proba = sigmoid_proba(dec_b, pa, pb)
        with open(args.proba, "w") as f:
            f.writelines(f"{p:.6g}\n" for p in proba)
        t = (np.asarray(y) > 0).astype(np.float64)
        brier = float(np.mean((proba - t) ** 2))
        pc = np.clip(proba, 1e-12, 1.0 - 1e-12)
        logloss = float(-np.mean(t * np.log(pc) + (1 - t) * np.log(1 - pc)))
        print(f"Brier score: {brier:.6f}")
        print(f"Log-loss: {logloss:.6f}")
    return 0


def cmd_serve(args: argparse.Namespace) -> int:
    """Online prediction server (docs/SERVING.md). Loads + warms every
    model, prints one ready line, then serves until SIGTERM/SIGINT —
    which triggers a graceful drain (everything accepted is answered)."""
    from dpsvm_tpu.serving import ModelRegistry
    from dpsvm_tpu.serving.server import ServingServer

    # Tuned-profile resolution for the serving knobs left at their
    # defaults (tuning/profile.py): explicit flags always win;
    # --no-tuned / DPSVM_NO_TUNED=1 opt out.
    tuned_entry = None
    if not args.no_tuned:
        from dpsvm_tpu.tuning import profile as tuned_profile
        tuned_entry = tuned_profile.active_entry()
    if args.max_batch is None:
        from dpsvm_tpu.tuning.profile import tuned_value
        mb = tuned_value(tuned_entry, "serve_max_batch")
        args.max_batch = int(mb) if mb else 256
        if mb and not args.quiet:
            print(f"tuned profile: max_batch={args.max_batch} "
                  "(--no-tuned for the built-in 256)",
                  file=sys.stderr)
    if args.hedge_ms == "off" and args.replicas >= 2:
        from dpsvm_tpu.tuning.profile import tuned_value
        hm = tuned_value(tuned_entry, "serve_hedge_ms")
        if hm:
            args.hedge_ms = str(float(hm))
            if not args.quiet:
                print(f"tuned profile: hedge_ms={args.hedge_ms} "
                      "(--no-tuned to disable)", file=sys.stderr)
    if args.max_batch < 1 or args.max_queue < 1:
        print("error: --max-batch and --max-queue must be >= 1",
              file=sys.stderr)
        return 2
    if args.replicas < 1:
        print("error: --replicas must be >= 1", file=sys.stderr)
        return 2
    if not (args.deadline_ms > 0):
        print("error: --deadline-ms must be > 0", file=sys.stderr)
        return 2
    if not (0.0 <= args.trace_sample_rate <= 1.0):
        print("error: --trace-sample-rate must be in [0, 1], got "
              f"{args.trace_sample_rate}", file=sys.stderr)
        return 2
    # --hedge-ms: "off", "auto" (p99-based), or a fixed delay in ms
    hedge = args.hedge_ms
    if hedge not in ("off", "auto"):
        try:
            hedge = float(hedge) / 1000.0
        except ValueError:
            print(f"error: --hedge-ms must be a number, 'auto' or "
                  f"'off', got {args.hedge_ms!r}", file=sys.stderr)
            return 2
    siblings = {}
    for spec in args.degrade_to:
        name, sep, sib = spec.partition("=")
        if not sep or not name or not sib:
            print(f"error: --degrade-to needs NAME=SIBLING, got "
                  f"{spec!r}", file=sys.stderr)
            return 2
        siblings[name] = sib
    cache_budget = args.model_cache_budget
    if cache_budget is not None and cache_budget < 1:
        print("error: --model-cache-budget must be >= 1",
              file=sys.stderr)
        return 2
    if args.hbm_budget_mb is not None and not (args.hbm_budget_mb > 0):
        print(f"error: --hbm-budget-mb must be > 0, got "
              f"{args.hbm_budget_mb}", file=sys.stderr)
        return 2
    if args.max_connections < 1:
        print("error: --max-connections must be >= 1", file=sys.stderr)
        return 2
    tenant_weights = {}
    if args.tenant_weight:
        if args.front_end != "async":
            print("error: --tenant-weight needs --front-end async "
                  "(the threaded transport has no fair queue)",
                  file=sys.stderr)
            return 2
        from dpsvm_tpu.serving.fairqueue import parse_tenant_weights
        try:
            tenant_weights = parse_tenant_weights(args.tenant_weight)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    if cache_budget is not None and args.no_b:
        # the cache's shared same-spec program serves include_b=True
        # decisions; mixing the two would silently change semantics
        print("error: --no-b is not supported with "
              "--model-cache-budget", file=sys.stderr)
        return 2
    registry = ModelRegistry()
    for i, spec in enumerate(args.model):
        name, sep, path = spec.partition("=")
        if not sep:
            name, path = ("default" if i == 0
                          else os.path.basename(spec.rstrip("/"))), spec
        if name in registry.names():
            print(f"error: duplicate model name {name!r} (use "
                  "NAME=PATH to disambiguate)", file=sys.stderr)
            return 2
        if not os.path.exists(path):
            print(f"error: no such model: {path}", file=sys.stderr)
            return 2
        if cache_budget is not None:
            # fleet mode: manifest-only registration — the model cache
            # hydrates on first request, within its HBM budget
            # (docs/SERVING.md "Model fleet"); boot cost is O(fleet
            # size) filename bookkeeping, not O(fleet size) compiles
            registry.register(name, path, lazy=True,
                              max_batch=args.max_batch,
                              include_b=True,
                              precision=args.precision,
                              **({"hbm_budget_mb": args.hbm_budget_mb}
                                 if args.hbm_budget_mb else {}))
            if not args.quiet:
                print(f"registered {name!r} (lazy): {path}",
                      file=sys.stderr)
            continue
        engine = registry.register(name, path,
                                   max_batch=args.max_batch,
                                   include_b=not args.no_b,
                                   precision=args.precision,
                                   **({"hbm_budget_mb":
                                       args.hbm_budget_mb}
                                      if args.hbm_budget_mb else {}))
        if not args.quiet:
            m = engine.manifest
            print(f"loaded {name!r}: task={m['task']} "
                  f"n_sv={m['n_sv']} (dropped {m['n_sv_dropped']} "
                  f"zero-coef) d={m['num_attributes']} "
                  f"precision={m['precision']} "
                  f"buckets={m['buckets']} "
                  f"warmup_compiles={m['warmup_compiles']} "
                  f"({m['warmup_compile_seconds']}s)"
                  + (" [mesh-sharded decisions]" if m.get("sharded")
                     else ""), file=sys.stderr)
    unknown = [s for pair in siblings.items() for s in pair
               if s not in registry.names()]
    if unknown:
        print(f"error: --degrade-to names unregistered model(s) "
              f"{sorted(set(unknown))} (loaded: {registry.names()})",
              file=sys.stderr)
        return 2
    # The CLI server exposes the PROCESS-wide registry — the same one
    # a training run in this process would feed — so /metricsz?format=
    # prometheus is the single scrape surface (docs/OBSERVABILITY.md
    # "Metrics").
    from dpsvm_tpu.observability.metrics import default_registry
    try:
        srv = ServingServer(registry, args.host, args.port,
                            max_batch=args.max_batch,
                            max_delay_ms=args.max_delay_ms,
                            max_queue=args.max_queue,
                            predict_timeout=args.deadline_ms / 1000.0,
                            replicas=args.replicas, hedge=hedge,
                            degrade=args.degrade, siblings=siblings,
                            trace_out=args.trace_out,
                            trace_sample_rate=args.trace_sample_rate,
                            metrics_registry=default_registry(),
                            watch_rules=args.watch_rules,
                            bundle_dir=args.bundle_dir,
                            watch=args.watch,
                            **({"tenant_budget": args.tenant_budget}
                               if args.tenant_budget is not None else {}),
                            model_cache_budget=cache_budget,
                            verbose=not args.quiet)
        if args.front_end == "async":
            from dpsvm_tpu.serving.frontdoor import AsyncFrontDoor
            front = AsyncFrontDoor(
                srv, max_connections=args.max_connections,
                tenant_weights=tenant_weights).start()
        else:
            front = srv.start()
    except ValueError as e:                 # width-mismatched sibling
        print(f"error: {e}", file=sys.stderr)
        return 2
    except OSError as e:                    # unreadable rules file
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.port_file:
        with open(args.port_file, "w") as f:
            f.write(str(front.port))
    print(f"serving on http://{args.host}:{front.port} "
          f"({args.front_end} front end; models: "
          f"{', '.join(registry.names())}) — SIGTERM/Ctrl-C "
          "drains", file=sys.stderr, flush=True)
    signum = front.serve_until_signal()
    if not args.quiet:
        m = srv.metrics()
        print(f"drained (signal {signum}): {m['requests']} requests, "
              f"{m['rejected']} rejected, {m['errors']} errors",
              file=sys.stderr)
    return 0


def cmd_loadgen(args: argparse.Namespace) -> int:
    """Load generator (docs/SERVING.md). Pure HTTP + numpy — no
    backend init; runs from any machine that can reach the server."""
    import json

    import numpy as np

    from dpsvm_tpu.serving.loadgen import (fetch_models, loadgen_row,
                                           run_saturate, synthetic_rows)

    want = tuple(w for w in args.want.split(",") if w)
    if args.models < 0:
        print("error: --models must be >= 0", file=sys.stderr)
        return 2
    if not (0.0 <= args.model_skew <= 1.0):
        print(f"error: --model-skew must be in [0, 1], got "
              f"{args.model_skew}", file=sys.stderr)
        return 2
    try:
        all_models = fetch_models(args.url, timeout=args.timeout)
    except (OSError, RuntimeError) as e:
        print(f"error: cannot reach {args.url}: {e}", file=sys.stderr)
        return 2
    if args.model not in all_models:
        print(f"error: server has no model {args.model!r} "
              f"(models: {sorted(all_models)[:20]})", file=sys.stderr)
        return 2
    manifest = all_models[args.model]
    # A lazy (fleet-cache) registration reports no feature width until
    # it hydrates; borrow the width from any resident sibling (the
    # fleet drill is a same-spec fleet), else require -f.
    width = manifest.get("num_attributes")
    if width is None:
        width = next((m["num_attributes"] for m in all_models.values()
                      if m.get("num_attributes") is not None), None)
    if args.input:
        from dpsvm_tpu.data.loader import load_dataset
        rows, _ = load_dataset(args.input, None, None)
        rows = np.asarray(rows, np.float32)
        if width is not None and rows.shape[1] != width:
            print(f"error: dataset has {rows.shape[1]} attributes, "
                  f"model {args.model!r} expects {width}",
                  file=sys.stderr)
            return 2
    elif width is not None:
        rows = synthetic_rows(width)
    else:
        print(f"error: model {args.model!r} is not resident and no "
              "sibling reports a feature width — pass -f DATASET so "
              "the loadgen knows the request shape", file=sys.stderr)
        return 2
    fleet_names: list = []
    if args.models > 0:
        # hot model first (the skew target), then the rest sorted —
        # a deterministic, replayable fleet selection
        rest = [n for n in sorted(all_models) if n != args.model]
        fleet_names = [args.model] + rest[:args.models - 1]
        if len(fleet_names) < args.models:
            print(f"error: --models {args.models} but the server has "
                  f"only {len(all_models)} models", file=sys.stderr)
            return 2
    trace = args.trace or os.environ.get("BENCH_TRACE_OUT") or None

    def _ledger_append(row):
        # serving rows join the same persistent perf ledger training
        # rows feed, so `dpsvm perf gate` sees both halves
        # (docs/OBSERVABILITY.md "Perf ledger"); best-effort.
        if not args.ledger:
            return
        from dpsvm_tpu.observability import ledger
        ledger.append(row.get("metric", "loadgen"), row,
                      kind="loadgen", trace=row.get("trace"))

    if args.connections < 0:
        print(f"error: --connections must be >= 0, got "
              f"{args.connections}", file=sys.stderr)
        return 2
    if args.saturate:
        row = run_saturate(args.url, rows, model=args.model,
                           p99_target_ms=args.p99_target_ms,
                           start_rps=args.start_rps,
                           rps_factor=args.rps_factor,
                           max_steps=args.max_steps,
                           step_requests=args.step_requests,
                           batch=args.batch,
                           concurrency=args.concurrency, want=want,
                           timeout=args.timeout, trace=trace,
                           connections=args.connections)
        print(json.dumps(row), flush=True)
        _ledger_append(row)
        return 0 if row["slo_met"] else 1
    row = loadgen_row(args.url, rows, model=args.model,
                      requests=args.requests, batch=args.batch,
                      concurrency=args.concurrency, mode=args.mode,
                      rps=args.rps, want=want, timeout=args.timeout,
                      chaos=args.chaos,
                      compare_sequential=args.compare_sequential,
                      trace=trace, tenants=args.tenants,
                      hot_tenant_skew=args.hot_tenant_skew,
                      models=fleet_names, model_skew=args.model_skew,
                      connections=args.connections)
    print(json.dumps(row), flush=True)
    _ledger_append(row)
    if row.get("cold_start_p99_ms") is not None:
        # The fleet shape additionally feeds the model_fleet ledger
        # case: the headline is cold-start p99 — how fast a paged-out
        # model comes back when its first request lands
        # (docs/SERVING.md "Model fleet").
        _ledger_append({
            "metric": "model_fleet",
            "value": row["cold_start_p99_ms"], "unit": "ms",
            "trace": row.get("trace"),
            "models": row.get("models"),
            "model_skew": row.get("model_skew"),
            "hot_model": row.get("hot_model"),
            "p99_ms": row.get("p99_ms"),
            "requests": row.get("requests"),
            "errors": row.get("errors")})
    if row.get("hot_tenant") and row.get("others_p99_ms") is not None:
        # The noisy-neighbour shape additionally feeds the
        # tenant_isolation ledger case: the headline is the COLD
        # tenants' p99 — how clean everyone else's latency stays while
        # one tenant hogs the queue (docs/OBSERVABILITY.md
        # "Per-tenant attribution").
        _ledger_append({
            "metric": "tenant_isolation",
            "value": row["others_p99_ms"], "unit": "ms",
            "trace": row.get("trace"),
            "tenants": row.get("tenants"),
            "hot_tenant_skew": row.get("hot_tenant_skew"),
            "hot_tenant": row.get("hot_tenant"),
            "hot_p99_ms": row.get("hot_p99_ms"),
            "others_p99_ms": row.get("others_p99_ms"),
            "requests": row.get("requests"),
            "errors": row.get("errors")})
    if args.chaos:
        # a chaos drill EXPECTS some failures; the verdict is the
        # availability of accepted requests (the acceptance bar)
        avail = row.get("availability_pct")
        return 0 if (avail is not None and avail >= 99.0) else 1
    return 0 if row["errors"] == 0 else 1


def cmd_grid(args: argparse.Namespace) -> int:
    """`dpsvm grid` (docs/SERVING.md "Model fleet"): train the whole
    C×gamma grid as mesh-parallel batched programs, score every cell
    on a seeded holdout, optionally cascade-polish the winner and
    promote it atomically. One compile per device partition instead of
    one per cell — that is where the grid_vs_sequential speedup lives."""
    import json

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.data.loader import load_dataset

    try:
        cs = tuple(float(v) for v in args.cs.split(",") if v.strip())
        gammas = (tuple(float(v) for v in args.gammas.split(",")
                        if v.strip())
                  if args.gammas else None)
    except ValueError:
        print(f"error: --cs/--gammas must be comma lists of numbers "
              f"(got --cs {args.cs!r} --gammas {args.gammas!r})",
              file=sys.stderr)
        return 2
    if not cs or any(c <= 0 for c in cs):
        print(f"error: --cs needs at least one positive C, got "
              f"{args.cs!r}", file=sys.stderr)
        return 2
    if gammas is not None and any(g <= 0 for g in gammas):
        print(f"error: --gammas must be positive, got {args.gammas!r}",
              file=sys.stderr)
        return 2
    if not (0.0 < args.holdout_frac < 1.0):
        print(f"error: --holdout-frac must be in (0, 1), got "
              f"{args.holdout_frac}", file=sys.stderr)
        return 2
    x, y = load_dataset(args.input, args.num_ex, args.num_att,
                        allow_nonfinite=args.allow_nonfinite,
                        mem_budget_mb=args.mem_budget_mb)
    config = SVMConfig(kernel=args.kernel, degree=args.degree,
                       coef0=args.coef0, verbose=not args.quiet,
                       **({"max_iter": args.max_iter}
                          if args.max_iter is not None else {}))

    from dpsvm_tpu.fleet import sequential_grid_seconds, train_grid

    tr = None
    if args.trace_out:
        from dpsvm_tpu.observability.record import RunTrace
        tr = RunTrace(args.trace_out, config=config, n=x.shape[0],
                      d=x.shape[1], gamma=(gammas[0] if gammas
                                           else 1.0 / x.shape[1]),
                      solver="grid")
    try:
        grid = train_grid(x, y, cs=cs, gammas=gammas, config=config,
                          holdout_frac=args.holdout_frac,
                          seed=args.seed, polish=args.polish,
                          trace=tr)
    finally:
        if tr is not None:
            tr.close()
    best = grid.best
    row = {
        "metric": "grid_train_seconds",
        "value": round(grid.train_seconds, 4),
        "unit": "s",
        "cs": list(cs),
        "gammas": [c.gamma for c in grid.cells[:len(grid.cells)
                                               // len(cs)]],
        "cells": [{"c": c.c, "gamma": round(c.gamma, 8),
                   "holdout_acc": round(c.holdout_acc, 6),
                   "n_sv": int(c.result.n_sv),
                   "converged": bool(c.result.converged)}
                  for c in grid.cells],
        "winner": {"c": best.c, "gamma": round(best.gamma, 8),
                   "holdout_acc": round(best.holdout_acc, 6),
                   "n_sv": int(best.result.n_sv)},
        "n_train": grid.n_train, "n_holdout": grid.n_holdout,
        "devices": grid.devices, "polished": grid.polished,
        "trace": args.trace_out,
    }

    def _ledger_append(case, value, unit, extra):
        if not args.ledger:
            return
        from dpsvm_tpu.observability import ledger
        ledger.append(case, extra, kind="fleet", value=value,
                      unit=unit, trace=args.trace_out)

    if args.compare_sequential:
        seq_s, seq_models = sequential_grid_seconds(
            x, y, cs=cs, gammas=gammas, config=config,
            holdout_frac=args.holdout_frac, seed=args.seed)
        speedup = (round(seq_s / grid.train_seconds, 3)
                   if grid.train_seconds > 0 else None)
        row["sequential_seconds"] = round(seq_s, 4)
        row["grid_vs_sequential_x"] = speedup
        # matched-accuracy guard: the speedup row only counts if the
        # batched cells converged to the same per-cell quality
        import numpy as np

        from dpsvm_tpu.fleet import holdout_split
        from dpsvm_tpu.models.svm import evaluate
        _, ho_idx = holdout_split(x.shape[0], args.holdout_frac,
                                  args.seed)
        x_ho = np.asarray(x)[ho_idx]
        y_ho = np.asarray(y)[ho_idx]
        seq_accs = [float(evaluate(m, x_ho, y_ho))
                    for _, _, m in seq_models]
        acc_gap = max(abs(sa - c.holdout_acc)
                      for sa, c in zip(seq_accs, grid.cells))
        row["seq_acc_gap_max"] = round(acc_gap, 6)
        _ledger_append("grid_vs_sequential", speedup, "x", {
            "grid_seconds": row["value"],
            "sequential_seconds": row["sequential_seconds"],
            "cells": len(grid.cells), "devices": grid.devices,
            "seq_acc_gap_max": row["seq_acc_gap_max"],
            "n": int(x.shape[0]), "d": int(x.shape[1])})
    _ledger_append("grid_train", row["value"], "s", {
        "cells": len(grid.cells), "devices": grid.devices,
        "winner": row["winner"], "polished": grid.polished,
        "n": int(x.shape[0]), "d": int(x.shape[1])})

    if args.out:
        import tempfile

        from dpsvm_tpu.models.io import save_model
        out = os.path.abspath(args.out)
        fd, tmp = tempfile.mkstemp(
            prefix=f".{os.path.basename(out)}.", suffix=".grid-cand",
            dir=os.path.dirname(out) or ".")
        os.close(fd)
        try:
            save_model(best.model, tmp)
            os.replace(tmp, out)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        row["out"] = out
    if args.promote:
        from dpsvm_tpu.fleet import promote_winner
        from dpsvm_tpu.serving import ModelRegistry
        target = os.path.abspath(args.promote)
        reg = ModelRegistry()
        reg.register("winner", target, lazy=True, warmup=False,
                     max_batch=32)
        try:
            gen = promote_winner(grid, reg, "winner")
        except (OSError, ValueError) as e:
            print(f"error: promote failed: {e}", file=sys.stderr)
            return 1
        row["promoted"] = target
        row["generation"] = gen
    if args.json or args.quiet:
        print(json.dumps(row), flush=True)
    else:
        print(f"grid {len(cs)}x{len(grid.cells) // len(cs)} on "
              f"{grid.devices} device(s): {grid.train_seconds:.2f}s "
              f"({grid.n_train} train / {grid.n_holdout} holdout rows)")
        for c in grid.cells:
            mark = " <-- winner" if c is best else ""
            print(f"  C={c.c:<8g} gamma={c.gamma:<12.6g} "
                  f"holdout_acc={c.holdout_acc:.4f} "
                  f"n_sv={c.result.n_sv}{mark}")
        if "grid_vs_sequential_x" in row:
            print(f"  sequential baseline: {row['sequential_seconds']}s "
                  f"-> {row['grid_vs_sequential_x']}x speedup "
                  f"(max per-cell acc gap {row['seq_acc_gap_max']})")
        if row.get("out"):
            print(f"  saved winner -> {row['out']}")
        if row.get("promoted"):
            print(f"  promoted -> {row['promoted']} "
                  f"(generation {row['generation']})")
    return 0


def cmd_tenants(args: argparse.Namespace) -> int:
    """`dpsvm tenants`: the by-tenant cost table
    (docs/OBSERVABILITY.md "Per-tenant attribution"). Two sources, one
    row shape: a serving trace's sampled span trees (full percentiles)
    or a live /metricsz cost ledger (running totals; no percentiles).
    Pure HTTP/file I/O — no backend init. Exit 0 = rendered, 1 = the
    source has no tenant attribution, 2 = unreachable/invalid."""
    import json
    import urllib.error
    import urllib.request

    from dpsvm_tpu.observability.report import render_tenant_table

    if args.url:
        url = args.url.rstrip("/")
        if not url.endswith("/metricsz"):
            url += "/metricsz"
        try:
            with urllib.request.urlopen(url, timeout=args.timeout) as r:
                obj = json.loads(r.read())
        except (urllib.error.URLError, OSError,
                json.JSONDecodeError) as e:
            print(f"error: cannot read {url}: {e}", file=sys.stderr)
            return 2
        tn = obj.get("tenants") if isinstance(obj, dict) else None
        if not isinstance(tn, dict):
            print("error: no 'tenants' block in /metricsz — is this a "
                  "`dpsvm serve` endpoint?", file=sys.stderr)
            return 1
        per = tn.get("per_tenant") or {}
        total_wall = sum(float(d.get("wall_ms", 0.0))
                         for d in per.values())
        rows = []
        for ten, d in per.items():
            wall = float(d.get("wall_ms", 0.0))
            rows.append({
                "tenant": ten,
                "requests": int(d.get("requests", 0)),
                "rows": int(d.get("rows", 0)),
                "wall_ms": round(wall, 3),
                "share": (wall / total_wall) if total_wall else 0.0,
                "queue_wait_ms": float(d.get("queue_wait_ms", 0.0)),
                "compute_ms": float(d.get("compute_ms", 0.0)),
                "p50_ms": None, "p99_ms": None,
                "errors": int(d.get("errors", 0)),
                "deadline_504": int(d.get("deadline_504", 0)),
                "models": []})
        rows.sort(key=lambda r: (-r["wall_ms"], r["tenant"]))
        if args.top is not None:
            rows = rows[:max(int(args.top), 1)]
        digest = {"source": url,
                  "budget": tn.get("budget"), "live": tn.get("live"),
                  "evictions": tn.get("evictions"),
                  "overflow": tn.get("overflow"), "rows": rows}
        if args.json:
            _pipe_safe_print(json.dumps(digest))
            return 0
        head = (f"tenants (live): budget {tn.get('budget')}, "
                f"{tn.get('live')} live series, "
                f"{tn.get('evictions')} evictions, "
                f"{tn.get('overflow')} folded into 'other'")
        _pipe_safe_print("\n".join(
            [head, ""] + render_tenant_table(rows)))
        return 0

    from dpsvm_tpu.observability.report import (load_trace,
                                                resolve_trace_path,
                                                tenant_attribution)
    try:
        records = load_trace(resolve_trace_path(args.trace))
    except FileNotFoundError as e:
        print(f"error: no such trace: {e}", file=sys.stderr)
        return 2
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    att = tenant_attribution(records, top=args.top)
    if att is None:
        print("error: no tenant-attributed span roots in this trace "
              "(pre-v4 schema, or --trace-sample-rate 0)",
              file=sys.stderr)
        return 1
    if args.json:
        _pipe_safe_print(json.dumps(att))
        return 0
    head = (f"tenants (trace): {att['tenants']} attributed, "
            f"{att['total_wall_ms']:,.1f} ms total wall")
    _pipe_safe_print("\n".join(
        [head, ""] + render_tenant_table(att["rows"])))
    return 0


def cmd_fleet(args: argparse.Namespace) -> int:
    """`dpsvm fleet`: N hosts' metrics sources -> one fleet snapshot
    (docs/OBSERVABILITY.md "Fleet"). Pure HTTP/file I/O — no backend
    init, so it runs from any box that can reach the hosts. Exit
    codes: 0 = rendered clean, 2 = unusable source list, 3 = a host
    was unreachable/unreadable, and with --watch the `dpsvm watch`
    codes on top (4 = warn fired, 5 = page fired)."""
    import json

    from dpsvm_tpu.observability import fleet, slo

    try:
        state = fleet.collect(args.sources, timeout=args.timeout)
        heartbeats = (fleet.read_heartbeats(args.hosts_dir)
                      if args.hosts_dir else None)
        snap = fleet.federate(state, heartbeats=heartbeats)
    except fleet.FleetError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.out:
        expo = fleet.render_exposition(snap)
        if args.out == "-":
            _pipe_safe_print(expo)
        else:
            with open(args.out, "w") as fh:
                fh.write(expo)
    tower = None
    if args.watch:
        try:
            tower = slo.Watchtower(slo.load_rules(args.rules,
                                                  default="fleet"))
        except (OSError, ValueError, slo.RuleError) as e:
            print(f"error: bad rules: {e}", file=sys.stderr)
            return 2
        tower.observe(fleet.fleet_watch_sample(snap))
    down = sorted(h for h, d in snap["hosts"].items()
                  if not d.get("up"))
    if args.json:
        digest = dict(snap, down=down)
        if tower is not None:
            digest["alerts"] = tower.states()
        _pipe_safe_print(json.dumps(digest))
    else:
        text = fleet.render_fleet_table(snap)
        if down:
            text += ("\n  UNREACHABLE host(s): "
                     + ", ".join(str(h) for h in down))
        if tower is not None:
            firing = tower.firing()
            text += ("\n  alerts: " + ("; ".join(
                f"{s['rule']} {s['severity'].upper()} ({s['reason']})"
                for s in firing) if firing else "none firing"))
        _pipe_safe_print(text)
    if tower is not None and tower.exit_code():
        return tower.exit_code()
    return 3 if down else 0


def cmd_tune(args: argparse.Namespace) -> int:
    """Measure + persist this backend's tuned profile (docs/PERF.md
    "Autotuning"; tuning/tuner.py)."""
    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.tuning import tuner

    knobs = [k for k in args.knobs.split(",") if k]
    unknown = [k for k in knobs if k not in tuner.DEFAULT_GRIDS]
    if unknown:
        print(f"error: unknown knob(s) {unknown}; pick from "
              f"{sorted(tuner.DEFAULT_GRIDS)}", file=sys.stderr)
        return 2
    grids = {}
    for spec in args.grid:
        name, sep, vals = spec.partition("=")
        try:
            if not sep or name not in tuner.DEFAULT_GRIDS:
                raise ValueError(name)
            grids[name] = tuple(int(v) for v in vals.split(",") if v)
        except ValueError:
            print(f"error: --grid needs KNOB=V1,V2,... with a known "
                  f"knob, got {spec!r}", file=sys.stderr)
            return 2
    if args.input:
        from dpsvm_tpu.data.loader import load_dataset
        x, y = load_dataset(args.input, None, None)
    else:
        from dpsvm_tpu.data.synthetic import make_planted
        gamma = (args.gamma if args.gamma is not None
                 else 1.0 / args.d)
        x, y = make_planted(n=args.n, d=args.d, gamma=gamma, seed=0)
    base = SVMConfig(c=args.cost, gamma=args.gamma, epsilon=1e-5,
                     max_iter=10_000_000)
    log = (lambda s: None) if args.quiet else (
        lambda s: print(s, file=sys.stderr, flush=True))
    _entry, rc = tuner.run_tune(
        x, y, base_config=base, knobs=knobs, grids=grids,
        probe_iters=args.probe_iters, rungs=args.rungs,
        deadline_s=args.deadline_s, min_win_pct=args.min_win_pct,
        profile_out=args.out, trace_dir=args.trace_dir,
        ledger_on=args.ledger, log=log)
    return rc


def cmd_scale(args: argparse.Namespace) -> int:
    from dpsvm_tpu.data.scale import scale_file

    n, d = scale_file(args.src, args.dst, lower=args.lower,
                      upper=args.upper, save_params=args.save_range,
                      restore_params=args.restore_range)
    print(f"Scaled {n} rows x {d} features to {args.dst}")
    if args.save_range:
        print(f"Range file: {args.save_range}")
    return 0


def cmd_convert(args: argparse.Namespace) -> int:
    from dpsvm_tpu.data.convert import (libsvm_to_dense_csv,
                                        mnist_to_odd_even_csv)

    if args.format == "shards":
        from dpsvm_tpu.data.stream import convert_to_shards
        manifest = convert_to_shards(
            args.src, args.dst,
            rows_per_shard=args.rows_per_shard,
            num_attributes=args.num_att,
            float_labels=args.float_labels,
            allow_nonfinite=args.allow_nonfinite,
            resume=args.resume)
        print(f"Wrote {manifest['n']} rows x {manifest['d']} features "
              f"as {len(manifest['shards'])} shard(s) of "
              f"{manifest['rows_per_shard']} rows to {args.dst} "
              "(manifest.json carries per-shard CRC32s + scaling "
              "stats)")
        return 0
    if args.format == "libsvm":
        rows = libsvm_to_dense_csv(args.src, args.dst, args.num_att)
    else:
        rows = mnist_to_odd_even_csv(args.src, args.dst)
    print(f"Wrote {rows} rows to {args.dst}")
    return 0


def cmd_info(args: argparse.Namespace) -> int:
    """Environment diagnostics — the ops question 'what will a training
    run actually see' answered without starting one. Probes the backend
    with a bounded wait so a dead tunnel reports instead of hanging."""
    import os

    import dpsvm_tpu

    print(f"dpsvm_tpu {dpsvm_tpu.__version__}")
    import jax

    print(f"jax {jax.__version__}")
    from dpsvm_tpu.utils.backend_guard import exit_if_hung, probe_devices

    devices, reason = probe_devices(args.timeout)
    if devices is None:
        print(f"backend: UNREACHABLE ({reason})")
    else:
        plat = devices[0].platform
        print(f"backend: {plat} ({len(devices)} device"
              f"{'s' if len(devices) != 1 else ''})")
        for d in devices:
            print(f"  {d}")
        print(f"distributed: shards up to {len(devices)} on this host "
              "(--shards); multi-host via jax.distributed "
              "(docs/DISTRIBUTED.md)")
    from dpsvm_tpu.native import load_native_lib

    lib = load_native_lib()
    print("native helper: "
          + ("loaded (C++ CSV/libsvm parser + model writer)"
             if lib is not None else
             "unavailable (pure-Python fallbacks active)"))
    from dpsvm_tpu.utils.backend_guard import compile_cache_dir

    cache = compile_cache_dir()
    state = "populated" if os.path.isdir(cache) and os.listdir(cache) \
        else "empty"
    print(f"compile cache: {cache} ({state})")
    if devices is None:
        # Diagnostics are fully printed; a hung probe must hard-exit
        # (wedged thread holds jax's init lock — see exit_if_hung).
        exit_if_hung(reason, 1)
        return 1
    return 0


def _pipe_safe_print(text: str) -> None:
    """print() for the read-only report surfaces, tolerant of a closed
    downstream pipe (`dpsvm report run.jsonl | head` is the normal
    consumption pattern; a BrokenPipeError traceback there reads as a
    crash). Python re-raises on the shutdown flush too, so stdout is
    redirected to devnull after the pipe breaks."""
    import os

    try:
        print(text)
        sys.stdout.flush()
    except BrokenPipeError:
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())


def cmd_report(args: argparse.Namespace) -> int:
    """Render a run-telemetry trace. Pure file I/O — no backend init,
    so it works on a machine with no accelerator (or a dead tunnel).
    ``--follow`` tails an in-flight trace instead (exit 0 = run
    finished, 1 = terminal stall/preempt event, 3 = file stopped
    growing)."""
    import json

    from dpsvm_tpu.telemetry import (follow_trace, load_trace_auto,
                                     render_report, resolve_trace_path,
                                     summarize_trace)

    width = max(int(args.width), 20)
    if args.follow:
        # The trace may not exist yet (watching a run about to start):
        # resolve directories when possible, else follow the raw path.
        # A multi-host trace family cannot be followed live — name one
        # host's file (or report the directory after the run).
        try:
            path = resolve_trace_path(args.trace)
        except FileNotFoundError:
            path = args.trace
        except ValueError as e:
            print(f"error: --follow needs one trace: {e}",
                  file=sys.stderr)
            return 2
        return follow_trace(path, interval=max(args.interval, 0.01),
                            stall_timeout=args.stall_timeout,
                            width=width)
    try:
        # a directory holding a multi-host trace_h* family is MERGED
        # onto one fleet timeline (per-host lanes in the rendering)
        records = load_trace_auto(args.trace)
    except FileNotFoundError as e:
        print(f"error: no such trace: {e}", file=sys.stderr)
        return 2
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        _pipe_safe_print(json.dumps(summarize_trace(records)))
    else:
        _pipe_safe_print(render_report(records, width=width))
    return 0


def cmd_compare(args: argparse.Namespace) -> int:
    """Two traces in, one verdict out (docs/OBSERVABILITY.md "Comparing
    runs"). Pure file I/O like report. Exit codes: 0 = no gated
    regression (or no gate requested), 1 = regression past
    --fail-on-regress, 2 = unreadable/invalid input."""
    import json

    from dpsvm_tpu.telemetry import (compare_paths, regressions,
                                     render_compare)

    try:
        cmp, ra, rb = compare_paths(args.a, args.b,
                                    marks=max(int(args.marks), 1))
    except FileNotFoundError as e:
        print(f"error: no such trace: {e}", file=sys.stderr)
        return 2
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    regress = (regressions(cmp, args.fail_on_regress)
               if args.fail_on_regress is not None else [])
    if args.fail_on_regress is not None:
        # Gated verdicts join the perf ledger: pairwise outcomes
        # become history `dpsvm perf gate` can check for accumulated
        # drift the pairwise gate cannot see. Best-effort (a ledger
        # hiccup must not change the compare verdict).
        import os as _os

        from dpsvm_tpu.observability import ledger
        by = {r["metric"]: r for r in cmp["metrics"]}
        ips_b = (by.get("iters_per_sec") or {}).get("b")
        ledger.append(
            _os.path.splitext(_os.path.basename(rb))[0],
            {"passed": not regress, "regressions": regress,
             "threshold_pct": args.fail_on_regress,
             "a": ra, "b": rb, "value": ips_b, "unit": "iter/s"},
            kind="compare", trace=rb)
    if args.json:
        _pipe_safe_print(json.dumps(dict(cmp, a_path=ra, b_path=rb,
                                         regressions=regress)))
    else:
        text = render_compare(cmp, label_a=ra, label_b=rb)
        if args.fail_on_regress is not None:
            if regress:
                text += ("\n\nREGRESSION past "
                         f"{args.fail_on_regress:g}% threshold:")
                text += "".join(f"\n  {r}" for r in regress)
            else:
                text += (f"\n\nno regression past "
                         f"{args.fail_on_regress:g}% threshold")
        _pipe_safe_print(text)
    return 1 if regress else 0


def cmd_perf(args: argparse.Namespace) -> int:
    """Perf-ledger history + historical regression gate
    (docs/OBSERVABILITY.md "Perf ledger"). Pure file I/O like
    report/compare — no backend init. Exit codes: 0 = OK (or gate
    passed), 1 = gate regression, 2 = no/unreadable ledger."""
    import json

    from dpsvm_tpu.observability import ledger

    path = ledger.ledger_path(args.ledger)
    if path is None or not os.path.isfile(path):
        where = path or "(ledger disabled: DPSVM_PERF_LEDGER is empty)"
        print(f"error: no perf ledger at {where} — bench/burst/"
              "loadgen/compare runs append to it automatically",
              file=sys.stderr)
        return 2
    try:
        records = ledger.read(path)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.action == "gate":
        try:
            verdicts = ledger.gate(records, window=args.window,
                                   threshold_pct=args.fail_on_regress,
                                   case=args.case, metric=args.metric)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if args.json:
            _pipe_safe_print(json.dumps({
                "ledger": path, "window": args.window,
                "threshold_pct": args.fail_on_regress,
                "cases": ledger.cases(records),
                "regressions": verdicts}))
        elif verdicts:
            print(f"HISTORICAL REGRESSION past "
                  f"{args.fail_on_regress:g}% (window {args.window}):")
            for v in verdicts:
                print(f"  {v}")
        else:
            n = len([args.case] if args.case
                    else ledger.cases(records))
            print(f"no historical regression past "
                  f"{args.fail_on_regress:g}% across {n} case(s) "
                  f"(median-of-last-{args.window} baseline, {path})")
        return 1 if verdicts else 0
    if args.json:
        out = {"ledger": path, "cases": {}}
        for c in ([args.case] if args.case
                  else ledger.cases(records)):
            out["cases"][c] = ledger.series(records, c,
                                            metric=args.metric)
            for h in out["cases"][c]:
                h.pop("record", None)
        _pipe_safe_print(json.dumps(out))
        return 0
    if args.case and args.case not in ledger.cases(records):
        print(f"error: no case {args.case!r} in {path} "
              f"(cases: {ledger.cases(records)})", file=sys.stderr)
        return 2
    _pipe_safe_print(f"perf ledger: {path} "
                     f"({len(records)} record(s))\n"
                     + ledger.render_history(
                         records, case=args.case, metric=args.metric,
                         last=args.last))
    return 0


def cmd_profile(args: argparse.Namespace) -> int:
    """`dpsvm profile summarize DIR`: the reconciliation table of an
    auto-windowed --profile-dir capture (observability/profiler.py).
    Pure file I/O — no backend init."""
    import json

    from dpsvm_tpu.observability import profiler

    try:
        result = profiler.summarize_profile(args.dir,
                                            trace_path=args.trace)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except (ValueError, json.JSONDecodeError) as e:
        print(f"error: unreadable profile summary: {e}",
              file=sys.stderr)
        return 2
    if args.json:
        _pipe_safe_print(json.dumps(result))
        return 0
    text = profiler.render_summary(
        result, trace_phase_counts=result.get("trace_phase_counts"))
    if args.trace is not None and not result.get("phases_match", True):
        text += ("\nWARNING: trace phases missing from the profile's "
                 "annotation vocabulary")
    _pipe_safe_print(text)
    return 0


def cmd_watch(args: argparse.Namespace) -> int:
    """`dpsvm watch`: continuous SLO evaluation against a live source
    (docs/OBSERVABILITY.md "Watch & alerts"). Pure HTTP/file I/O — no
    backend init — so it runs from any machine that can reach the
    source. Exit codes: 0 clean, 4 a warn rule fired, 5 a page rule
    fired (worst severity DURING the watch — a fired-and-cleared burn
    still fails the gate), 3 source stale/unreachable, 2 usage."""
    import json
    import time
    import urllib.error
    import urllib.request

    from dpsvm_tpu.observability import blackbox, slo

    default_kind = "training" if args.trace else "serving"
    try:
        rules = slo.load_rules(args.rules, default=default_kind)
    except (OSError, ValueError) as e:
        print(f"error: bad rules: {e}", file=sys.stderr)
        return 2
    tower = slo.Watchtower(rules)
    follower = slo.SnapshotFollower()
    if args.trace and os.path.isdir(args.trace):
        from dpsvm_tpu.observability.report import resolve_trace_path
        try:
            args.trace = resolve_trace_path(args.trace)
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    flight = None
    if args.bundle_dir:
        flight = blackbox.FlightRecorder(blackbox.make_manifest(
            solver=f"watch-{default_kind}",
            config={"source": args.url or args.metrics_file
                    or args.trace}))

    def say(msg: str) -> None:
        if not args.quiet and not args.json:
            print(msg, flush=True)

    def handle(transitions, t_label) -> None:
        for tr in transitions:
            mark = ("FIRING" if tr["state"] == "firing" else "ok")
            say(f"[{t_label}] {mark:>6} {tr['severity']:<4} "
                f"{tr['rule']} ({tr['window']}) {tr['reason']}")
            if flight is not None:
                flight.event("alert", rule=tr["rule"],
                             window=tr["window"],
                             severity=tr["severity"],
                             state=tr["state"], reason=tr["reason"])
                if tr["state"] == "firing":
                    blackbox.dump_bundle(
                        args.bundle_dir, recorder=flight,
                        rule=tr["rule"], severity=tr["severity"],
                        window=tr["window"], reason=tr["reason"],
                        extra={"source": f"watch-{default_kind}"})

    url = None
    if args.url:
        url = args.url.rstrip("/")
        if not url.endswith("/metricsz"):
            url += "/metricsz"

    start = time.monotonic()
    last_progress = start
    trace_pos = 0
    trace_done = None
    stale = False
    # The SOURCE's own watchtower outranks ours: a serving process
    # reports its alert states in /metricsz, and a fresh `watch --url
    # --once` has no sample history of its own — without this merge it
    # would read a mid-incident server as clean.
    server_worst: Optional[str] = None
    server_firing: set = set()
    while True:
        now = time.monotonic()
        got_sample = False
        if url is not None:
            try:
                with urllib.request.urlopen(url, timeout=10) as r:
                    raw = r.read()
                try:
                    obj = json.loads(raw)
                except json.JSONDecodeError:
                    obj = None
                if isinstance(obj, dict) and ("alerts" in obj
                                              or "requests" in obj):
                    # the serving server's JSON blob: counters + its
                    # own alert states
                    sample = slo.sample_from_metricsz_json(obj)
                    firing_now = set()
                    for a in obj.get("alerts") or []:
                        if a.get("state") != "firing":
                            continue
                        sev = a.get("severity", "warn")
                        firing_now.add(a.get("rule"))
                        server_worst = slo.worst_severity(
                            server_worst, sev)
                        if a.get("rule") not in server_firing:
                            ten = (f" [tenant {a['tenant']}]"
                                   if a.get("tenant") else "")
                            say(f"[live] FIRING {sev:<4} "
                                f"{a.get('rule')} "
                                f"({a.get('window')}){ten} — reported "
                                "by the source's own watchtower")
                    for rule in server_firing - firing_now:
                        say(f"[live]     ok      {rule} — cleared at "
                            "the source")
                    server_firing = firing_now
                else:
                    # registry-snapshot shape (the train sidecar):
                    # re-fetch as the text exposition and flatten
                    with urllib.request.urlopen(
                            url + "?format=prometheus",
                            timeout=10) as r:
                        sample = slo.sample_from_prometheus(
                            r.read().decode())
                handle(tower.observe(sample, t=now), "live")
                got_sample = True
            except (urllib.error.URLError, OSError) as e:
                say(f"source unreachable: {e}")
        elif args.metrics_file is not None:
            try:
                with open(args.metrics_file) as fh:
                    text = fh.read()
            except OSError:
                text = None             # not written yet: wait
            if text:
                fresh, problems = follower.note(
                    slo.parse_snapshot_header(text))
                for p in problems:
                    say(f"WARNING: {p}")
                if fresh:
                    handle(tower.observe(
                        slo.sample_from_prometheus(text), t=now),
                        f"seq={follower.last_seq}")
                    got_sample = True
        else:
            try:
                with open(args.trace) as fh:
                    fh.seek(trace_pos)
                    new = fh.read()
                    trace_pos = fh.tell()
            except OSError:
                new = ""
            for line in new.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue            # torn final line of a live run
                got_sample = True
                kind = rec.get("kind")
                if kind == "chunk":
                    t_rec, sample = slo.sample_from_chunk(rec)
                    handle(tower.observe(sample, t=t_rec),
                           f"iter={rec.get('n_iter')}")
                elif (kind == "summary"
                      or (kind == "event"
                          and rec.get("event") in ("stall",
                                                   "preempt"))):
                    trace_done = (rec.get("event")
                                  if kind == "event" else "summary")
        if got_sample:
            last_progress = now
        if trace_done is not None:
            say(f"trace ended ({trace_done})")
            break
        if args.once and got_sample:
            break
        if args.duration and now - start >= args.duration:
            break
        if now - last_progress >= args.stale_timeout:
            stale = True
            break
        time.sleep(max(args.interval, 0.05))

    states = tower.states()
    worst = slo.worst_severity(tower.worst_fired, server_worst)
    code = slo.EXIT_STALE if stale else slo.severity_exit_code(worst)
    if args.json:
        _pipe_safe_print(json.dumps({
            "states": states, "worst_fired": worst,
            "source_reported": sorted(server_firing),
            "stale": stale,
            "snapshots": {"missed": follower.missed,
                          "duplicates": follower.duplicates},
            "exit_code": code}))
    else:
        say("")
        for s in states:
            mark = "FIRING" if s["state"] == "firing" else "ok"
            say(f"{mark:>6} {s['severity']:<4} {s['rule']} "
                f"({s['window']})"
                + (f" [tenant {s['tenant']}]" if s.get("tenant")
                   else "")
                + (f" — {s['reason']}" if s["reason"] else "")
                + (f" [fired {s['fired_count']}x]"
                   if s["fired_count"] else ""))
        for rule in sorted(server_firing):
            say(f"FIRING (source-reported) {rule}")
        if stale:
            print(f"error: source stale for {args.stale_timeout:g}s",
                  file=sys.stderr)
    return code


def cmd_bundle(args: argparse.Namespace) -> int:
    """`dpsvm bundle DIR`: render + validate one incident bundle
    (observability/blackbox.py). Exit 0 = valid, 1 = invalid, 2 = no
    bundle found."""
    import json

    from dpsvm_tpu.observability import blackbox

    try:
        path = blackbox.resolve_bundle_dir(args.dir)
    except (FileNotFoundError, NotADirectoryError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    problems = blackbox.validate_bundle(path)
    if args.json:
        try:
            incident = blackbox.load_incident(path)
        except (OSError, json.JSONDecodeError):
            incident = None
        _pipe_safe_print(json.dumps({
            "path": path, "valid": not problems,
            "problems": problems, "incident": incident}))
    else:
        try:
            _pipe_safe_print(blackbox.render_bundle(path))
        except (OSError, json.JSONDecodeError) as e:
            print(f"error: unrenderable bundle: {e}", file=sys.stderr)
            return 1
        if problems:
            print("bundle INVALID:", file=sys.stderr)
            for p in problems:
                print(f"  {p}", file=sys.stderr)
        else:
            _pipe_safe_print("bundle OK (trace schema-valid, "
                             "exposition grammar-valid)")
    return 1 if problems else 0


def _init_backend(args: argparse.Namespace) -> int:
    """Apply --platform/DPSVM_PLATFORM and fail fast on a dead backend.

    0 on success; nonzero = the caller should exit with it. The numpy
    backend needs no device and skips the probe entirely. The
    apply-and-verify logic lives in probe_devices (its ``override``
    parameter), so an ambient BENCH_PLATFORM can never clobber an
    explicit --platform.
    """
    import os

    if getattr(args, "backend", "xla") == "numpy":
        return 0
    label = "--platform" if args.platform else "DPSVM_PLATFORM"
    platform = args.platform or os.environ.get("DPSVM_PLATFORM", "").strip()
    from dpsvm_tpu.utils.backend_guard import exit_if_hung, probe_devices

    devices, reason = probe_devices(args.backend_timeout,
                                    override=platform or None,
                                    override_label=label)
    if devices is None:
        print(f"error: {reason} — try --platform cpu to run on the "
              "host, or `cli info` for diagnostics", file=sys.stderr)
        exit_if_hung(reason, 3)
        return 3
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    raw = list(sys.argv[1:] if argv is None else argv)
    args = build_parser().parse_args(argv)
    if args.command == "train" and getattr(args, "retries", 0) > 0:
        # Retry supervisor (resilience/supervisor.py): every attempt is
        # a child process — that is what lets it recover from the stall
        # watchdog's os._exit(124) and real SIGTERM preemptions, not
        # just catchable exceptions. The child runs this same CLI minus
        # the supervisor flags; the newest intact checkpoint slot is
        # injected as --resume before every attempt.
        from dpsvm_tpu.resilience import supervisor
        child = ([sys.executable, "-m", "dpsvm_tpu.cli"]
                 + supervisor.strip_flags(raw, ("--retries",
                                                "--retry-backoff")))
        return supervisor.supervise(
            child, retries=args.retries, backoff_s=args.retry_backoff,
            checkpoint_path=args.checkpoint)
    if args.command == "train":
        coord = getattr(args, "coordinator", None)
        if not coord and (getattr(args, "num_hosts", None) is not None
                          or getattr(args, "host_id", None) is not None):
            print("error: --num-hosts/--host-id require --coordinator "
                  "(docs/DISTRIBUTED.md 'Multi-host')", file=sys.stderr)
            return 2
        if coord:
            nh, hid = args.num_hosts, args.host_id
            if (nh is None) != (hid is None):
                print("error: --num-hosts and --host-id must be given "
                      "together", file=sys.stderr)
                return 2
            if nh is not None and not 0 <= hid < nh:
                print(f"error: --host-id {hid} out of range for "
                      f"--num-hosts {nh}", file=sys.stderr)
                return 2
            # MUST run before _init_backend: the backend probe warms
            # XLA, after which jax.distributed.initialize refuses to
            # run in this process (parallel/multihost.py).
            from dpsvm_tpu.parallel import multihost
            multihost.initialize(coordinator=coord, num_processes=nh,
                                 process_id=hid)
    try:
        if args.command in ("train", "test", "serve", "tune", "grid"):
            rc = _init_backend(args)
            if rc:
                return rc
        if args.command == "grid":
            return cmd_grid(args)
        if args.command == "train":
            return cmd_train(args)
        if args.command == "tune":
            return cmd_tune(args)
        if args.command == "convert":
            return cmd_convert(args)
        if args.command == "scale":
            return cmd_scale(args)
        if args.command == "info":
            return cmd_info(args)
        if args.command == "doctor":
            from dpsvm_tpu.resilience.doctor import run_doctor
            return run_doctor(shards=args.shards,
                              checkpoint_path=args.checkpoint,
                              data_path=args.data,
                              timeout_s=args.timeout,
                              serving_url=args.serving_url,
                              coordinator=args.coordinator,
                              hosts_dir=args.hosts_dir,
                              num_hosts=args.num_hosts,
                              heartbeat_max_age_s=args.heartbeat_max_age)
        if args.command == "report":
            return cmd_report(args)
        if args.command == "compare":
            return cmd_compare(args)
        if args.command == "perf":
            return cmd_perf(args)
        if args.command == "profile":
            return cmd_profile(args)
        if args.command == "watch":
            return cmd_watch(args)
        if args.command == "bundle":
            return cmd_bundle(args)
        if args.command == "serve":
            return cmd_serve(args)
        if args.command == "loadgen":
            return cmd_loadgen(args)
        if args.command == "tenants":
            return cmd_tenants(args)
        if args.command == "fleet":
            return cmd_fleet(args)
        return cmd_test(args)
    except PreemptedError as e:
        # Resumable by design: the supervisor (or the next manual run)
        # picks the snapshot up. 75 = EX_TEMPFAIL, the retry cue.
        print(f"preempted: {e}", file=sys.stderr)
        return PREEMPT_EXIT_CODE
    except DivergenceError as e:
        print(f"error: {e} (see --on-divergence / docs/ROBUSTNESS.md)",
              file=sys.stderr)
        return 1
    except FileNotFoundError as e:
        print(f"error: file not found: {e}", file=sys.stderr)
        return 2
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except Exception as e:
        # CheckpointError (corrupt file with no intact rotation slot)
        # and ShardLostError live in modules imported lazily with the
        # solvers — resolve them the same way so `--help` never pays
        # the numpy import.
        from dpsvm_tpu.data.stream import StreamError
        from dpsvm_tpu.resilience.elastic import ShardLostError
        from dpsvm_tpu.utils.checkpoint import CheckpointError
        if isinstance(e, StreamError):
            # Shard corruption with on_bad_shard='raise', the bounded
            # bad-fraction abort, or a mem-budget refusal: all are
            # one-line operator errors, not tracebacks.
            print(f"error: {e}", file=sys.stderr)
            return 2
        if isinstance(e, ShardLostError):
            # Transient like a preemption: the run resumes from the
            # newest intact checkpoint — on whatever mesh the relaunch
            # sees (the elastic re-shard path). 75 is the supervisor's
            # retry cue.
            print(f"shard lost: {e}", file=sys.stderr)
            return PREEMPT_EXIT_CODE
        if isinstance(e, CheckpointError):
            print(f"error: {e}", file=sys.stderr)
            return 2
        raise


if __name__ == "__main__":
    sys.exit(main())

"""Batched one-vs-one training: all K(K-1)/2 subproblems in ONE program.

TPU-native multiclass design with no reference analog (the reference,
``svmTrainMain.cpp``, is strictly binary; LIBSVM trains OvO pairs one
after another). Sequential OvO pays the whole per-iteration latency
floor (~22 us of sequential-dependency cost per SMO step, measured —
docs/PERF.md "Per-phase cost") and the per-pair dispatch/compile
overhead P times over. But the P pair subproblems are INDEPENDENT and
share one X, which is exactly the shape the hardware wants batched:

* every subproblem's working-pair row fetch joins one
  ``(2P, d) @ (d, n)`` MXU matmul — the dominant VMEM stream of X is
  paid once per batched step for ALL pairs instead of once per pair;
* selection becomes a masked ``(P, n)`` row-wise reduction (the lanes
  the VPU wants), amortizing the scalar-chain latency over P problems;
* one compiled program, one dispatch stream, one convergence poll.

Each subproblem advances one SMO step per batched step until ITS OWN
gap closes (frozen thereafter via masked updates), replicating the
sequential solver's per-problem trajectory (``solver/smo.py``):
selection order over the subset, eta, clips, the do-while trailing
update, per-problem iteration counting. The parity claim, stated
precisely: EQUAL GIVEN EQUAL ARITHMETIC — the batched row fetch is a
``(2P, d) @ (d, n)`` matmul where the sequential path computes
``(2, d) @ (d, n_sub)`` over the compacted subset, and the different
tiling can differ by ulps, which SMO's argmin can amplify into a
different (equally valid) trajectory near ties. tests/test_batched_ovo
asserts BITWISE equality where the layouts coincide (one pair covering
every row — identical matmul shapes) and model-level equality (same
n_sv, alpha/b within float tolerance, same convergence) on true
multiclass problems. This is the same claim shape as
``parallel/dist_decomp.py``'s sharded-fetch caveat.
The wall-clock cost of a batched step is set by the slowest-converging
pair; lanes of finished pairs ride along masked (their updates are
zeroed), which is cheap because the step cost is dominated by the
shared X stream, not the per-pair scalar work.

Parity scope (v1, guards in ``train_multiclass``): first-order
selection, unweighted, single device, no cache/shrinking/working-set,
every kernel family except precomputed (pair training needs row AND
column slices of K). Both clip rules.
"""

from __future__ import annotations

import functools
import time
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dpsvm_tpu.config import SENTINEL, SVMConfig, TrainResult
from dpsvm_tpu.ops.kernels import KernelSpec, host_row_stats, rows_from_dots
from dpsvm_tpu.ops.selection import masked_scores
from dpsvm_tpu.ops.update import alpha_pair_step
from dpsvm_tpu.utils import watchdog


def compact_submodel(x: np.ndarray, sel: np.ndarray, ys: np.ndarray,
                     result: TrainResult, xs: "Optional[np.ndarray]" = None):
    """(SVMModel, compacted TrainResult) for one batched subproblem:
    the 'callers compact with their own row masks' step of
    ``train_ovo_batched``'s contract, in ONE place for every consumer
    (OvO pairs, binary CV folds, multiclass CV fold x pair).

    ``xs``: the precomputed x[sel] slice, for callers scoring several
    subproblems that share one mask (the CV C-sweep's per-fold C
    column) — skips re-copying the training slice per subproblem."""
    import dataclasses

    from dpsvm_tpu.models.svm import SVMModel

    if xs is None:
        xs = np.ascontiguousarray(x[sel])
    rr = dataclasses.replace(
        result, alpha=np.asarray(result.alpha, np.float32)[sel])
    return SVMModel.from_train_result(xs, np.asarray(ys, np.int32),
                                      rr), rr


def ovo_pair_shapes(y, classes, d):
    """(n_a + n_b, d) for every OvO pair of ``classes`` in ``y`` — the
    subproblem shapes the sequential path resolves auto sentinels at.
    ONE implementation shared by the OvO and CV entry points so their
    ``batched_guard`` shape lists cannot drift."""
    y = np.asarray(y)
    counts = {cl: int(np.sum(y == cl)) for cl in classes}
    return [(counts[classes[a]] + counts[classes[b]], d)
            for a in range(len(classes))
            for b in range(a + 1, len(classes))]


def batched_guard(config: SVMConfig, what: str,
                  subproblem_shapes=None) -> None:
    """Reject configs the batched program would silently ignore or
    change the math of (the no-silent-ignore policy of config.validate's
    guard tables). Shared by the OvO and CV batched entry points.

    ``subproblem_shapes``: iterable of (n, d) the sequential equivalent
    would train — per-pair sizes for OvO, per-fold sizes for CV. When
    the config carries auto sentinels (working_set=0 / shrinking=
    "auto"), the sequential path resolves them PER SUBPROBLEM via
    ``config.resolved``; the batched program only implements the
    classic first-order path, so any subproblem whose resolution picks
    a different solver path must be rejected here, not silently trained
    differently. (Today ``_auto_solver_plan`` resolves to classic at
    every shape, making this a no-op — but the policy slots are
    designed to flip on measured chip rows, and batched=True must not
    drift from the sequential default when they do.)"""
    blockers = [name for name, bad in (
        ("selection", config.selection != "first-order"),
        ("weights", config.weight_pos != 1.0 or config.weight_neg != 1.0),
        ("shards", config.shards != 1),
        ("shrinking", config.shrinking not in (False, "auto")),
        ("working_set", config.working_set not in (0, 2)),
        ("cache_size", config.cache_size > 0),
        ("use_pallas", config.use_pallas == "on"),
        ("backend", config.backend != "xla"),
        ("polish", config.polish),
    ) if bad]
    if blockers:
        raise ValueError(
            f"batched {what} runs the plain first-order single-device "
            f"path; incompatible options set: {blockers} (train "
            "with batched=False for these)")
    if (config.shrinking == "auto" or config.working_set == 0) \
            and subproblem_shapes is not None:
        for n_i, d_i in subproblem_shapes:
            r = config.resolved(int(n_i), int(d_i))
            if r.working_set != 2 or r.shrinking:
                raise ValueError(
                    f"batched {what}: the auto solver plan resolves to "
                    f"a non-classic path (working_set={r.working_set}, "
                    f"shrinking={r.shrinking}) for a {n_i}x{d_i} "
                    "subproblem; the batched program only implements "
                    "the classic first-order path — train with "
                    "batched=False, or set working_set=2 / "
                    "shrinking=False explicitly to accept the classic "
                    "path for every subproblem")


class OvoCarry(NamedTuple):
    alpha: jax.Array    # (P, n) f32
    f: jax.Array        # (P, n) f32
    b_hi: jax.Array     # (P,) f32 — previous step's selection, like the
    b_lo: jax.Array     # (P,) f32   pair solver's do-while carry slots
    n_iter: jax.Array   # (P,) i32 — per-problem step counts
    t: jax.Array        # () i32 — batched steps taken (poll cadence)


def build_pair_targets(y: np.ndarray, classes: np.ndarray
                       ) -> Tuple[np.ndarray, np.ndarray,
                                  List[Tuple[int, int]]]:
    """(yb (P, n) f32 with +/-1 on the pair's rows and 0 elsewhere,
    valid (P, n) bool, pairs): the OvO subproblem layout over the SHARED
    example axis. Row order inside a subproblem is the full-set order,
    which boolean-mask compaction preserves — the tie-break order the
    sequential trainer sees on its compacted subset."""
    y = np.asarray(y)
    k = len(classes)
    pairs = [(a, b) for a in range(k) for b in range(a + 1, k)]
    n = y.shape[0]
    yb = np.zeros((len(pairs), n), np.float32)
    valid = np.zeros((len(pairs), n), bool)
    for p, (a, b) in enumerate(pairs):
        sel_a = y == classes[a]
        sel_b = y == classes[b]
        yb[p, sel_a] = 1.0
        yb[p, sel_b] = -1.0
        valid[p] = sel_a | sel_b
    return yb, valid, pairs


def _ovo_step(carry: OvoCarry, x, yb, x2, valid, c_arr, g_arr,
              *, kspec: KernelSpec, epsilon: float, max_iter: int,
              precision, pairwise_clip: bool) -> OvoCarry:
    """One batched step: every still-active subproblem advances one
    exact first-order SMO iteration; finished ones are frozen.

    ``c_arr`` is the (P,) per-subproblem box bound — identical values
    for OvO/CV batches, distinct ones for the C-grid sweep (the box is
    the ONLY place C enters the iteration, so one compiled program
    serves any C assignment). ``g_arr`` is the (P,) per-subproblem
    kernel gamma, traded the same way: the row-fetch dots are
    gamma-independent, so per-problem gammas share the one matmul and
    only the elementwise epilogue differs — one program serves the
    whole (C, gamma) grid."""
    alpha, f = carry.alpha, carry.f
    P = alpha.shape[0]
    rows_p = jnp.arange(P)

    # Active = carry b's (previous selection) still show a violating
    # pair AND budget left — the sequential solver's do-while cond,
    # applied per problem.
    active = (carry.b_lo > carry.b_hi + 2.0 * epsilon) \
        & (carry.n_iter < jnp.int32(max_iter))

    # --- masked first-order selection, all problems at once ----------
    # (masked_scores is elementwise, so the shared membership
    # definition broadcasts over the (P, n) batch unchanged.)
    f_up, f_low = masked_scores(alpha, yb, f, c_arr[:, None], valid)
    i_hi = jnp.argmin(f_up, axis=1)                     # (P,)
    i_lo = jnp.argmax(f_low, axis=1)
    b_hi = jnp.take_along_axis(f_up, i_hi[:, None], 1)[:, 0]
    b_lo = jnp.take_along_axis(f_low, i_lo[:, None], 1)[:, 0]

    # --- shared row fetch: ONE (2P, d) @ (d, n) MXU pass -------------
    w_idx = jnp.concatenate([i_hi, i_lo])               # (2P,)
    rows = x[w_idx]                                     # (2P, d)
    dots = jnp.matmul(rows, x.T, precision=precision)   # (2P, n)
    g2 = jnp.concatenate([g_arr, g_arr])[:, None]       # (2P, 1)
    k_all = rows_from_dots(dots, x2[w_idx], x2, kspec, gamma=g2)
    k_hi, k_lo = k_all[:P], k_all[P:]                   # (P, n) each

    gather = lambda m, i: jnp.take_along_axis(m, i[:, None], 1)[:, 0]
    eta = (gather(k_hi, i_hi) + gather(k_lo, i_lo)
           - 2.0 * gather(k_hi, i_lo))                  # (P,)

    y_hi = gather(yb, i_hi)
    y_lo = gather(yb, i_lo)
    a_hi = gather(alpha, i_hi)
    a_lo = gather(alpha, i_lo)
    a_hi_n, a_lo_n = alpha_pair_step(a_hi, a_lo, y_hi, y_lo, b_hi, b_lo,
                                     eta, c_arr, c_arr, pairwise_clip)
    # Freeze finished problems: their alphas keep the old values and
    # their f deltas are zero.
    a_hi_n = jnp.where(active, a_hi_n, a_hi)
    a_lo_n = jnp.where(active, a_lo_n, a_lo)

    # Write order lo-then-hi per problem (the i_hi == i_lo corner),
    # matching solver/smo.py:229-230.
    alpha = alpha.at[rows_p, i_lo].set(a_lo_n)
    alpha = alpha.at[rows_p, i_hi].set(a_hi_n)
    f = f + ((a_hi_n - a_hi) * y_hi)[:, None] * k_hi \
          + ((a_lo_n - a_lo) * y_lo)[:, None] * k_lo

    return OvoCarry(
        alpha=alpha, f=f,
        # b slots update only for problems that stepped, so a finished
        # problem's cond stays false forever (and its final gap is the
        # one its last real step saw — same as sequential).
        b_hi=jnp.where(active, b_hi, carry.b_hi),
        b_lo=jnp.where(active, b_lo, carry.b_lo),
        n_iter=carry.n_iter + active.astype(jnp.int32),
        t=carry.t + 1,
    )


@functools.lru_cache(maxsize=16)
def _build_ovo_runner(kspec: KernelSpec, epsilon: float,
                      max_iter: int, precision_name: str,
                      pairwise_clip: bool):
    """Compiled batched chunk runner, cached per hyperparameter set.
    Shapes (P, n, d) specialize via jit; C rides as a traced (P,)
    argument so one program serves every C assignment."""
    precision = getattr(lax.Precision, precision_name)

    def chunk(carry: OvoCarry, x, yb, x2, valid, c_arr, g_arr, limit):
        def cond(s):
            any_active = jnp.any(
                (s.b_lo > s.b_hi + 2.0 * epsilon)
                & (s.n_iter < jnp.int32(max_iter)))
            return any_active & (s.t < limit)

        final = lax.while_loop(
            cond,
            lambda s: _ovo_step(s, x, yb, x2, valid, c_arr, g_arr,
                                kspec=kspec,
                                epsilon=epsilon, max_iter=max_iter,
                                precision=precision,
                                pairwise_clip=pairwise_clip),
            carry)
        # Per-problem poll stats in ONE transfer: (3, P) i32 with the
        # b's riding as bit patterns (same trick as driver.pack_stats).
        stats = jnp.stack([
            final.n_iter,
            lax.bitcast_convert_type(final.b_lo, jnp.int32),
            lax.bitcast_convert_type(final.b_hi, jnp.int32)])
        return final, stats

    return jax.jit(chunk, donate_argnums=(0,))


def train_ovo_batched(x: np.ndarray, yb: np.ndarray, valid: np.ndarray,
                      config: SVMConfig,
                      device: Optional[jax.Device] = None,
                      c_values: Optional[np.ndarray] = None,
                      gamma_values: Optional[np.ndarray] = None
                      ) -> List[TrainResult]:
    """Train the (P, n) OvO batch; one TrainResult per subproblem, each
    carrying the FULL-LENGTH (n,) alpha (zeros off the subproblem —
    callers compact with their own row masks).

    ``c_values`` (optional (P,)) gives each subproblem its own box
    bound — the C-grid sweep (train_c_sweep). Default: config.c
    everywhere. ``gamma_values`` (optional (P,)) likewise gives each
    subproblem its own kernel gamma (the gamma axis of a grid);
    default: the config's resolved gamma. Each TrainResult reports the
    gamma its subproblem trained with."""
    config.validate()
    n, d = x.shape
    P = yb.shape[0]
    gamma = float(config.resolve_gamma(d))
    kspec = config.kernel_spec(d)
    precision_name = config.matmul_precision.upper()

    t0 = time.perf_counter()
    xd = jax.device_put(jnp.asarray(x, jnp.float32), device)
    ybd = jax.device_put(jnp.asarray(yb, jnp.float32), device)
    x2 = jax.device_put(host_row_stats(x, kspec), device)
    vd = jax.device_put(jnp.asarray(valid), device)
    carry = OvoCarry(
        alpha=jnp.zeros((P, n), jnp.float32),
        f=jnp.asarray(-yb, jnp.float32),
        b_hi=jnp.full((P,), jnp.float32(-SENTINEL)),
        b_lo=jnp.full((P,), jnp.float32(SENTINEL)),
        n_iter=jnp.zeros((P,), jnp.int32),
        t=jnp.int32(0),
    )
    if device is not None:
        carry = jax.device_put(carry, device)

    if c_values is None:
        c_arr = np.full((P,), np.float32(config.c))
    else:
        c_arr = np.asarray(c_values, np.float32)
        if c_arr.shape != (P,):
            raise ValueError(f"c_values must have shape ({P},), got "
                             f"{c_arr.shape}")
        if not (np.all(np.isfinite(c_arr)) and np.all(c_arr > 0)):
            # (isfinite matters: NaN/inf pass a bare > 0 / <= 0 test
            # and train a silently-"converged" empty model with b=nan)
            raise ValueError("every C in c_values must be a finite "
                             "number > 0")
    if gamma_values is None:
        g_arr = np.full((P,), np.float32(gamma))
    else:
        g_arr = np.asarray(gamma_values, np.float32)
        if g_arr.shape != (P,):
            raise ValueError(f"gamma_values must have shape ({P},), "
                             f"got {g_arr.shape}")
        if not (np.all(np.isfinite(g_arr)) and np.all(g_arr > 0)):
            raise ValueError("every gamma in gamma_values must be a "
                             "finite number > 0")
    c_d = jax.device_put(jnp.asarray(c_arr), device)
    g_d = jax.device_put(jnp.asarray(g_arr), device)
    runner = _build_ovo_runner(kspec,
                               float(config.epsilon),
                               int(config.max_iter), precision_name,
                               config.clip == "pairwise")

    eps = float(config.epsilon)
    chunk = int(config.chunk_iters)
    # The batched-step budget: every problem is frozen after max_iter
    # of ITS OWN steps, so max_iter batched steps bound the whole run.
    budget = int(config.max_iter)
    watchdog.pet()

    limit = min(chunk, budget)
    carry, stats = runner(carry, xd, ybd, x2, vd, c_d, g_d,
                          jnp.int32(limit))
    while True:
        # Speculative next chunk before the poll blocks (same dispatch
        # pipelining as driver.host_training_loop; a chunk dispatched
        # after global convergence exits on its first cond check).
        limit_next = min(limit + chunk, budget)
        if limit_next > limit:
            carry_next, stats_next = runner(carry, xd, ybd, x2, vd,
                                            c_d, g_d,
                                            jnp.int32(limit_next))
        else:
            carry_next = stats_next = None

        s = np.asarray(stats)               # blocks; (3, P) i32
        watchdog.pet()
        n_iter = s[0]
        b_lo = s[1].view(np.float32)
        b_hi = s[2].view(np.float32)
        done = ~(b_lo > b_hi + 2.0 * eps)
        capped = n_iter >= budget
        if np.all(done | capped) or stats_next is None:
            break
        if (config.wall_budget_s
                and time.perf_counter() - t0 > config.wall_budget_s):
            # Time budget exhausted. The speculative chunk is already in
            # flight and is NOT a no-op mid-training, so poll it: the
            # reported (n_iter, b) must describe the carry actually
            # returned below.
            s = np.asarray(stats_next)
            watchdog.pet()
            n_iter = s[0]
            b_lo = s[1].view(np.float32)
            b_hi = s[2].view(np.float32)
            done = ~(b_lo > b_hi + 2.0 * eps)
            carry, stats_next = carry_next, None
            break
        carry, stats, limit = carry_next, stats_next, limit_next

    train_seconds = time.perf_counter() - t0
    alpha_all = np.asarray(carry.alpha if stats_next is None
                           else carry_next.alpha)
    # A speculative chunk after global convergence is a no-op, so its
    # carry equals the polled one; reading whichever is newest is safe
    # and keeps the donated-buffer chain simple.
    results = []
    for p in range(P):
        results.append(TrainResult(
            alpha=alpha_all[p],
            b=(float(b_lo[p]) + float(b_hi[p])) / 2.0,
            n_iter=int(n_iter[p]),
            converged=bool(done[p]),
            b_lo=float(b_lo[p]),
            b_hi=float(b_hi[p]),
            train_seconds=train_seconds,   # shared program: wall clock
            gamma=float(g_arr[p]),         # is per-batch, not per-pair
            n_sv=int(np.sum(alpha_all[p] > 0)),
            kernel=config.kernel,
            coef0=float(config.coef0),
            degree=int(config.degree),
        ))
    return results


def validate_c_grid(cs, config: SVMConfig, gammas=None):
    """Shared validation for the grid-sweep entry points (train_c_sweep,
    models/cv.cross_validate_c_sweep): ONE copy of the cs/gammas and
    kernel rules so the paths cannot drift. Returns (cs, gammas) as the
    f32 arrays actually trained with, gammas None when not swept
    (callers keep their original values for reporting — f32 rounding
    must not leak into results)."""
    if config.kernel == "precomputed":
        # The batched step computes kernel rows from X (matmul +
        # epilogue); the precomputed gather path is not wired into it.
        raise ValueError("the batched C-sweep does not support the "
                         "precomputed kernel; fit each C with "
                         "api.fit instead")
    cs = np.asarray(cs, np.float32)
    if cs.ndim != 1 or len(cs) == 0:
        raise ValueError(f"cs must be a non-empty 1-D list of C values, "
                         f"got shape {cs.shape}")
    if not (np.all(np.isfinite(cs)) and np.all(cs > 0)):
        raise ValueError("every C must be a finite number > 0 "
                         "(after float32 cast)")
    if gammas is None:
        return cs, None
    if config.kernel == "linear":
        # gamma does not enter the linear kernel at all; training
        # len(gammas) bitwise-identical copies and reporting a
        # "best_gamma" would fabricate a model-selection result
        # (no-silent-ignore).
        raise ValueError("the linear kernel has no gamma; drop the "
                         "gamma axis of the sweep")
    gammas = np.asarray(gammas, np.float32)
    if gammas.ndim != 1 or len(gammas) == 0:
        raise ValueError(f"gammas must be a non-empty 1-D list, got "
                         f"shape {gammas.shape}")
    if not (np.all(np.isfinite(gammas)) and np.all(gammas > 0)):
        raise ValueError("every gamma must be a finite number > 0 "
                         "(after float32 cast)")
    return cs, gammas


def train_c_sweep(x: np.ndarray, y: np.ndarray, cs,
                  config: SVMConfig,
                  device: Optional[jax.Device] = None,
                  gammas=None) -> List[TrainResult]:
    """Train the SAME binary problem at every point of a C (x gamma)
    grid — in ONE compiled batched program (LIBSVM users run grid.py
    and pay one full training per grid point; here every grid point
    shares the X stream and the per-step latency like any other
    subproblem batch: the box bound is the only place C enters the
    iteration, and gamma only enters the elementwise kernel epilogue
    after the gamma-independent dot products).

    ``y`` is +/-1. Without ``gammas``: one TrainResult per C in input
    order (config's resolved gamma). With ``gammas``: the full product
    grid in row-major (C, gamma) order — result index i*len(gammas)+j
    is (cs[i], gammas[j]), and each TrainResult reports its own gamma.
    config.c is ignored in favor of ``cs``. Same solver scope as every
    batched path (``batched_guard``)."""
    x = np.asarray(x)
    batched_guard(config, "C-sweep",
                  [(x.shape[0], x.shape[1])])
    cs, gammas = validate_c_grid(cs, config, gammas)
    y = np.asarray(y, np.float32)
    bad = set(np.unique(y)) - {1.0, -1.0}
    if bad:
        raise ValueError(f"train_c_sweep takes +/-1 labels, got extra "
                         f"values {sorted(bad)}")
    if gammas is None:
        c_values, gamma_values = cs, None
    else:
        c_values = np.repeat(cs, len(gammas))
        gamma_values = np.tile(gammas, len(cs))
    P = len(c_values)
    yb = np.tile(y, (P, 1))
    valid = np.ones((P, len(y)), bool)
    return train_ovo_batched(x, yb, valid, config, device=device,
                             c_values=c_values,
                             gamma_values=gamma_values)

"""Large-working-set SMO decomposition: the MXU-utilization path.

The 2-violator iteration (solver/smo.py) is latency-bound by design:
each step moves two kernel rows, ~188 MFLOP at the MNIST shape, leaving
the MXU ~99% idle (docs/PERF.md "Per-phase cost"). The classic remedy —
what SVMlight/LIBSVM call *decomposition* and GPU solvers (ThunderSVM,
the GPU-SMO literature) run with large q — is to amortize one big
kernel-block fetch over many cheap pair updates:

  1. select the top q/2 violators from I_up (smallest f) and top q/2
     from I_low (largest f) with ``lax.top_k`` — the globally
     most-violating pair is always slots 0 of each half, which is the
     condition decomposition convergence proofs need;
  2. ONE ``(q, d) @ (d, n)`` MXU matmul + fused kernel epilogue yields
     the working-set block K_WN; its column gather K_WW = K_WN[:, W] is
     the (q, q) subproblem kernel;
  3. an inner ``lax.while_loop`` runs plain SMO pair steps entirely on
     (q,)-sized state (alpha_W, f_W maintained via K_WW rows) until the
     subproblem's own gap closes to the global tolerance or
     ``inner_cap`` steps — no O(n) traffic per inner step;
  4. one fused rank-q update applies the block's total change:
     f += (dalpha * y_W) @ K_WN, alpha scattered back by index.

Everything — outer selection, top_k, matmul, the inner loop, the rank-q
update — lives inside ONE ``lax.while_loop`` under jit, chunk-polled by
the same host driver as the 2-violator path.

This is *not* a reference-parity path (the reference has nothing like
it — its iteration is svmTrain.cu:469-497's single pair). The model it
converges to is the same dual optimum, checked against the oracle and
LibSVM by tests/test_decomp.py; the trajectory is intentionally
different. ``n_iter`` counts inner pair-updates so budgets and logs stay
comparable with the 2-violator solvers. Eta is always TAU-clamped (the
subproblem block can contain duplicate-geometry rows; there is no raw-
division parity contract to preserve here).
"""

from __future__ import annotations

import functools
import sys
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dpsvm_tpu.config import SENTINEL, SVMConfig, TrainResult
from dpsvm_tpu.ops.kernels import (KernelSpec, host_row_stats,
                                   host_row_norms_sq,
                                   rows_from_dots)
from dpsvm_tpu.observability import compilewatch
from dpsvm_tpu.ops.selection import masked_scores_and_masks
from dpsvm_tpu.ops.update import alpha_pair_step
from dpsvm_tpu.solver.driver import (device_sv_count, host_training_loop,
                                     pack_stats, resume_state)


class DecompCarry(NamedTuple):
    alpha: jax.Array    # (n,) f32
    f: jax.Array        # (n,) f32
    b_hi: jax.Array     # () f32 latest global selection
    b_lo: jax.Array     # () f32
    n_iter: jax.Array   # () i32 cumulative INNER pair-updates
    rounds: jax.Array   # () i32 outer rounds (block fetch + subsolve +
                        # rank-q update) — telemetry only, rides the
                        # packed-stats transfer (docs/OBSERVABILITY.md)


def init_carry(y) -> DecompCarry:
    """Same state/convention as smo.init_carry (host NumPy, zero extra
    XLA programs); sentinels force the first outer round."""
    y_np = np.asarray(y, np.float32)
    return DecompCarry(
        alpha=np.zeros_like(y_np),
        f=-y_np,
        b_hi=np.float32(-SENTINEL),
        b_lo=np.float32(SENTINEL),
        n_iter=np.int32(0),
        rounds=np.int32(0),
    )


class _InnerState(NamedTuple):
    a: jax.Array        # (q,) alphas of the working set
    f: jax.Array        # (q,) subproblem gradient (exact, via K_WW)
    b_hi: jax.Array
    b_lo: jax.Array
    t: jax.Array        # () i32 inner steps taken


def inner_subsolve(k_ww, y_w, c_w, a_w0, f_w0, active, *, epsilon,
                   step_cap, pairwise_clip, seed_transform=None
                   ) -> _InnerState:
    """The WSS2 SMO subsolve on a (q, q) block — shared by the
    single-device and distributed decomposition paths (this block
    encodes the measured design facts: exact-f32 K_WW callers, the TAU
    eta clamp, real-extrema seeding so an already-optimal block no-ops
    instead of corner-slamming; see decomp_step's comments).

    ``seed_transform`` lets the distributed caller pcast the seed to
    shard_map's varying types; arithmetic is identical either way."""
    kdiag_w = jnp.diagonal(k_ww)

    def inner_cond(s: _InnerState):
        return (s.b_lo > s.b_hi + 2.0 * epsilon) & (s.t < step_cap)

    def inner_body(s: _InnerState):
        fu, fl, _, in_low_w = masked_scores_and_masks(s.a, y_w, s.f, c_w,
                                                      valid=active)
        i_hi = jnp.argmin(fu)
        bh = fu[i_hi]
        bl = jnp.max(fl)                    # stopping gap: max violator
        # Second-order (LIBSVM WSS2) partner choice — free here because
        # the exact kernel column K_WW[i_hi] is already on hand (the
        # 2-violator solver pays a serial (1,d)@(d,n) matmul for this).
        # First-order inner steps need ~10-20x more of them at benchmark
        # shapes, and an inner step costs ~22 us of fixed latency
        # regardless of q, so step QUALITY is everything (measured:
        # first-order inner stalls the MNIST shape at 2M steps; WSS2
        # inner converges it).
        bb = fl - bh
        aa = jnp.maximum(kdiag_w[i_hi] + kdiag_w - 2.0 * k_ww[i_hi],
                         1e-12)
        obj = jnp.where(in_low_w & (bb > 0), bb * bb / aa, -1.0)
        i_lo = jnp.argmax(obj)
        bl_sel = fl[i_lo]
        eta = jnp.maximum(k_ww[i_hi, i_hi] + k_ww[i_lo, i_lo]
                          - 2.0 * k_ww[i_hi, i_lo], 1e-12)
        a_hi, a_lo = s.a[i_hi], s.a[i_lo]
        a_hi_n, a_lo_n = alpha_pair_step(a_hi, a_lo, y_w[i_hi], y_w[i_lo],
                                         bh, bl_sel, eta,
                                         c_w[i_hi], c_w[i_lo],
                                         pairwise_clip)
        a = s.a.at[i_lo].set(a_lo_n)
        a = a.at[i_hi].set(a_hi_n)
        fsub = (s.f + (a_hi_n - a_hi) * y_w[i_hi] * k_ww[i_hi]
                + (a_lo_n - a_lo) * y_w[i_lo] * k_ww[i_lo])
        return _InnerState(a, fsub, bh, bl, s.t + 1)

    # Seed with the block's REAL entry extrema, not do-while sentinels:
    # when the subproblem enters already at its optimum (the outer
    # loop's trailing round, or a warm-start from the solved model), a
    # sentinel-forced first step would find no positive violator,
    # argmax an all(-1) objective to slot 0, and bl_sel = -SENTINEL
    # would slam that alpha to a box corner while still reporting
    # convergence. With the real entry gap the loop never starts.
    # Whenever the global gap is open the block's entry gap is >= it
    # (the global pair is in W), so >= 1 inner step still happens and
    # every non-trailing round makes strict progress.
    fu0, fl0, _, _ = masked_scores_and_masks(a_w0, y_w, f_w0, c_w,
                                             valid=active)
    inner0 = _InnerState(a_w0, f_w0, jnp.min(fu0), jnp.max(fl0),
                         jnp.int32(0))
    if seed_transform is not None:
        inner0 = seed_transform(inner0)
    return lax.while_loop(inner_cond, inner_body, inner0)


def decomp_step(carry: DecompCarry, x: jax.Array, y: jax.Array,
                x2: jax.Array, c: float, kspec: KernelSpec, *,
                q: int, inner_cap: int, epsilon: float,
                limit=None, weights=(1.0, 1.0),
                precision=lax.Precision.HIGHEST,
                pairwise_clip: bool = False,
                pallas_inner: bool = False,
                interpret: bool = False,
                valid=None) -> DecompCarry:
    """One outer decomposition round (select-q -> block -> subsolve ->
    rank-q update). ``limit`` (traced) caps the round's inner steps so
    ``n_iter`` stops exactly at the budget like every other solver.
    ``pallas_inner`` runs the subsolve as one Pallas kernel launch
    (experimental/subsolve_kernel.py) instead of the XLA while_loop — same math,
    bitwise-equal in interpret-mode tests."""
    alpha, f = carry.alpha, carry.f
    wp, wn = weights
    if wp != 1.0 or wn != 1.0:
        c_box = jnp.where(y > 0, jnp.float32(c * wp), jnp.float32(c * wn))
    else:
        c_box = c

    # --- outer selection: top q/2 violators per side --------------------
    f_up, f_low, in_up, in_low = masked_scores_and_masks(alpha, y, f, c_box,
                                                         valid=valid)
    _, up_idx = lax.top_k(-f_up, q // 2)        # ascending f: worst first
    _, low_idx = lax.top_k(f_low, q // 2)       # descending f
    b_hi = f_up[up_idx[0]]
    b_lo = f_low[low_idx[0]]

    # Dedup (an interior alpha is in both sets): fixed-shape jnp.unique,
    # padding with -1. Padded/non-member slots join the subproblem as
    # permanently-masked entries.
    w_idx = jnp.unique(jnp.concatenate([up_idx, low_idx]),
                       size=q, fill_value=jnp.int32(-1))
    active = w_idx >= 0
    wi = jnp.where(active, w_idx, 0)
    # (Every point with alpha in [0, C] is in I_up or I_low, so beyond
    # the -1 padding no further membership masking is needed — except
    # capacity-padding rows under the shrinking manager, whose sentinel
    # scores can still be picked as top_k filler when real violators run
    # out; they must stay frozen in the subsolve.)
    if valid is not None:
        active = active & valid[wi]

    # --- the subproblem kernel K_WW, computed EXACTLY (f32 HIGHEST),
    # not gathered from the possibly-bf16 K_WN: in DEFAULT precision a
    # gathered block is only bf16-accurate, which breaks its positive
    # semidefiniteness for near-duplicate rows — the inner SMO then sees
    # negative-eta pairs, the TAU clamp turns them into huge corner
    # steps, and the subsolve thrashes instead of converging (measured:
    # the MNIST-shape run stalls at 2M inner steps, train_acc 0.73-0.87).
    # The (q, d) @ (d, q) pass is O(q^2 d) — noise next to the (q, n)
    # fetch below.
    rows = x[wi]
    if kspec.kind == "precomputed":
        # rows are gathered K rows; the (q, q) block is a column gather
        # of the stored (exact) values — the PSD concern above is moot.
        k_ww = rows[:, wi]
    else:
        dots_ww = jnp.matmul(rows, rows.T,
                             precision=lax.Precision.HIGHEST)
        k_ww = rows_from_dots(dots_ww, x2[wi], x2[wi], kspec)  # (q, q)

    y_w = y[wi]
    a_w0 = alpha[wi]
    f_w0 = f[wi]
    if isinstance(c_box, jnp.ndarray):
        c_w = c_box[wi]
    else:
        c_w = jnp.full((q,), jnp.float32(c))

    # --- inner subsolve: WSS2 SMO on (q,)-sized state (shared helper,
    # also driven by parallel/dist_decomp.py) ---------------------------
    step_cap = jnp.int32(inner_cap)
    if limit is not None:
        step_cap = jnp.minimum(step_cap, limit - carry.n_iter)
    if pallas_inner:
        from dpsvm_tpu.experimental.subsolve_kernel import (
            pallas_inner_subsolve)
        a_in, f_in, bh_in, bl_in, t_in = pallas_inner_subsolve(
            k_ww, y_w, c_w, a_w0, f_w0, active, epsilon, step_cap,
            max_cap=inner_cap, pairwise=pairwise_clip,
            interpret=interpret)
        inner = _InnerState(a_in, f_in, bh_in, bl_in, t_in)
    else:
        inner = inner_subsolve(k_ww, y_w, c_w, a_w0, f_w0, active,
                               epsilon=epsilon, step_cap=step_cap,
                               pairwise_clip=pairwise_clip)

    # --- rank-q application: the ONE (q, d) @ (d, n) MXU pass ----------
    # Deliberately AFTER the subsolve: the (q, n) block is consumed only
    # by this weighted row-sum, so XLA can fuse the kernel epilogue into
    # the reduction instead of materializing (and re-reading) a
    # (q, n) f32 intermediate — at q=1024, n=60000 that is 2x245 MB of
    # HBM traffic per round saved.
    dalpha = jnp.where(active, inner.a - a_w0, 0.0)
    # Padding slots carry dalpha == 0, so duplicate index-0 adds are
    # no-ops; real slots are unique by construction.
    alpha = alpha.at[wi].add(dalpha)
    if kspec.kind == "precomputed":
        k_wn = rows                                          # (q, n)
    else:
        dots = jnp.matmul(rows, x.T, precision=precision)    # (q, n)
        k_wn = rows_from_dots(dots, x2[wi], x2, kspec)       # (q, n)
    f = f + jnp.matmul((dalpha * y_w)[None, :], k_wn,
                       precision=precision)[0]
    return DecompCarry(alpha, f, b_hi, b_lo, carry.n_iter + inner.t,
                       carry.rounds + 1)


@functools.lru_cache(maxsize=32)
def _build_decomp_runner(c: float, kspec, epsilon: float, q: int,
                         inner_cap: int, precision_name: str,
                         weights=(1.0, 1.0), pairwise_clip: bool = False,
                         pallas_inner: bool = False,
                         masked: bool = False):
    """Compiled chunk runner with the decomposition outer loop inside;
    same contract as smo._build_chunk_runner (including the
    ``masked=True`` padded-capacity variant for the shrinking manager:
    an extra dynamic ``n_valid`` before ``limit``). The interpret-mode
    policy for the Pallas inner kernel is resolved HERE (off-TPU
    backends run it interpreted, the CPU test suite's path) so every
    call site shares one policy."""
    from dpsvm_tpu.experimental.fused import _should_interpret

    interpret = _should_interpret() if pallas_inner else False
    precision = getattr(lax.Precision, precision_name)
    kspec = KernelSpec.coerce(kspec)

    def body(s, x, y, x2, limit, valid):
        return decomp_step(s, x, y, x2, c, kspec, q=q,
                           inner_cap=inner_cap, epsilon=epsilon,
                           limit=limit, weights=weights,
                           precision=precision,
                           pairwise_clip=pairwise_clip,
                           pallas_inner=pallas_inner,
                           interpret=interpret,
                           valid=valid)

    def stats(final: DecompCarry):
        return pack_stats(final.n_iter, final.b_lo, final.b_hi,
                          n_sv=device_sv_count(final.alpha),
                          rounds=final.rounds)

    if masked:
        def run(carry: DecompCarry, x, y, x2, n_valid, limit):
            valid = jnp.arange(x.shape[0], dtype=jnp.int32) < n_valid
            final = lax.while_loop(
                lambda s: (s.b_lo > s.b_hi + 2.0 * epsilon)
                          & (s.n_iter < limit),
                lambda s: body(s, x, y, x2, limit, valid),
                carry)
            return final, stats(final)
    else:
        def run(carry: DecompCarry, x, y, x2, limit):
            final = lax.while_loop(
                lambda s: (s.b_lo > s.b_hi + 2.0 * epsilon)
                          & (s.n_iter < limit),
                lambda s: body(s, x, y, x2, limit, None),
                carry)
            return final, stats(final)

    return jax.jit(run, donate_argnums=(0,))


def train_single_device_decomp(x: np.ndarray, y: np.ndarray,
                               config: SVMConfig,
                               device: Optional[jax.Device] = None,
                               f_init: Optional[np.ndarray] = None,
                               alpha_init: Optional[np.ndarray] = None
                               ) -> TrainResult:
    """Train with working_set = q > 2. Same host contract as
    smo.train_single_device (NumPy in/out, chunk polling, checkpoints)."""
    config.validate()
    n, d = x.shape
    # top_k needs k <= n; tiny problems degrade gracefully to a smaller
    # (even) block.
    q = 2 * min(int(config.working_set) // 2, n)
    # Auto cap q/4: SHORT subsolves win. Only the first ~q/4 steps of a
    # round act on large violations; letting the subsolve run to its own
    # convergence (cap 4q) grinds on tiny block-local violations while
    # the global picture is stale (measured, CI scale: q=512 cap=2048
    # needs 20.7k inner steps to converge what cap=64 does in 7.0k; the
    # MNIST shape with cap=4q stalls entirely at the 2M budget).
    # Tuning guide (20000x128 planted, f32; pair-SMO baseline = 50k
    # iterations): total inner pair-updates to convergence scale with
    # BOTH knobs — q=1024: cap 32/64/128/256 -> 60k/98k/161k/219k;
    # q=2048 cap 64 -> 66k; q=4096 cap 128 -> 45k (BELOW the pair
    # count). Large blocks with short subsolves buy step quality;
    # rounds (each one (q,d)@(d,n) pass) grow as total/cap — pick the
    # trade for the hardware's round cost. The scan is committed and
    # re-runnable (benchmarks/iteration_economy.py, results in
    # benchmarks/results/iteration_economy_r4.jsonl); its cross-shape
    # rows show the economics improve with d (q=4096 cap 128 at
    # 8000x784: 13k updates, 0.66x the pair count) and fail outright at
    # small-d/small-gamma (30000x54 C=64: q arms DNF at 600k) — see
    # docs/PERF.md "Solver-path iteration economics".
    # q-SELECTION RULE (same scan, round 4): q must exceed the problem's
    # SV count by ~1.3x, or the subsolves grind on stale global state
    # and the update count blows up 2.5-3x instead of winning 0.7x —
    # measured at TWO shapes: 8000x784 (n_sv~1.4k: q1024 34.4k updates
    # vs q2048 13.7k vs q4096 13.0k) and 20000x784 (n_sv~3.1k: q2048
    # 103k vs q4096 34.8k, classic 49.8k). Above the threshold the
    # economy is flat in q, so prefer the smallest q >= 1.3x the
    # expected SV count; the 60000x784 benchmark shape (n_sv~8.1k)
    # therefore needs q~12288, NOT 4096 — or grow_working_set=True to
    # apply the rule without knowing n_sv (the auto cap q/4 is applied
    # inside build() below so a grown block's cap tracks its q).
    gamma = float(config.resolve_gamma(d))
    kspec = config.kernel_spec(d)

    xd = jax.device_put(jnp.asarray(x, jnp.float32), device)
    yd = jax.device_put(jnp.asarray(y, jnp.float32), device)
    x2 = jax.device_put(host_row_stats(x, kspec), device)
    carry = init_carry(np.asarray(y, np.float32))
    if f_init is not None:
        carry = carry._replace(f=np.asarray(f_init, np.float32))
    if alpha_init is not None:
        carry = carry._replace(alpha=np.asarray(alpha_init, np.float32))

    def carry_from_ckpt(ck):
        # Initial resume AND the driver's divergence rollback
        # (docs/ROBUSTNESS.md). The rounds counter restarts at 0 — it is
        # telemetry, not solver state, like the checkpoint format says.
        c2 = init_carry(np.asarray(y, np.float32))._replace(
            alpha=np.asarray(ck.alpha, np.float32),
            f=np.asarray(ck.f, np.float32),
            b_hi=np.float32(ck.b_hi), b_lo=np.float32(ck.b_lo),
            n_iter=np.int32(ck.n_iter))
        return jax.device_put(c2, device) if device is not None else c2

    ckpt = resume_state(config, n, d, gamma)
    if ckpt is not None:
        carry = carry._replace(
            alpha=np.asarray(ckpt.alpha), f=np.asarray(ckpt.f),
            b_hi=np.float32(ckpt.b_hi), b_lo=np.float32(ckpt.b_lo),
            n_iter=np.int32(ckpt.n_iter))
    if device is not None:
        carry = jax.device_put(carry, device)

    def build(q_now: int):
        cap = int(config.inner_iters) or max(32, q_now // 4)
        # Compile accounting per program: a growth swap builds (and
        # names) a fresh q so the trace shows WHICH regrow paid the
        # recompile (docs/OBSERVABILITY.md).
        r = compilewatch.instrument(
            _build_decomp_runner(float(config.c), kspec,
                                 float(config.epsilon), q_now, cap,
                                 config.matmul_precision.upper(),
                                 (float(config.weight_pos),
                                  float(config.weight_neg)),
                                 config.clip == "pairwise",
                                 pallas_inner=config.use_pallas == "on"),
            f"decomp-chunk/q={q_now}")
        return lambda cr, lim: r(cr, xd, yd, x2, np.int32(lim))

    poll_hook = (_make_growth_hook(config, n, q, build)
                 if config.grow_working_set else None)

    return host_training_loop(
        config, gamma, n, d, carry,
        step_chunk=build(q),
        carry_to_host=lambda cr: (np.asarray(cr.alpha), np.asarray(cr.f)),
        it0=int(ckpt.n_iter) if ckpt is not None else 0,
        poll_hook=poll_hook,
        carry_from_ckpt=carry_from_ckpt,
    )


# Growth-manager tuning. Check cadence: the SV count now rides the
# per-chunk packed-stats transfer (solver/driver.py), so a check costs
# NOTHING — it reads an already-fetched host integer. (It used to pull
# the whole alpha vector, an n-float D2H that under pipelined dispatch
# also blocked on the just-dispatched speculative chunk, serializing
# the poll loop against in-flight work.) The backoff cadence —
# GROW_CHECK_MIN to GROW_CHECK_MAX inner updates while nothing grows,
# resetting on growth — is kept to bound how often the manager
# re-evaluates growth between recompiles (rebuild hysteresis + log
# noise), not for poll economics. The fine initial cadence matters:
# the SV population ramps up EARLY in the solve, and a coarse first
# check leaves the run grinding undersized for a large fraction of its
# trajectory (measured at 8000x784 planted, cap 128 [cpu]: a fixed
# 16,384-update cadence landed adaptive-from-1024 at 28.4k updates —
# barely better than fixed-1024's 34.4k — because the first check
# fired halfway through; the backoff cadence lands it at 18.9k vs
# fixed-right-size's 13.0-13.7k). GROW_AT_OCCUPANCY triggers growth;
# GROW_TARGET_FACTOR is the measured q-selection rule's ~1.3x plus
# margin for SVs yet to appear; GROW_QUANTUM keeps new sizes
# MXU-tile-friendly.
GROW_CHECK_MIN = 2_048
GROW_CHECK_MAX = 16_384
GROW_AT_OCCUPANCY = 0.75
GROW_TARGET_FACTOR = 1.5
GROW_QUANTUM = 2_048
# Growth must self-bound by accelerator memory, unlike an explicit
# fixed q (the user's own choice): each outer round materializes
# (q, n)-shaped intermediates — the dots matmul output, plus the
# kernel-epilogue block when XLA does not fuse it into the rank-q
# reduction — so budget ~8 bytes per (q-row x example) and keep
# headroom for X and the vector state. 8 GB keeps q at the
# sweep-validated 2048 at covtype scale (n=500k: the (q, n) block is
# 4 GB at q=2048, 8 GB at 4096 — the r3 sweep's own sizing note) and
# is no constraint at the mnist shape (q_mem ~ 16k at n=60k).
GROW_HBM_BUDGET = 8 * 1024 ** 3


def _make_growth_hook(config: SVMConfig, n: int, q0: int, build):
    """poll_hook implementing adaptive working-set growth.

    The q-selection rule is measured but needs n_sv, which is unknown
    until the problem is solved: q below the SV count makes subsolves
    grind on stale global state (2.5-3x the updates at both scanned
    shapes), flat above ~1.3x n_sv. The manager starts at the
    configured q and, whenever the current SV count crosses
    GROW_AT_OCCUPANCY of the block, rebuilds the runner at
    GROW_TARGET_FACTOR x n_sv (rounded up to the GROW_QUANTUM tile
    multiple, at least doubled, capped by the validation bound and n).
    The carry is program-independent, so growth is purely a new
    compiled program — at most ~2 rebuilds per run by construction
    (each at least doubles q), each costing one compile (~tens of
    seconds on a tunneled TPU, vs the measured 2.5-3x update blowup of
    running undersized).

    The SV count is read from the poll's packed ChunkStats — already on
    the host, no device read. It describes the chunk just polled (one
    chunk stale under pipelined dispatch), exactly the freshness the
    old alpha-pull gave, without blocking on the in-flight speculative
    chunk."""
    from dpsvm_tpu.utils import watchdog

    q_mem = int(GROW_HBM_BUDGET // (8 * max(n, 1)))
    q_max = min(16_384, n - (n % 2), max(q_mem - (q_mem % 2), q0))
    state = {"q": q0, "last_check": 0, "cadence": GROW_CHECK_MIN}

    def hook(n_iter: int, carry, stats):
        if (state["q"] >= q_max
                or n_iter - state["last_check"] < state["cadence"]):
            return None
        state["last_check"] = n_iter
        n_sv = int(stats.n_sv)
        if n_sv <= GROW_AT_OCCUPANCY * state["q"]:
            state["cadence"] = min(2 * state["cadence"], GROW_CHECK_MAX)
            return None
        state["cadence"] = GROW_CHECK_MIN
        target = int(np.ceil(GROW_TARGET_FACTOR * n_sv / GROW_QUANTUM)
                     * GROW_QUANTUM)
        new_q = min(q_max, max(2 * state["q"], target))
        new_q -= new_q % 2
        if new_q <= state["q"]:
            return None
        if config.verbose:
            print(f"[grow] n_sv={n_sv} at q={state['q']} "
                  f"(occupancy {n_sv / state['q']:.2f}) -> q={new_q}",
                  file=sys.stderr, flush=True)
        state["q"] = new_q
        # The rebuild pays a fresh XLA compile; give the stall watchdog
        # a fresh window so a healthy compile is never killed as a
        # stall (same discipline as the shrinking manager's rebuilds).
        watchdog.pet()
        step = build(new_q)
        watchdog.pet()
        return step

    return hook

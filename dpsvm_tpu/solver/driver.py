"""Shared host-side training driver.

Both solvers (single-device, distributed) expose the same contract: a
compiled chunk runner that advances the carry until convergence or an
iteration limit, entirely on device. This module owns everything around
it — the polling loop, convergence bookkeeping, progress logging,
checkpointing, profiler tracing and NaN-debug toggles — so the behavior
is identical across execution modes.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Optional

import jax
import numpy as np

from dpsvm_tpu.config import SVMConfig, TrainResult
from dpsvm_tpu.utils.checkpoint import (SolverCheckpoint, load_checkpoint,
                                        maybe_checkpoint)
from dpsvm_tpu.utils.logging import log_progress


def resume_state(config: SVMConfig, n: int, d: int, gamma: float
                 ) -> Optional[SolverCheckpoint]:
    """Load + validate the resume checkpoint if one is configured."""
    if not config.resume_from:
        return None
    ckpt = load_checkpoint(config.resume_from)
    ckpt.validate_against(n, d, config, gamma)
    return ckpt


@contextlib.contextmanager
def _debug_nans(enabled: bool):
    if not enabled:
        yield
        return
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def host_training_loop(
    config: SVMConfig,
    gamma: float,
    n: int,
    d: int,
    carry,
    step_chunk: Callable,                      # (carry, limit:int) -> carry
    carry_to_host: Callable,                   # carry -> (alpha, f) np arrays
    carry_iter: Callable = lambda c: int(c.n_iter),
    carry_gap: Callable = lambda c: (float(c.b_lo), float(c.b_hi)),
) -> TrainResult:
    """Run chunks until convergence / max_iter; return the TrainResult."""
    eps = float(config.epsilon)
    last_saved = carry_iter(carry)

    profile = (jax.profiler.trace(config.profile_dir)
               if config.profile_dir else contextlib.nullcontext())

    t0 = time.perf_counter()
    with profile, _debug_nans(config.debug_nans):
        while True:
            limit = min(carry_iter(carry) + config.chunk_iters,
                        config.max_iter)
            carry = step_chunk(carry, limit)
            n_iter = carry_iter(carry)
            b_lo, b_hi = carry_gap(carry)
            converged = not (b_lo > b_hi + 2.0 * eps)
            done = converged or n_iter >= config.max_iter

            log_progress(config, n_iter, b_lo, b_hi, final=done)

            def make() -> SolverCheckpoint:
                alpha, f = carry_to_host(carry)
                return SolverCheckpoint(
                    alpha=alpha, f=f, n_iter=n_iter, b_lo=b_lo, b_hi=b_hi,
                    c=float(config.c), gamma=gamma,
                    epsilon=float(config.epsilon), n=n, d=d,
                    weight_pos=float(config.weight_pos),
                    weight_neg=float(config.weight_neg),
                    kernel=config.kernel, coef0=float(config.coef0),
                    degree=int(config.degree))

            last_saved = maybe_checkpoint(config, last_saved, n_iter, make)
            if done:
                break

    alpha, _ = carry_to_host(carry)
    return TrainResult(
        alpha=alpha,
        b=(b_lo + b_hi) / 2.0,           # svmTrainMain.cpp:329
        n_iter=n_iter,
        converged=converged,
        b_lo=b_lo,
        b_hi=b_hi,
        train_seconds=time.perf_counter() - t0,
        gamma=gamma,
        n_sv=int(np.sum(alpha > 0)),
        kernel=config.kernel,
        coef0=float(config.coef0),
        degree=int(config.degree),
    )

"""Shared host-side training driver.

Both solvers (single-device, distributed) expose the same contract: a
compiled chunk runner that advances the carry until convergence or an
iteration limit, entirely on device. This module owns everything around
it — the polling loop, convergence bookkeeping, progress logging,
checkpointing, profiler tracing, run telemetry (docs/OBSERVABILITY.md)
and NaN-debug toggles — so the behavior is identical across execution
modes.

Poll economics (measured on the v5e tunnel, benchmarks/
profile_train_path.py): a blocking device->host scalar read costs
~100 ms of round-trip latency, so the round-2 loop — three separate
``int()``/``float()`` reads per chunk — spent ~10 s of a 15 s training
run waiting on polls. Two fixes live here:

* **packed stats**: every poll scalar — n_iter, b_lo, b_hi, plus the
  telemetry counters (SV count, cache hits/misses, decomposition
  rounds) — is packed into ONE (7,) device array INSIDE each solver's
  compiled chunk runner (``pack_stats`` is traced into the same
  program, returned as a second output) and fetched with a single
  transfer per chunk. No auxiliary jitted gather exists — a separate
  tiny program would pay its own ~0.5-3 s per-process first-compile on
  the tunneled TPU — and tracing a run (``SVMConfig.trace_out``) adds
  ZERO device->host transfers because everything a chunk record needs
  already rides this one array;
* **pipelined dispatch**: the next chunk is dispatched BEFORE the
  previous chunk's stats are read. The device-side ``lax.while_loop``
  checks convergence every iteration, so a speculative chunk dispatched
  after the converged one is a no-op (its cond fails immediately) — the
  poll latency and the dispatch gap both overlap real compute, and the
  device never idles between chunks. Disabled while checkpointing
  (the checkpoint must read the carry at the polled iteration, and the
  donated carry has already been handed to the speculative chunk).
"""

from __future__ import annotations

import contextlib
import math
import os
import signal
import sys
import time
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dpsvm_tpu.config import SVMConfig, TrainResult
from dpsvm_tpu.observability import compilewatch
from dpsvm_tpu.observability import metrics as metricslib
from dpsvm_tpu.observability import profiler as profilerlib
from dpsvm_tpu.observability.device import memory_snapshot
from dpsvm_tpu.resilience import elastic, faultinject, hostgroup, preempt
from dpsvm_tpu.resilience.health import (DesyncError, DivergenceError,
                                         HealthMonitor)
from dpsvm_tpu.utils import watchdog
from dpsvm_tpu.utils.checkpoint import (CheckpointCorruptError,
                                        CheckpointError, SolverCheckpoint,
                                        checkpoint_candidates,
                                        load_checkpoint, maybe_checkpoint,
                                        newest_intact_checkpoint,
                                        save_checkpoint)
from dpsvm_tpu.utils.logging import log_progress
from dpsvm_tpu.utils.timing import PhaseTimer

# Lifecycle facts that become known BEFORE the run trace exists — a
# resume that skipped corrupt rotation slots, a supervisor retry — queue
# here and are drained into the trace right after the manifest
# (begin_trace). Process-local, consumed per trace.
_PENDING_TRACE_EVENTS: list = []


def queue_trace_event(event: str, **extra) -> None:
    _PENDING_TRACE_EVENTS.append((event, extra))


def drain_queued_events(trace) -> None:
    """Mid-run lifecycle facts queued by subsystems with no trace
    handle — the streaming data layer's ``quarantine`` events fire
    inside a chunk dispatch — land in the trace at the next poll
    boundary, the same queue-then-drain pattern the compilewatch log
    uses. Draining with tracing off discards them, so one run's events
    can never leak into the next run's trace."""
    if not _PENDING_TRACE_EVENTS:
        return
    pending, _PENDING_TRACE_EVENTS[:] = _PENDING_TRACE_EVENTS[:], []
    if trace is None:
        return
    for event, extra in pending:
        trace.event(event, **extra)


def resume_state(config: SVMConfig, n: int, d: int, gamma: float,
                 shards: int = 1) -> Optional[SolverCheckpoint]:
    """Load + validate the resume checkpoint if one is configured.

    A corrupt ``resume_from`` (truncated, bit-flipped — anything
    ``load_checkpoint`` rejects) falls back to the newest intact
    rotation slot (``state.1.npz``, …), logging what was skipped and
    queueing a ``rollback`` trace event for the run. Only when EVERY
    slot is unreadable does the error propagate; an intact checkpoint
    for the wrong problem/config always raises (that is permanent, not
    transient).

    ``shards`` is the current run's mesh size. A checkpoint recorded
    under a DIFFERENT mesh is NOT a mismatch — it is the elastic
    re-shard-on-load path (docs/DISTRIBUTED.md "Elastic training"):
    the state is the global unpadded (alpha, f), the trainers' pad-
    and-shard protocol re-slices it for the new device count, and the
    run records a ``reshard`` trace event naming both meshes."""
    if not config.resume_from:
        return None
    skipped = []
    last_err: Optional[CheckpointError] = None
    for path in checkpoint_candidates(config.resume_from):
        try:
            ckpt = load_checkpoint(path)
        except CheckpointCorruptError as e:
            print(f"WARNING: {e}; trying older rotation slot",
                  file=sys.stderr, flush=True)
            skipped.append(path)
            last_err = e
            continue
        ckpt.validate_against(n, d, config, gamma, shards=shards)
        if skipped:
            queue_trace_event("rollback", n_iter=ckpt.n_iter,
                              reason="corrupt checkpoint on resume",
                              checkpoint=path, skipped=skipped)
            print(f"WARNING: resuming from rotation slot {path} "
                  f"(skipped corrupt: {skipped})",
                  file=sys.stderr, flush=True)
        if ckpt.needs_reshard(shards):
            queue_trace_event("reshard", n_iter=ckpt.n_iter,
                              from_shards=int(ckpt.shards),
                              to_shards=int(shards), checkpoint=path)
            print(f"RESHARD: checkpoint {path} was saved on a "
                  f"{ckpt.mesh_desc()}; resuming on {shards} — "
                  f"re-slicing the global state onto the new mesh",
                  file=sys.stderr, flush=True)
        return ckpt
    raise CheckpointError(
        f"no intact checkpoint to resume: {config.resume_from} and "
        f"every rotation slot failed ({skipped})") from last_err


@contextlib.contextmanager
def _debug_nans(enabled: bool):
    if not enabled:
        yield
        return
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


# The one packed-stats layout every chunk runner emits and every poll
# reads: [n_iter, b_lo bits, b_hi bits, n_sv, cache_hits, cache_misses,
# rounds], all i32 (floats as exact bit patterns).
STATS_WIDTH = 7


class ChunkStats(NamedTuple):
    """Host-side view of one packed-stats read (docs/OBSERVABILITY.md
    "Counter semantics"). ``shard_probes`` is the per-shard probe block
    ((P, 3) i32: n_iter + the gap bounds as bit patterns) the SPMD
    runners append to the same transfer — None on single-device
    paths (resilience/elastic.py)."""
    n_iter: int
    b_lo: float
    b_hi: float
    n_sv: int = 0
    cache_hits: int = 0
    cache_misses: int = 0
    rounds: int = 0
    shard_probes: Optional[object] = None


def pack_stats(n_iter, b_lo, b_hi, n_sv=None, cache_hits=None,
               cache_misses=None, rounds=None):
    """Poll scalars + telemetry counters as one (7,) i32 array — one
    D2H transfer instead of several blocking scalar reads. The floats
    ride as bit patterns so every field is exact (an f32 lane would
    corrupt n_iter above 2^24 and stall the max_iter exit check —
    reference covtype budget is 3e6 and nothing validates an upper
    bound). Called INSIDE each solver's compiled chunk runner, so no
    auxiliary XLA program exists to pay the per-program first-compile
    overhead. Counter arguments default to 0 so paths without a cache
    (or without decomposition rounds) pack the same shape."""
    bits = jax.lax.bitcast_convert_type(jnp.stack([b_lo, b_hi]), jnp.int32)

    def lane(v):
        return jnp.reshape(jnp.asarray(0 if v is None else v,
                                       jnp.int32), (1,))

    return jnp.concatenate([jnp.reshape(n_iter, (1,)), bits,
                            lane(n_sv), lane(cache_hits),
                            lane(cache_misses), lane(rounds)])


def read_stats(stats) -> ChunkStats:
    """Block until the chunk's packed stats land, then unpack. Tolerates
    the legacy (3,) layout (counters read as 0) so older callers and
    recorded arrays stay readable. The deterministic NaN fault
    (resilience/faultinject.py) poisons the result HERE — the one point
    every consumer (driver loop, benchmarks) reads device state."""
    if getattr(stats, "is_fully_addressable", True):
        s = np.asarray(stats)   # blocks until the chunk's stats land
    else:
        # Cross-process mesh (multi-host): the per-shard probe tail is
        # sharded over processes, so the packed array is not fully
        # addressable here — assemble it with the same multihost-safe
        # gather the final (alpha, f) read-back uses. Every host polls
        # at every chunk, so the collective is symmetric.
        from dpsvm_tpu.parallel.mesh import to_host
        s = to_host(stats)
    watchdog.pet()
    b = s[1:3].view(np.float32)
    extra = [int(v) for v in s[3:STATS_WIDTH]]
    extra += [0] * (4 - len(extra))
    # The SPMD runners append per-shard probe lanes after the seven
    # replicated ones — same array, same single transfer
    # (resilience/elastic.py "shard probes").
    probes = None
    if len(s) > STATS_WIDTH:
        probes = np.asarray(s[STATS_WIDTH:], np.int32).reshape(
            -1, elastic.PROBE_WIDTH)
    st = ChunkStats(int(s[0]), float(b[0]), float(b[1]), *extra,
                    shard_probes=probes)
    plan = faultinject.current()
    if plan is not None:
        st = plan.poison_stats(st)
    return st


def _read_stats(stats) -> tuple:
    """Legacy 3-tuple read, kept for callers that only poll
    convergence (benchmarks, older tests)."""
    s = read_stats(stats)
    return s.n_iter, s.b_lo, s.b_hi


def _finite_converged(b_lo: float, b_hi: float, eps: float) -> bool:
    """The driver's convergence verdict: gap closed AND finite."""
    return (math.isfinite(b_lo) and math.isfinite(b_hi)
            and not (b_lo > b_hi + 2.0 * eps))


def device_sv_count(alpha):
    """count(alpha > 0) as i32, traced into the chunk program (padding
    rows hold alpha == 0 and never count)."""
    return jnp.sum(alpha > 0, dtype=jnp.int32)


def trace_env() -> dict:
    """Backend facts for the trace manifest (the backend is already up
    by the time any solver runs, so this is a dictionary read)."""
    try:
        devs = jax.devices()
        return {"backend": devs[0].platform,
                "device_kind": getattr(devs[0], "device_kind", None),
                "device_count": len(devs)}
    except Exception:
        return {"backend": None, "device_kind": None,
                "device_count": None}


def begin_trace(config: SVMConfig, n: int, d: int, gamma: float,
                solver: str, it0: int = 0):
    """RunTrace for this run, or None when tracing is off. Shared with
    the shrinking manager (solver/shrink.py) so every producer writes
    the one schema. Drains the pending-event queue (resume fallbacks,
    supervisor retries) right after the manifest; a subprocess-mode
    retry announces itself via ``DPSVM_RETRY_ATTEMPT``
    (resilience/supervisor.py)."""
    pending, _PENDING_TRACE_EVENTS[:] = _PENDING_TRACE_EVENTS[:], []
    if not getattr(config, "trace_out", None):
        return None
    from dpsvm_tpu.telemetry import RunTrace
    trace = RunTrace(config.trace_out, config=config, n=n, d=d,
                     gamma=gamma, solver=solver, it0=it0, env=trace_env())
    attempt = os.environ.get("DPSVM_RETRY_ATTEMPT", "").strip()
    if attempt.isdigit():
        trace.event("retry", n_iter=it0, attempt=int(attempt))
    # A post-host-loss attempt announces the reformation the same way
    # (resilience/hostgroup.py sets the markers): the dead host first,
    # then the group change — so one trace tells the recovery story
    # even though each attempt is a separate process writing a fresh
    # file.
    lost = os.environ.get("DPSVM_HOST_LOST", "").strip()
    if lost.isdigit():
        trace.event("host_lost", n_iter=it0, host_id=int(lost))
    rf = os.environ.get("DPSVM_REFORM_FROM", "").strip()
    rt = os.environ.get("DPSVM_REFORM_TO", "").strip()
    if rf.isdigit() and rt.isdigit():
        trace.event("reform", n_iter=it0, from_hosts=int(rf),
                    to_hosts=int(rt))
    for event, extra in pending:
        trace.event(event, **extra)
    return trace


def drain_compiles(trace, n_iter: int = 0, metrics=None) -> list:
    """Flush pending compile observations (observability/compilewatch)
    into ``trace`` as ``compile`` records and, when given, the metric
    registry feeder (``metrics.TrainingMetrics``). Draining with both
    off discards them, so one run's compiles can never leak into the
    next run's trace. Called at poll boundaries by every trace producer
    (this driver, the shrinking manager, the bench harnesses). Returns
    the drained observations (the watch hook reads the newest
    program's FLOPs estimate from them)."""
    drained = []
    for rec in compilewatch.drain():
        drained.append(rec)
        if trace is not None:
            trace.compile(program=rec["program"],
                          seconds=rec["seconds"],
                          signature=rec.get("signature"),
                          flops=rec.get("flops"),
                          bytes=rec.get("bytes"), n_iter=n_iter)
        if metrics is not None:
            metrics.on_compile(rec)
    return drained


def host_training_loop(
    config: SVMConfig,
    gamma: float,
    n: int,
    d: int,
    carry,
    step_chunk: Callable,           # (carry, limit:int) -> (carry, stats)
    carry_to_host: Callable,        # carry -> (alpha, f) np arrays
    it0: int = 0,                   # carry's entry iteration (0 or resume)
    poll_hook: Optional[Callable] = None,
    carry_from_ckpt: Optional[Callable] = None,
    shards: int = 1,                # mesh size (dist paths; 1 = single)
) -> TrainResult:
    """Run chunks until convergence / max_iter; return the TrainResult.

    ``poll_hook(n_iter, carry, stats) -> Optional[new_step_chunk]``:
    called at each poll while the run is not done; a non-None return
    replaces ``step_chunk`` for subsequent dispatches (the decomposition
    growth manager swaps in a larger-q program this way — legal because
    the carry layout is program-independent). ``stats`` is the poll's
    ChunkStats, so a hook that needs the SV count reads it for free
    instead of pulling alpha (which, pipelined, would block on the
    just-dispatched speculative chunk). In pipelined mode one
    already-dispatched speculative chunk still runs under the old
    program; its math is the same, only its block size is.

    With ``config.trace_out`` set, every poll appends a chunk record to
    the run trace (manifest/chunk/summary schema: utils/trace.py) —
    all of it read from the ONE packed-stats transfer above.

    Resilience (docs/ROBUSTNESS.md) — every solver path gets it here:

    * a SIGTERM/SIGINT during the loop (resilience/preempt.trap) is
      deferred to the next poll boundary, where the loop snapshots a
      final checkpoint, emits a ``preempt`` trace event and raises
      ``PreemptedError`` (CLI exit 75, the supervisor's resume cue).
      Pipelined dispatch STAYS pipelined: only when a signal is
      actually pending does the loop read the in-flight speculative
      chunk's stats, which both sequentializes that one poll and makes
      the snapshot consistent with the carry it describes;
    * every poll's stats feed a HealthMonitor (resilience/health.py) —
      non-finite gap, stagnation, SV collapse. Policy
      ``config.on_divergence``: raise / rollback / ignore. ``rollback``
      restores the newest intact checkpoint through ``carry_from_ckpt``
      (a solver-provided callback rebuilding a device carry from a
      SolverCheckpoint; paths that omit it degrade rollback to raise)
      and continues with a halved ``chunk_iters``;
    * deterministic faults (resilience/faultinject.py) fire at their
      configured poll/iteration, so all of the above runs in CI on CPU.

    Elastic extensions (``shards > 1`` — resilience/elastic.py,
    docs/DISTRIBUTED.md "Elastic training"): the per-shard probe block
    riding the same packed-stats transfer feeds (a) cross-shard desync
    detection — disagreement on replicated-by-construction values
    emits a ``desync`` trace event and rides the same ``on_divergence``
    policy (raise -> ``DesyncError``, rollback -> checkpoint restore);
    (b) per-shard heartbeat ages on every chunk record plus the stall
    watchdog's dist verdict; (c) the kill-shard drill
    (``DPSVM_FAULT_DIST_KILL_SHARD``) raising ``ShardLostError`` — the
    transient signal ``elastic.run_elastic`` answers by resuming on
    the surviving mesh. Checkpoints record the save-time mesh and
    per-shard CRCs.
    """
    eps = float(config.epsilon)
    chunk = config.chunk_iters
    # Pipelining changes WHEN the carry is read, not what is computed:
    # with checkpointing on, fall back to the strictly-sequential order
    # so maybe_checkpoint sees the carry at the polled iteration.
    pipeline = config.checkpoint_every == 0
    last_saved = it0

    from dpsvm_tpu.telemetry import SOLVER_NAMES
    trace = begin_trace(config, n, d, gamma,
                        SOLVER_NAMES.get(type(carry).__name__,
                                         type(carry).__name__), it0)
    monitor = HealthMonitor(policy=config.on_divergence,
                            window=config.health_window)
    # Elastic instruments for the SPMD paths (no-ops at shards == 1):
    # desync detection + heartbeats over the per-shard probe block.
    desync = elastic.DesyncDetector()
    heartbeats = (elastic.ShardHeartbeats(shards) if shards > 1
                  else None)
    elastic.register_heartbeats(heartbeats)
    faults = faultinject.current()
    # Auto-windowed jax.profiler capture (observability/profiler.py):
    # the session starts/stops the device trace at poll boundaries and
    # its annotation hook wraps every PhaseTimer phase in a
    # TraceAnnotation span of the same name, so the XLA timeline and
    # the trace's phase_counts share one vocabulary.
    session = (profilerlib.ProfileSession(
        config.profile_dir,
        solver=SOLVER_NAMES.get(type(carry).__name__,
                                type(carry).__name__))
        if config.profile_dir else None)
    # Host-loop accounting, not device time: "dispatch" buckets the
    # (async) enqueue calls, "poll" the blocking stats reads — device
    # execution overlaps both in pipelined mode. The buckets ride every
    # chunk record and the trace summary.
    timer = PhaseTimer(annotate=session.annotation
                       if session is not None else None)
    if session is not None:
        session.attach_timer(timer)
    # Live metrics surface (observability/metrics.py): the process
    # registry is fed from the SAME packed-stats reads the trace rides
    # — host dict arithmetic only, zero extra D2H transfers (pinned in
    # tests/test_metrics.py). Exporters are opt-in: the read-only HTTP
    # sidecar (--metrics-port) and the per-poll text snapshot file
    # (--metrics-out); both torn down in the finally block.
    train_metrics = metricslib.TrainingMetrics(
        solver=SOLVER_NAMES.get(type(carry).__name__,
                                type(carry).__name__), n=n, d=d)
    # Continuous watch + black-box flight recorder
    # (observability/slo.py + blackbox.py, docs/OBSERVABILITY.md
    # "Watch & alerts"): armed by --watch-rules / --bundle-dir. The
    # watchtower evaluates the training rules against the SAME
    # host-side facts every poll already holds (packed stats, compile
    # counters, heartbeat ages) and the flight recorder tees off the
    # trace feed — a watched run performs ZERO additional
    # device->host transfers, pinned in tests/test_watch.py.
    watcher = None
    flight = None
    incidents = None
    watch_peaks = None
    watch_prev = None           # (n_iter, t) for the it/s fact
    watch_flops = None          # newest chunk program's per-iter FLOPs
    if config.bundle_dir or config.watch_rules:
        from dpsvm_tpu.observability import blackbox, roofline, slo
        env = trace_env()
        watcher = slo.Watchtower(
            slo.load_rules(config.watch_rules, default="training"))
        incidents = metricslib.incidents_counter(train_metrics.registry)
        watch_peaks = roofline.peaks_for(env.get("device_kind"))
        flight = blackbox.FlightRecorder(blackbox.make_manifest(
            solver=SOLVER_NAMES.get(type(carry).__name__,
                                    type(carry).__name__),
            n=n, d=d, gamma=gamma,
            config={"kernel": config.kernel,
                    "coef0": float(config.coef0),
                    "degree": int(config.degree),
                    "shards": int(shards)},
            env=env))
        trace = blackbox.TeeTrace(trace, flight)
        if config.bundle_dir:
            blackbox.arm_emergency(flight, config.bundle_dir,
                                   train_metrics.registry)

    def watch_incident(rule: str, severity: str, window: str,
                       reason: str, n_iter: int) -> None:
        """One firing -> incident counter + metrics snapshot + bundle
        + `incident` trace event (the trace here is the TeeTrace, so
        the flight ring carries the alert history the bundle dumps)."""
        from dpsvm_tpu.observability import blackbox
        incidents.inc()
        flight.snapshot_metrics(train_metrics.registry)
        if not config.bundle_dir:
            return
        path = blackbox.dump_bundle(
            config.bundle_dir, recorder=flight, rule=rule,
            severity=severity, window=window, reason=reason,
            registry=train_metrics.registry,
            extra={"source": "training", "n_iter": int(n_iter)})
        if path and trace is not None:
            trace.event("incident", n_iter=n_iter, rule=rule,
                        window=window, severity=severity, bundle=path)
    exporting = (config.metrics_port is not None
                 or bool(config.metrics_out))
    sidecar = None
    if config.metrics_port is not None:
        sidecar = metricslib.MetricsServer(train_metrics.registry,
                                           port=config.metrics_port)
        print(f"metrics: http://127.0.0.1:{sidecar.port}/metricsz"
              "?format=prometheus (read-only, down at run end)",
              file=sys.stderr, flush=True)

    t0 = time.perf_counter()
    prev_polled = it0
    # Setup (data gen, H2D, host norms) is done once we get here; give
    # the stall watchdog a fresh window for the first chunk's compile.
    watchdog.pet()

    def snapshot(n_iter: int, b_lo: float, b_hi: float) -> SolverCheckpoint:
        # Closure over the loop's CURRENT carry (the cell, not a copy).
        # sys.modules, not an import: a process that never loaded
        # parallel.multihost is single-host by construction, and
        # importing it here would cycle through dpsvm_tpu.parallel.
        mh = sys.modules.get("dpsvm_tpu.parallel.multihost")
        alpha, f = carry_to_host(carry)
        return SolverCheckpoint(
            alpha=alpha, f=f, n_iter=n_iter, b_lo=b_lo, b_hi=b_hi,
            c=float(config.c), gamma=gamma,
            epsilon=float(config.epsilon), n=n, d=d,
            weight_pos=float(config.weight_pos),
            weight_neg=float(config.weight_neg),
            kernel=config.kernel, coef0=float(config.coef0),
            degree=int(config.degree),
            shards=int(shards),     # shard-aware manifest + per-shard
                                    # CRCs (utils/checkpoint.py)
            host_count=mh.host_count() if mh is not None else 1,
            host_id=mh.host_id() if mh is not None else 0)

    try:
        with _debug_nans(config.debug_nans), preempt.trap():
            limit = min(it0 + chunk, config.max_iter)
            with timer.phase("dispatch"):
                carry, stats = step_chunk(carry, limit)
            while True:
                if pipeline:
                    # Dispatch the next chunk before the poll blocks;
                    # the speculative chunk is free when this one
                    # converged (the device cond exits instantly), and
                    # the poll's round-trip latency overlaps its
                    # execution.
                    limit = min(limit + chunk, config.max_iter)
                    with timer.phase("dispatch"):
                        carry, next_stats = step_chunk(carry, limit)

                with timer.phase("poll"):
                    st = read_stats(stats)
                if faults is not None and faults.note_poll():
                    preempt.simulate(signal.SIGTERM)
                n_iter, b_lo, b_hi = st.n_iter, st.b_lo, st.b_hi
                if faults is not None and shards > 1:
                    # Kill-shard drill: the injected "host died" —
                    # raised WITHOUT a snapshot, like the real thing
                    # (recovery starts from the newest periodic
                    # checkpoint, on the surviving mesh).
                    lost = faults.dist_kill_now()
                    if lost:
                        if trace is not None:
                            trace.event("shard_lost", n_iter=n_iter,
                                        shard=lost - 1, shards=shards)
                        raise elastic.ShardLostError(lost - 1, shards,
                                                     n_iter)
                if faults is not None and faults.host_kill_now():
                    # Host-loss drill: a REAL host death — no cleanup,
                    # no snapshot, no atexit. The group supervisor
                    # (resilience/hostgroup.py) must notice the exit /
                    # heartbeat silence from OUTSIDE and reform.
                    os.kill(os.getpid(), signal.SIGKILL)
                # Liveness for that supervisor and `dpsvm doctor`:
                # no-op outside a host group.
                hostgroup.note_poll_heartbeat(n_iter)
                shard_ages = (heartbeats.note_poll(st.shard_probes)
                              if heartbeats is not None else None)
                # Device/compiler facts for this poll, all host-side
                # reads (docs/OBSERVABILITY.md): compile observations
                # queued by the instrumented chunk runners land as
                # trace records before the chunk they delayed, and the
                # allocator watermark is a dictionary read — still
                # ZERO extra device->host transfers.
                drained = drain_compiles(trace, n_iter,
                                         metrics=train_metrics)
                for rec in drained:
                    if rec.get("flops") is not None:
                        watch_flops = float(rec["flops"])
                drain_queued_events(trace)
                hbm = (memory_snapshot()
                       if trace is not None or exporting else None)
                if session is not None:
                    session.note_poll()
                # Finite-aware: every NaN comparison is False, so a
                # plain `not (b_lo > ...)` would declare a NaN gap
                # CONVERGED and return garbage marked success. A
                # non-finite gap is never converged — it loops into the
                # HealthMonitor below instead.
                converged = _finite_converged(b_lo, b_hi, eps)
                done = converged or n_iter >= config.max_iter
                if (not done and config.wall_budget_s
                        and time.perf_counter() - t0
                        > config.wall_budget_s):
                    # Time budget exhausted: stop dispatching. In
                    # pipelined mode a speculative chunk is already in
                    # flight; read its stats so the returned
                    # (n_iter, alpha) describe the same state — the
                    # extra chunk is counted, not silently run.
                    if pipeline:
                        with timer.phase("poll"):
                            st = read_stats(next_stats)
                        n_iter, b_lo, b_hi = st.n_iter, st.b_lo, st.b_hi
                        converged = _finite_converged(b_lo, b_hi, eps)
                    done = True
                    if trace is not None:
                        trace.event("wall_budget", n_iter=n_iter)

                if not done and preempt.pending() is not None:
                    # Preemption snapshot. A completed run ignores the
                    # signal (its artifacts are about to be written —
                    # that IS beating the preemption deadline).
                    if pipeline:
                        # Sequential fallback only NOW: the carry is the
                        # in-flight speculative chunk's output, so its
                        # stats — not the ones just polled — describe
                        # the state being snapshotted.
                        with timer.phase("poll"):
                            st = read_stats(next_stats)
                        n_iter, b_lo, b_hi = st.n_iter, st.b_lo, st.b_hi
                    signum = preempt.pending()
                    saved_to = None
                    if config.checkpoint_path:
                        try:
                            with timer.phase("checkpoint"):
                                save_checkpoint(
                                    config.checkpoint_path,
                                    snapshot(n_iter, b_lo, b_hi),
                                    keep=config.checkpoint_keep)
                            saved_to = config.checkpoint_path
                        except (OSError, CheckpointError) as e:
                            print(f"WARNING: preemption snapshot failed "
                                  f"({e}); previous checkpoint kept",
                                  file=sys.stderr, flush=True)
                    log_progress(config, n_iter, b_lo, b_hi, final=True,
                                 prev_iter=prev_polled)
                    if trace is not None:
                        trace.event("preempt", n_iter=n_iter,
                                    signal=int(signum),
                                    checkpoint=saved_to)
                    raise preempt.PreemptedError(signum, n_iter,
                                                 saved_to)

                log_progress(config, n_iter, b_lo, b_hi, final=done,
                             prev_iter=prev_polled)
                prev_polled = n_iter
                if trace is not None:
                    trace.chunk(n_iter=n_iter, b_lo=b_lo, b_hi=b_hi,
                                n_sv=st.n_sv, cache_hits=st.cache_hits,
                                cache_misses=st.cache_misses,
                                rounds=st.rounds,
                                phases=dict(timer.seconds),
                                phase_counts=dict(timer.counts),
                                hbm=hbm,
                                **({"shard_ages": shard_ages}
                                   if shard_ages is not None else {}))
                # Same values, second consumer: the live metric
                # registry (every argument is already host-side).
                train_metrics.on_poll(
                    n_iter=n_iter, b_lo=b_lo, b_hi=b_hi, n_sv=st.n_sv,
                    cache_hits=st.cache_hits,
                    cache_misses=st.cache_misses,
                    phases=timer.seconds, phase_counts=timer.counts,
                    hbm=hbm, shard_ages=shard_ages)
                if config.metrics_out:
                    metricslib.write_snapshot(train_metrics.registry,
                                              config.metrics_out)

                if watcher is not None:
                    # One watch sample per poll — every fact is
                    # already host-side (the packed-stats read, the
                    # compile counters, the heartbeat ages): zero
                    # extra device transfers.
                    w_now = time.perf_counter()
                    gap = b_lo - b_hi
                    sample = {"n_iter": float(n_iter),
                              "n_sv": float(st.n_sv),
                              "gap": (gap if math.isfinite(gap)
                                      else float("inf"))}
                    comp, comp_s = train_metrics.compile_totals()
                    sample["compiles"] = comp
                    sample["compile_seconds"] = comp_s
                    if shard_ages is not None and len(shard_ages):
                        sample["heartbeat_age"] = float(
                            max(shard_ages))
                    if (watch_peaks is not None
                            and watch_flops is not None
                            and watch_prev is not None
                            and w_now > watch_prev[1]
                            and n_iter > watch_prev[0]):
                        ips = ((n_iter - watch_prev[0])
                               / (w_now - watch_prev[1]))
                        sample["roofline_fraction"] = (
                            watch_flops * ips
                            / watch_peaks["peak_flops"])
                    watch_prev = (int(n_iter), w_now)
                    for w_tr in watcher.observe(sample, t=w_now):
                        if trace is not None:
                            trace.event("alert", n_iter=n_iter,
                                        rule=w_tr["rule"],
                                        window=w_tr["window"],
                                        severity=w_tr["severity"],
                                        state=w_tr["state"],
                                        reason=w_tr["reason"])
                        if w_tr["state"] == "firing":
                            watch_incident(w_tr["rule"],
                                           w_tr["severity"],
                                           w_tr["window"],
                                           w_tr["reason"], n_iter)

                # Divergence guards — BEFORE maybe_checkpoint, so a sick
                # state is never saved over a good rotation slot. The
                # cross-shard desync check rides the same policy: a
                # desynchronized mesh IS a divergent run, and rollback
                # (restore a known-good global state everywhere) is
                # exactly its recovery.
                reason = None if done else monitor.check(
                    n_iter=n_iter, b_lo=b_lo, b_hi=b_hi, n_sv=st.n_sv)
                ev_kind = "divergence"
                if reason is None and not done:
                    reason = desync.check(st.shard_probes)
                    if reason is not None:
                        ev_kind = "desync"
                if reason is not None:
                    if flight is not None:
                        # The health guards are the oldest alert rules
                        # of all: a tripped guard is an incident, so
                        # the black box dumps BEFORE the policy acts
                        # (a raise must still leave its artifact).
                        watch_incident(
                            f"health-{ev_kind}", "page",
                            f"health_window={config.health_window}",
                            reason, n_iter)
                    policy = monitor.policy
                    if policy == "rollback" and (
                            carry_from_ckpt is None
                            or not config.checkpoint_path
                            or monitor.exhausted):
                        why = ("rollback budget exhausted"
                               if monitor.exhausted else
                               "this solver path has no rollback hook"
                               if carry_from_ckpt is None else
                               "no checkpoint_path configured")
                        print(f"WARNING: divergence policy 'rollback' "
                              f"unavailable ({why}); raising",
                              file=sys.stderr, flush=True)
                        policy = "raise"
                    # `desync` events carry the mesh size (the schema
                    # validator checks it — observability/schema.py).
                    ev_extra = ({"shards": int(shards)}
                                if ev_kind == "desync" else {})
                    if policy == "ignore":
                        print(f"WARNING: {reason} at iter {n_iter} "
                              "(on_divergence='ignore')",
                              file=sys.stderr, flush=True)
                        if trace is not None:
                            trace.event(ev_kind, n_iter=n_iter,
                                        reason=reason, action="ignore",
                                        **ev_extra)
                    elif policy == "raise":
                        if trace is not None:
                            trace.event(ev_kind, n_iter=n_iter,
                                        reason=reason, action="raise",
                                        **ev_extra)
                        err = (DesyncError if ev_kind == "desync"
                               else DivergenceError)
                        raise err(reason, n_iter)
                    else:
                        best, skipped = newest_intact_checkpoint(
                            config.checkpoint_path)
                        if best is None:
                            raise DivergenceError(
                                f"{reason}; rollback found no intact "
                                f"checkpoint (skipped {skipped})", n_iter)
                        if trace is not None and ev_kind == "desync":
                            trace.event(ev_kind, n_iter=n_iter,
                                        reason=reason,
                                        action="rollback", **ev_extra)
                        ck = load_checkpoint(best)
                        ck.validate_against(n, d, config, gamma,
                                            shards=shards)
                        carry = carry_from_ckpt(ck)
                        chunk = max(chunk // 2, 1)
                        monitor.note_rollback(ck.n_iter)
                        desync.reset()   # restored state re-earns trust
                        print(f"WARNING: {reason} at iter {n_iter}; "
                              f"rolled back to {best} (iter "
                              f"{ck.n_iter}), chunk_iters now {chunk}",
                              file=sys.stderr, flush=True)
                        if trace is not None:
                            trace.event("rollback", n_iter=ck.n_iter,
                                        reason=reason, checkpoint=best,
                                        skipped=skipped,
                                        chunk_iters=chunk)
                        n_iter = prev_polled = ck.n_iter
                        last_saved = ck.n_iter
                        # Dispatch the restored carry and re-enter the
                        # poll loop. Works in BOTH loop modes: pipelined
                        # (checkpoint_every=0 with a resume/preempt
                        # snapshot on disk) re-enters at the top, which
                        # dispatches the next speculative chunk from
                        # this limit; the in-flight chunk of the sick
                        # carry is simply never read.
                        limit = min(n_iter + chunk, config.max_iter)
                        with timer.phase("dispatch"):
                            carry, stats = step_chunk(carry, limit)
                        continue

                if poll_hook is not None and not done:
                    with timer.phase("hook"):
                        replacement = poll_hook(n_iter, carry, st)
                    if replacement is not None:
                        step_chunk = replacement
                        if trace is not None:
                            trace.event("program_swap", n_iter=n_iter)

                def make() -> SolverCheckpoint:
                    return snapshot(n_iter, b_lo, b_hi)

                with timer.phase("checkpoint"):
                    saved = maybe_checkpoint(config, last_saved, n_iter,
                                             make)
                if trace is not None and saved != last_saved:
                    trace.event("checkpoint", n_iter=n_iter)
                last_saved = saved
                if done:
                    break
                if pipeline:
                    stats = next_stats
                else:
                    limit = min(n_iter + chunk, config.max_iter)
                    with timer.phase("dispatch"):
                        carry, stats = step_chunk(carry, limit)
        # In pipelined mode `carry` is the speculative chunk dispatched
        # after the final poll; it was a no-op (converged => cond false
        # on entry; max_iter => limit == n_iter), so its state equals
        # the final state.
        alpha, _ = carry_to_host(carry)
        # OWN the returned duals. np.asarray of a CPU-backend jax array
        # is a ZERO-COPY view of the device buffer; once `carry` is
        # garbage-collected the buffer is recycled by whatever compiles
        # or runs next, and result.alpha silently mutates after the
        # fact (observed as garbage ±1e11 coefficients in a model built
        # from a returned result — the long-standing "bench flake").
        # One n-vector memcpy at run end buys a result that cannot be
        # corrupted by anything that happens later.
        alpha = np.array(alpha, np.float32, copy=True)
        result = TrainResult(
            alpha=alpha,
            b=(b_lo + b_hi) / 2.0,           # svmTrainMain.cpp:329
            n_iter=n_iter,
            converged=converged,
            b_lo=b_lo,
            b_hi=b_hi,
            train_seconds=time.perf_counter() - t0,
            gamma=gamma,
            n_sv=int(np.sum(alpha > 0)),
            kernel=config.kernel,
            coef0=float(config.coef0),
            degree=int(config.degree),
        )
        train_metrics.on_done(converged=result.converged,
                              n_iter=result.n_iter)
        if trace is not None:
            drain_compiles(trace, result.n_iter, metrics=train_metrics)
            drain_queued_events(trace)
            trace.summary(converged=result.converged,
                          n_iter=result.n_iter, b=result.b,
                          b_lo=result.b_lo, b_hi=result.b_hi,
                          n_sv=result.n_sv,
                          train_seconds=result.train_seconds,
                          cache_hits=st.cache_hits,
                          cache_misses=st.cache_misses,
                          rounds=st.rounds,
                          phases=dict(timer.seconds),
                          phase_counts=dict(timer.counts))
        return result
    finally:
        # Leftover compile observations (error exits, untraced runs)
        # must not leak into the next run's trace.
        if flight is not None:
            from dpsvm_tpu.observability import blackbox
            blackbox.disarm_emergency(flight)
        elastic.register_heartbeats(None)
        drain_compiles(trace if trace is not None and not trace.closed
                       else None, metrics=train_metrics)
        drain_queued_events(trace if trace is not None
                            and not trace.closed else None)
        if trace is not None:
            trace.close()
        # Exporter teardown: final snapshot for the scrape-less file,
        # sidecar listener down, profiler window closed + sidecar
        # summary written — none of these may raise over a dying run.
        if config.metrics_out:
            metricslib.write_snapshot(train_metrics.registry,
                                      config.metrics_out)
        if sidecar is not None:
            sidecar.close()
        if session is not None:
            session.close()

"""Shared host-side training driver.

Both solvers (single-device, distributed) expose the same contract: a
compiled chunk runner that advances the carry until convergence or an
iteration limit, entirely on device. This module owns everything around
it — the polling loop, convergence bookkeeping, progress logging,
checkpointing, profiler tracing and NaN-debug toggles — so the behavior
is identical across execution modes.

Poll economics (measured on the v5e tunnel, benchmarks/
profile_train_path.py): a blocking device->host scalar read costs
~100 ms of round-trip latency, so the round-2 loop — three separate
``int()``/``float()`` reads per chunk — spent ~10 s of a 15 s training
run waiting on polls. Two fixes live here:

* **packed stats**: the three poll scalars (n_iter, b_lo, b_hi) are
  packed into ONE (3,) device array INSIDE each solver's compiled chunk
  runner (``pack_stats`` is traced into the same program, returned as a
  second output) and fetched with a single transfer per chunk. No
  auxiliary jitted gather exists — a separate tiny program would pay
  its own ~0.5-3 s per-process first-compile on the tunneled TPU;
* **pipelined dispatch**: the next chunk is dispatched BEFORE the
  previous chunk's stats are read. The device-side ``lax.while_loop``
  checks convergence every iteration, so a speculative chunk dispatched
  after the converged one is a no-op (its cond fails immediately) — the
  poll latency and the dispatch gap both overlap real compute, and the
  device never idles between chunks. Disabled while checkpointing
  (the checkpoint must read the carry at the polled iteration, and the
  donated carry has already been handed to the speculative chunk).
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dpsvm_tpu.config import SVMConfig, TrainResult
from dpsvm_tpu.utils import watchdog
from dpsvm_tpu.utils.checkpoint import (SolverCheckpoint, load_checkpoint,
                                        maybe_checkpoint)
from dpsvm_tpu.utils.logging import log_progress


def resume_state(config: SVMConfig, n: int, d: int, gamma: float
                 ) -> Optional[SolverCheckpoint]:
    """Load + validate the resume checkpoint if one is configured."""
    if not config.resume_from:
        return None
    ckpt = load_checkpoint(config.resume_from)
    ckpt.validate_against(n, d, config, gamma)
    return ckpt


@contextlib.contextmanager
def _debug_nans(enabled: bool):
    if not enabled:
        yield
        return
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def pack_stats(n_iter, b_lo, b_hi):
    """(n_iter, b_lo, b_hi) as one (3,) i32 array — one D2H transfer
    instead of three blocking scalar reads. The floats ride as bit
    patterns so every field is exact (an f32 lane would corrupt n_iter
    above 2^24 and stall the max_iter exit check — reference covtype
    budget is 3e6 and nothing validates an upper bound). Called INSIDE
    each solver's compiled chunk runner, so no auxiliary XLA program
    exists to pay the per-program first-compile overhead."""
    bits = jax.lax.bitcast_convert_type(jnp.stack([b_lo, b_hi]), jnp.int32)
    return jnp.concatenate([jnp.reshape(n_iter, (1,)), bits])


def _read_stats(stats) -> tuple:
    s = np.asarray(stats)       # blocks until the chunk's stats land
    watchdog.pet()
    b = s[1:].view(np.float32)
    return int(s[0]), float(b[0]), float(b[1])


def host_training_loop(
    config: SVMConfig,
    gamma: float,
    n: int,
    d: int,
    carry,
    step_chunk: Callable,           # (carry, limit:int) -> (carry, stats)
    carry_to_host: Callable,        # carry -> (alpha, f) np arrays
    it0: int = 0,                   # carry's entry iteration (0 or resume)
    poll_hook: Optional[Callable] = None,
) -> TrainResult:
    """Run chunks until convergence / max_iter; return the TrainResult.

    ``poll_hook(n_iter, carry) -> Optional[new_step_chunk]``: called at
    each poll while the run is not done; a non-None return replaces
    ``step_chunk`` for subsequent dispatches (the decomposition growth
    manager swaps in a larger-q program this way — legal because the
    carry layout is program-independent). In pipelined mode one
    already-dispatched speculative chunk still runs under the old
    program; its math is the same, only its block size is."""
    eps = float(config.epsilon)
    chunk = config.chunk_iters
    # Pipelining changes WHEN the carry is read, not what is computed:
    # with checkpointing on, fall back to the strictly-sequential order
    # so maybe_checkpoint sees the carry at the polled iteration.
    pipeline = config.checkpoint_every == 0
    last_saved = it0

    profile = (jax.profiler.trace(config.profile_dir)
               if config.profile_dir else contextlib.nullcontext())

    t0 = time.perf_counter()
    prev_polled = it0
    # Setup (data gen, H2D, host norms) is done once we get here; give
    # the stall watchdog a fresh window for the first chunk's compile.
    watchdog.pet()
    with profile, _debug_nans(config.debug_nans):
        limit = min(it0 + chunk, config.max_iter)
        carry, stats = step_chunk(carry, limit)
        while True:
            if pipeline:
                # Dispatch the next chunk before the poll blocks; the
                # speculative chunk is free when this one converged
                # (the device cond exits instantly), and the poll's
                # round-trip latency overlaps its execution.
                limit = min(limit + chunk, config.max_iter)
                carry, next_stats = step_chunk(carry, limit)

            n_iter, b_lo, b_hi = _read_stats(stats)
            converged = not (b_lo > b_hi + 2.0 * eps)
            done = converged or n_iter >= config.max_iter
            if (not done and config.wall_budget_s
                    and time.perf_counter() - t0 > config.wall_budget_s):
                # Time budget exhausted: stop dispatching. In pipelined
                # mode a speculative chunk is already in flight; read its
                # stats so the returned (n_iter, alpha) describe the same
                # state — the extra chunk is counted, not silently run.
                if pipeline:
                    n_iter, b_lo, b_hi = _read_stats(next_stats)
                    converged = not (b_lo > b_hi + 2.0 * eps)
                done = True

            log_progress(config, n_iter, b_lo, b_hi, final=done,
                         prev_iter=prev_polled)
            prev_polled = n_iter

            if poll_hook is not None and not done:
                replacement = poll_hook(n_iter, carry)
                if replacement is not None:
                    step_chunk = replacement

            def make() -> SolverCheckpoint:
                alpha, f = carry_to_host(carry)
                return SolverCheckpoint(
                    alpha=alpha, f=f, n_iter=n_iter, b_lo=b_lo, b_hi=b_hi,
                    c=float(config.c), gamma=gamma,
                    epsilon=float(config.epsilon), n=n, d=d,
                    weight_pos=float(config.weight_pos),
                    weight_neg=float(config.weight_neg),
                    kernel=config.kernel, coef0=float(config.coef0),
                    degree=int(config.degree))

            last_saved = maybe_checkpoint(config, last_saved, n_iter, make)
            if done:
                break
            if pipeline:
                stats = next_stats
            else:
                limit = min(n_iter + chunk, config.max_iter)
                carry, stats = step_chunk(carry, limit)
    # In pipelined mode `carry` is the speculative chunk dispatched after
    # the final poll; it was a no-op (converged => cond false on entry;
    # max_iter => limit == n_iter), so its state equals the final state.
    alpha, _ = carry_to_host(carry)
    return TrainResult(
        alpha=alpha,
        b=(b_lo + b_hi) / 2.0,           # svmTrainMain.cpp:329
        n_iter=n_iter,
        converged=converged,
        b_lo=b_lo,
        b_hi=b_hi,
        train_seconds=time.perf_counter() - t0,
        gamma=gamma,
        n_sv=int(np.sum(alpha > 0)),
        kernel=config.kernel,
        coef0=float(config.coef0),
        degree=int(config.degree),
    )

"""Shared host-side training driver.

Both solvers (single-device, distributed) expose the same contract: a
compiled chunk runner that advances the carry until convergence or an
iteration limit, entirely on device. This module owns everything around
it — the polling loop, convergence bookkeeping, progress logging,
checkpointing, profiler tracing, run telemetry (docs/OBSERVABILITY.md)
and NaN-debug toggles — so the behavior is identical across execution
modes.

Poll economics (measured on the v5e tunnel, benchmarks/
profile_train_path.py): a blocking device->host scalar read costs
~100 ms of round-trip latency, so the round-2 loop — three separate
``int()``/``float()`` reads per chunk — spent ~10 s of a 15 s training
run waiting on polls. Two fixes live here:

* **packed stats**: every poll scalar — n_iter, b_lo, b_hi, plus the
  telemetry counters (SV count, cache hits/misses, decomposition
  rounds) — is packed into ONE (7,) device array INSIDE each solver's
  compiled chunk runner (``pack_stats`` is traced into the same
  program, returned as a second output) and fetched with a single
  transfer per chunk. No auxiliary jitted gather exists — a separate
  tiny program would pay its own ~0.5-3 s per-process first-compile on
  the tunneled TPU — and tracing a run (``SVMConfig.trace_out``) adds
  ZERO device->host transfers because everything a chunk record needs
  already rides this one array;
* **pipelined dispatch**: the next chunk is dispatched BEFORE the
  previous chunk's stats are read. The device-side ``lax.while_loop``
  checks convergence every iteration, so a speculative chunk dispatched
  after the converged one is a no-op (its cond fails immediately) — the
  poll latency and the dispatch gap both overlap real compute, and the
  device never idles between chunks. Disabled while checkpointing
  (the checkpoint must read the carry at the polled iteration, and the
  donated carry has already been handed to the speculative chunk).
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dpsvm_tpu.config import SVMConfig, TrainResult
from dpsvm_tpu.utils import watchdog
from dpsvm_tpu.utils.checkpoint import (SolverCheckpoint, load_checkpoint,
                                        maybe_checkpoint)
from dpsvm_tpu.utils.logging import log_progress
from dpsvm_tpu.utils.timing import PhaseTimer


def resume_state(config: SVMConfig, n: int, d: int, gamma: float
                 ) -> Optional[SolverCheckpoint]:
    """Load + validate the resume checkpoint if one is configured."""
    if not config.resume_from:
        return None
    ckpt = load_checkpoint(config.resume_from)
    ckpt.validate_against(n, d, config, gamma)
    return ckpt


@contextlib.contextmanager
def _debug_nans(enabled: bool):
    if not enabled:
        yield
        return
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


# The one packed-stats layout every chunk runner emits and every poll
# reads: [n_iter, b_lo bits, b_hi bits, n_sv, cache_hits, cache_misses,
# rounds], all i32 (floats as exact bit patterns).
STATS_WIDTH = 7


class ChunkStats(NamedTuple):
    """Host-side view of one packed-stats read (docs/OBSERVABILITY.md
    "Counter semantics")."""
    n_iter: int
    b_lo: float
    b_hi: float
    n_sv: int = 0
    cache_hits: int = 0
    cache_misses: int = 0
    rounds: int = 0


def pack_stats(n_iter, b_lo, b_hi, n_sv=None, cache_hits=None,
               cache_misses=None, rounds=None):
    """Poll scalars + telemetry counters as one (7,) i32 array — one
    D2H transfer instead of several blocking scalar reads. The floats
    ride as bit patterns so every field is exact (an f32 lane would
    corrupt n_iter above 2^24 and stall the max_iter exit check —
    reference covtype budget is 3e6 and nothing validates an upper
    bound). Called INSIDE each solver's compiled chunk runner, so no
    auxiliary XLA program exists to pay the per-program first-compile
    overhead. Counter arguments default to 0 so paths without a cache
    (or without decomposition rounds) pack the same shape."""
    bits = jax.lax.bitcast_convert_type(jnp.stack([b_lo, b_hi]), jnp.int32)

    def lane(v):
        return jnp.reshape(jnp.asarray(0 if v is None else v,
                                       jnp.int32), (1,))

    return jnp.concatenate([jnp.reshape(n_iter, (1,)), bits,
                            lane(n_sv), lane(cache_hits),
                            lane(cache_misses), lane(rounds)])


def read_stats(stats) -> ChunkStats:
    """Block until the chunk's packed stats land, then unpack. Tolerates
    the legacy (3,) layout (counters read as 0) so older callers and
    recorded arrays stay readable."""
    s = np.asarray(stats)       # blocks until the chunk's stats land
    watchdog.pet()
    b = s[1:3].view(np.float32)
    extra = [int(v) for v in s[3:STATS_WIDTH]]
    extra += [0] * (4 - len(extra))
    return ChunkStats(int(s[0]), float(b[0]), float(b[1]), *extra)


def _read_stats(stats) -> tuple:
    """Legacy 3-tuple read, kept for callers that only poll
    convergence (benchmarks, older tests)."""
    s = read_stats(stats)
    return s.n_iter, s.b_lo, s.b_hi


def device_sv_count(alpha):
    """count(alpha > 0) as i32, traced into the chunk program (padding
    rows hold alpha == 0 and never count)."""
    return jnp.sum(alpha > 0, dtype=jnp.int32)


def trace_env() -> dict:
    """Backend facts for the trace manifest (the backend is already up
    by the time any solver runs, so this is a dictionary read)."""
    try:
        devs = jax.devices()
        return {"backend": devs[0].platform,
                "device_kind": getattr(devs[0], "device_kind", None),
                "device_count": len(devs)}
    except Exception:
        return {"backend": None, "device_kind": None,
                "device_count": None}


def begin_trace(config: SVMConfig, n: int, d: int, gamma: float,
                solver: str, it0: int = 0):
    """RunTrace for this run, or None when tracing is off. Shared with
    the shrinking manager (solver/shrink.py) so every producer writes
    the one schema."""
    if not getattr(config, "trace_out", None):
        return None
    from dpsvm_tpu.telemetry import RunTrace
    return RunTrace(config.trace_out, config=config, n=n, d=d,
                    gamma=gamma, solver=solver, it0=it0, env=trace_env())


def host_training_loop(
    config: SVMConfig,
    gamma: float,
    n: int,
    d: int,
    carry,
    step_chunk: Callable,           # (carry, limit:int) -> (carry, stats)
    carry_to_host: Callable,        # carry -> (alpha, f) np arrays
    it0: int = 0,                   # carry's entry iteration (0 or resume)
    poll_hook: Optional[Callable] = None,
) -> TrainResult:
    """Run chunks until convergence / max_iter; return the TrainResult.

    ``poll_hook(n_iter, carry, stats) -> Optional[new_step_chunk]``:
    called at each poll while the run is not done; a non-None return
    replaces ``step_chunk`` for subsequent dispatches (the decomposition
    growth manager swaps in a larger-q program this way — legal because
    the carry layout is program-independent). ``stats`` is the poll's
    ChunkStats, so a hook that needs the SV count reads it for free
    instead of pulling alpha (which, pipelined, would block on the
    just-dispatched speculative chunk). In pipelined mode one
    already-dispatched speculative chunk still runs under the old
    program; its math is the same, only its block size is.

    With ``config.trace_out`` set, every poll appends a chunk record to
    the run trace (manifest/chunk/summary schema: utils/trace.py) —
    all of it read from the ONE packed-stats transfer above.
    """
    eps = float(config.epsilon)
    chunk = config.chunk_iters
    # Pipelining changes WHEN the carry is read, not what is computed:
    # with checkpointing on, fall back to the strictly-sequential order
    # so maybe_checkpoint sees the carry at the polled iteration.
    pipeline = config.checkpoint_every == 0
    last_saved = it0

    from dpsvm_tpu.telemetry import SOLVER_NAMES
    trace = begin_trace(config, n, d, gamma,
                        SOLVER_NAMES.get(type(carry).__name__,
                                         type(carry).__name__), it0)
    # Host-loop accounting, not device time: "dispatch" buckets the
    # (async) enqueue calls, "poll" the blocking stats reads — device
    # execution overlaps both in pipelined mode. The buckets ride every
    # chunk record and the trace summary.
    timer = PhaseTimer()

    profile = (jax.profiler.trace(config.profile_dir)
               if config.profile_dir else contextlib.nullcontext())

    t0 = time.perf_counter()
    prev_polled = it0
    # Setup (data gen, H2D, host norms) is done once we get here; give
    # the stall watchdog a fresh window for the first chunk's compile.
    watchdog.pet()
    try:
        with profile, _debug_nans(config.debug_nans):
            limit = min(it0 + chunk, config.max_iter)
            with timer.phase("dispatch"):
                carry, stats = step_chunk(carry, limit)
            while True:
                if pipeline:
                    # Dispatch the next chunk before the poll blocks;
                    # the speculative chunk is free when this one
                    # converged (the device cond exits instantly), and
                    # the poll's round-trip latency overlaps its
                    # execution.
                    limit = min(limit + chunk, config.max_iter)
                    with timer.phase("dispatch"):
                        carry, next_stats = step_chunk(carry, limit)

                with timer.phase("poll"):
                    st = read_stats(stats)
                n_iter, b_lo, b_hi = st.n_iter, st.b_lo, st.b_hi
                converged = not (b_lo > b_hi + 2.0 * eps)
                done = converged or n_iter >= config.max_iter
                if (not done and config.wall_budget_s
                        and time.perf_counter() - t0
                        > config.wall_budget_s):
                    # Time budget exhausted: stop dispatching. In
                    # pipelined mode a speculative chunk is already in
                    # flight; read its stats so the returned
                    # (n_iter, alpha) describe the same state — the
                    # extra chunk is counted, not silently run.
                    if pipeline:
                        with timer.phase("poll"):
                            st = read_stats(next_stats)
                        n_iter, b_lo, b_hi = st.n_iter, st.b_lo, st.b_hi
                        converged = not (b_lo > b_hi + 2.0 * eps)
                    done = True
                    if trace is not None:
                        trace.event("wall_budget", n_iter=n_iter)

                log_progress(config, n_iter, b_lo, b_hi, final=done,
                             prev_iter=prev_polled)
                prev_polled = n_iter
                if trace is not None:
                    trace.chunk(n_iter=n_iter, b_lo=b_lo, b_hi=b_hi,
                                n_sv=st.n_sv, cache_hits=st.cache_hits,
                                cache_misses=st.cache_misses,
                                rounds=st.rounds,
                                phases=dict(timer.seconds))

                if poll_hook is not None and not done:
                    with timer.phase("hook"):
                        replacement = poll_hook(n_iter, carry, st)
                    if replacement is not None:
                        step_chunk = replacement
                        if trace is not None:
                            trace.event("program_swap", n_iter=n_iter)

                def make() -> SolverCheckpoint:
                    alpha, f = carry_to_host(carry)
                    return SolverCheckpoint(
                        alpha=alpha, f=f, n_iter=n_iter, b_lo=b_lo,
                        b_hi=b_hi,
                        c=float(config.c), gamma=gamma,
                        epsilon=float(config.epsilon), n=n, d=d,
                        weight_pos=float(config.weight_pos),
                        weight_neg=float(config.weight_neg),
                        kernel=config.kernel, coef0=float(config.coef0),
                        degree=int(config.degree))

                with timer.phase("checkpoint"):
                    saved = maybe_checkpoint(config, last_saved, n_iter,
                                             make)
                if trace is not None and saved != last_saved:
                    trace.event("checkpoint", n_iter=n_iter)
                last_saved = saved
                if done:
                    break
                if pipeline:
                    stats = next_stats
                else:
                    limit = min(n_iter + chunk, config.max_iter)
                    with timer.phase("dispatch"):
                        carry, stats = step_chunk(carry, limit)
        # In pipelined mode `carry` is the speculative chunk dispatched
        # after the final poll; it was a no-op (converged => cond false
        # on entry; max_iter => limit == n_iter), so its state equals
        # the final state.
        alpha, _ = carry_to_host(carry)
        result = TrainResult(
            alpha=alpha,
            b=(b_lo + b_hi) / 2.0,           # svmTrainMain.cpp:329
            n_iter=n_iter,
            converged=converged,
            b_lo=b_lo,
            b_hi=b_hi,
            train_seconds=time.perf_counter() - t0,
            gamma=gamma,
            n_sv=int(np.sum(alpha > 0)),
            kernel=config.kernel,
            coef0=float(config.coef0),
            degree=int(config.degree),
        )
        if trace is not None:
            trace.summary(converged=result.converged,
                          n_iter=result.n_iter, b=result.b,
                          b_lo=result.b_lo, b_hi=result.b_hi,
                          n_sv=result.n_sv,
                          train_seconds=result.train_seconds,
                          cache_hits=st.cache_hits,
                          cache_misses=st.cache_misses,
                          rounds=st.rounds,
                          phases=dict(timer.seconds))
        return result
    finally:
        if trace is not None:
            trace.close()

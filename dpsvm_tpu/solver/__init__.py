"""SMO solvers: NumPy oracle, single-device XLA, distributed shard_map."""

from dpsvm_tpu.solver.oracle import smo_reference
from dpsvm_tpu.solver.smo import train_single_device

__all__ = ["smo_reference", "train_single_device"]

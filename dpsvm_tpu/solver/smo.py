"""Single-device SMO solver: the whole loop inside one XLA program.

The reference pays a host round-trip every iteration — Thrust kernel
launches, a 16-byte device->host read, an MPI Allgather, three host-CBLAS
RBF evaluations and four scalar device accesses per iteration
(``svmTrainMain.cpp:235-310``, SURVEY CS-1). Tens of thousands of
iterations each eat kernel-launch + network latency. Here the entire
modified-SMO iteration is the body of a ``lax.while_loop`` compiled once
under ``jit``:

* working-set selection: masked argmin/argmax (ops.selection);
* both kernel rows: one (2, d) @ (d, n) MXU matmul + fused exp epilogue
  (ops.kernels), or the HBM row cache when enabled (ops.rowcache);
* eta / alpha update / clip: replicated scalar math, exact reference
  semantics (``svmTrainMain.cpp:282-295`` — see oracle.py docstring);
* f update: fused elementwise AXPY on the two kernel rows.

The host only re-enters every ``chunk_iters`` iterations to poll
convergence and log — the carry is donated, so alpha/f update in place.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dpsvm_tpu.config import SENTINEL, SVMConfig, TrainResult
from dpsvm_tpu.ops.kernels import (KernelSpec, host_row_stats,
                                   host_row_norms_sq,
                                   kdiag_from_norms, rows_from_dots)
from dpsvm_tpu.ops.rowcache import RowCache, cache_fetch_pair, cache_init
from dpsvm_tpu.ops.selection import (masked_extrema, masked_extrema_packed,
                                     masked_scores_and_masks)
from dpsvm_tpu.observability import compilewatch
from dpsvm_tpu.ops.update import alpha_pair_step
from dpsvm_tpu.solver.driver import (device_sv_count, host_training_loop,
                                     pack_stats, resume_state)


class SMOCarry(NamedTuple):
    alpha: jax.Array    # (n,) f32
    f: jax.Array        # (n,) f32 optimality/gradient vector
    b_hi: jax.Array     # () f32 from the latest selection
    b_lo: jax.Array     # () f32
    n_iter: jax.Array   # () i32
    cache: RowCache


def init_carry(y, cache_lines: int) -> SMOCarry:
    """alpha = 0, f = -y (svmTrain.cu:349,380); sentinels force the first
    iteration to run, preserving the reference's do-while shape.

    Built host-side in NumPy on purpose: every distinct tiny XLA program
    costs ~0.5-3 s of first-compile overhead per process on the tunneled
    TPU (measured, benchmarks/profile_train_path.py), and the jnp
    zeros/neg/full constructors here used to be 3-4 such programs. The
    NumPy pytree transfers to the device at the first runner call with
    zero compiles."""
    n = y.shape[0]
    y_np = np.asarray(y, np.float32)
    return SMOCarry(
        alpha=np.zeros((n,), np.float32),
        f=-y_np,
        b_hi=np.float32(-SENTINEL),
        b_lo=np.float32(SENTINEL),
        n_iter=np.int32(0),
        cache=cache_init(cache_lines, n),
    )


def smo_step(carry: SMOCarry, x: jax.Array, y: jax.Array, x2: jax.Array,
             c: float, kspec: KernelSpec, *, use_cache: bool = False,
             second_order: bool = False, weights=(1.0, 1.0),
             precision=lax.Precision.HIGHEST,
             packed_select: bool = False,
             pairwise_clip: bool = False,
             guard_eta: bool = False,
             nu_selection: bool = False,
             valid: Optional[jax.Array] = None) -> SMOCarry:
    """One modified-SMO iteration (select -> eta -> alpha -> f).

    ``second_order`` switches the lo-index choice to the LIBSVM WSS2 rule
    (Fan/Chen/Lin 2005): among violators j in I_low with f_j > b_hi,
    maximize (f_j - b_hi)^2 / a_j with a_j = K_ii + K_jj - 2 K(hi, j)
    (= 2 - 2 K(hi, j) for RBF — the literal kept on that path for bit
    parity). The stopping gap and the intercept still come from the max
    violator (b_lo), matching the reference's convergence rule
    (svmTrainMain.cpp:310,329).

    ``kspec`` statically selects the kernel family; "rbf" is the exact
    reference-parity path, the rest (linear/poly/sigmoid — LIBSVM -t)
    share every other line of the iteration.

    ``weights`` = (w_pos, w_neg) class-weights the box bound per example
    (C_i = C * w(y_i)); (1, 1) keeps the exact scalar reference path.

    ``valid`` (optional bool (n,)) masks padding rows out of every
    selection rule — the shrinking manager pads active subproblems to
    power-of-two capacities so re-shrink cycles reuse compiled programs
    (solver/shrink.py). None keeps the exact unmasked path.
    """
    alpha, f = carry.alpha, carry.f
    wp, wn = weights
    weighted = wp != 1.0 or wn != 1.0
    if weighted:
        # Per-example box bound, derived from y on the fly (XLA fuses
        # this into the mask computation).
        c_box = jnp.where(y > 0, jnp.float32(c * wp), jnp.float32(c * wn))
        c_of = lambda i: c_box[i]
    else:
        c_box = c
        c_of = lambda i: jnp.float32(c)

    if nu_selection:
        # LIBSVM Solver_NU (svm.cpp select_working_set of the NU
        # variant): two equality constraints (one per class) mean a
        # working pair must share its label, so the violating pair is
        # chosen per class and the class with the larger KKT gap wins.
        # The stopping quantity is max(gap_+, gap_-); it rides the
        # carry's (b_hi, b_lo) slots as (0, max_gap) so the shared
        # do-while cond `b_lo > b_hi + 2 eps` applies unchanged — the
        # nu wrappers (models/nusvm.py) derive the real intercept/rho
        # from the final state, not from these slots.
        f_up, f_low, _, _ = masked_scores_and_masks(alpha, y, f, c_box,
                                                    valid=valid)
        pos = y > 0
        fup_p = jnp.where(pos, f_up, jnp.float32(SENTINEL))
        flo_p = jnp.where(pos, f_low, jnp.float32(-SENTINEL))
        fup_m = jnp.where(pos, jnp.float32(SENTINEL), f_up)
        flo_m = jnp.where(pos, jnp.float32(-SENTINEL), f_low)
        ihp, ilp = jnp.argmin(fup_p), jnp.argmax(flo_p)
        ihm, ilm = jnp.argmin(fup_m), jnp.argmax(flo_m)
        gap_p = flo_p[ilp] - fup_p[ihp]
        gap_m = flo_m[ilm] - fup_m[ihm]
        use_p = gap_p >= gap_m
        i_hi = jnp.where(use_p, ihp, ihm)
        i_lo = jnp.where(use_p, ilp, ilm)
        b_hi_sel = jnp.where(use_p, fup_p[ihp], fup_m[ihm])
        b_lo_sel = jnp.where(use_p, flo_p[ilp], flo_m[ilm])
        if kspec.kind == "precomputed":
            k = jnp.stack([x[i_hi], x[i_lo]])   # gathered K rows
        else:
            rows = jnp.stack([x[i_hi], x[i_lo]])             # (2, d)
            dots = jnp.matmul(rows, x.T, precision=precision)  # (2, n)
            w2 = jnp.stack([x2[i_hi], x2[i_lo]])
            k = rows_from_dots(dots, w2, x2, kspec)
        b_hi = b_hi_sel                 # the alpha step's gradient pair
        b_lo = jnp.maximum(gap_p, gap_m)
        cache = carry.cache
    elif second_order:
        f_up, f_low, _, in_low = masked_scores_and_masks(alpha, y, f, c_box,
                                                         valid=valid)
        i_hi = jnp.argmin(f_up)
        b_hi = f_up[i_hi]
        b_lo = jnp.max(f_low)                       # stopping gap only
        if kspec.kind == "precomputed":
            k_hi = x[i_hi]                      # the gathered K row
        else:
            dots_hi = jnp.matmul(x[i_hi][None, :], x.T,
                                 precision=precision)          # (1, n)
            k_hi = rows_from_dots(dots_hi, x2[i_hi][None], x2, kspec)[0]
        bb = f_low - b_hi
        if kspec.is_rbf:
            a = jnp.maximum(2.0 - 2.0 * k_hi, 1e-12)
        else:
            kd = kdiag_from_norms(x2, kspec)
            a = jnp.maximum(kd[i_hi] + kd - 2.0 * k_hi, 1e-12)
        obj = jnp.where(in_low & (bb > 0), bb * bb / a, -1.0)
        i_lo = jnp.argmax(obj)
        if kspec.kind == "precomputed":
            k_lo = x[i_lo]
        else:
            dots_lo = jnp.matmul(x[i_lo][None, :], x.T,
                                 precision=precision)
            k_lo = rows_from_dots(dots_lo, x2[i_lo][None], x2,
                                  kspec)[0]
        k = jnp.stack([k_hi, k_lo])
        b_lo_sel = f_low[i_lo]                      # alpha step uses the
        cache = carry.cache                         # SELECTED violator
    else:
        select = masked_extrema_packed if packed_select else masked_extrema
        i_hi, b_hi, i_lo, b_lo = select(alpha, y, f, c_box, valid)
        b_lo_sel = b_lo

        cache = carry.cache
        if kspec.kind == "precomputed":
            # The fetch is a 2-row gather of K — nothing to cache,
            # nothing to recompute (config rejects cache_size > 0).
            k = jnp.stack([x[i_hi], x[i_lo]])
        else:
            if use_cache:
                dots, cache = cache_fetch_pair(
                    cache, i_hi, i_lo,
                    lambda: jnp.matmul(jnp.stack([x[i_hi], x[i_lo]]),
                                       x.T, precision=precision))
            else:
                rows = jnp.stack([x[i_hi], x[i_lo]])             # (2, d)
                dots = jnp.matmul(rows, x.T,
                                  precision=precision)           # (2, n)

            w2 = jnp.stack([x2[i_hi], x2[i_lo]])
            k = rows_from_dots(dots, w2, x2, kspec)              # (2, n)

    eta = k[0, i_hi] + k[1, i_lo] - 2.0 * k[0, i_lo]
    if second_order or guard_eta or nu_selection:
        # WSS2 steers toward small-eta pairs (the selection objective
        # divides by the clamped a_j), so clamp the update denominator
        # the same way LIBSVM does (TAU). ``guard_eta`` applies the same
        # clamp to first-order on f_init-seeded problems (SVR/one-class):
        # SVR stacks every row twice with opposite pseudo-labels
        # (models/svr.py), so a selected twin pair has eta exactly 0 and
        # the raw division would slam both alphas to box corners via inf.
        # The plain classification path keeps the reference's raw
        # division (svmTrainMain.cpp:289) for bit parity.
        eta = jnp.maximum(eta, 1e-12)

    y_hi, y_lo = y[i_hi], y[i_lo]
    a_hi, a_lo = alpha[i_hi], alpha[i_lo]
    a_hi_n, a_lo_n = alpha_pair_step(a_hi, a_lo, y_hi, y_lo, b_hi,
                                     b_lo_sel, eta, c_of(i_hi), c_of(i_lo),
                                     pairwise_clip)

    # Write order lo-then-hi mirrors train_step2 (svmTrain.cu:491-492) for
    # the i_hi == i_lo corner.
    alpha = alpha.at[i_lo].set(a_lo_n)
    alpha = alpha.at[i_hi].set(a_hi_n)
    f = f + (a_hi_n - a_hi) * y_hi * k[0] + (a_lo_n - a_lo) * y_lo * k[1]

    if nu_selection:
        # Stopping slots carry (0, max class gap), not the step's pair.
        b_hi = jnp.float32(0.0)
    return SMOCarry(alpha, f, b_hi, b_lo, carry.n_iter + 1, cache)


@functools.lru_cache(maxsize=32)
def _build_chunk_runner(c: float, kspec, epsilon: float,
                        use_cache: bool, precision_name: str,
                        second_order: bool = False,
                        weights=(1.0, 1.0),
                        packed_select: bool = False,
                        pairwise_clip: bool = False,
                        guard_eta: bool = False,
                        nu_selection: bool = False,
                        masked: bool = False):
    """Compiled chunk runner: run SMO iterations until convergence or the
    iteration limit, entirely on device. Cached per hyperparameter set;
    shapes specialize via jit.

    ``kspec`` is a KernelSpec, or a bare gamma float as RBF shorthand
    (the original call convention, kept for the benchmark harnesses).

    ``masked=True`` builds the padded-capacity variant used by the
    shrinking manager: ``run`` takes an extra dynamic ``n_valid`` i32
    before ``limit`` and masks rows >= n_valid out of selection. Kept a
    build-time flag so the headline unmasked path pays nothing for it.
    """
    precision = getattr(lax.Precision, precision_name)
    kspec = KernelSpec.coerce(kspec)

    def cond(carry: SMOCarry, limit):
        return (carry.b_lo > carry.b_hi + 2.0 * epsilon) & (carry.n_iter < limit)

    def body(s, x, y, x2, valid):
        return smo_step(s, x, y, x2, c, kspec,
                        use_cache=use_cache,
                        second_order=second_order,
                        weights=weights,
                        precision=precision,
                        packed_select=packed_select,
                        pairwise_clip=pairwise_clip,
                        guard_eta=guard_eta,
                        nu_selection=nu_selection,
                        valid=valid)

    # Poll stats packed inside the same program: the host reads one
    # (7,) array per chunk — convergence scalars plus the telemetry
    # counters (SV count, cache hits/misses) — instead of several
    # blocking scalars, and no auxiliary XLA program exists to pay
    # first-compile overhead (solver/driver.py "Poll economics").
    def stats(final: SMOCarry):
        return pack_stats(final.n_iter, final.b_lo, final.b_hi,
                          n_sv=device_sv_count(final.alpha),
                          cache_hits=final.cache.hits,
                          cache_misses=final.cache.misses)

    if masked:
        def run(carry: SMOCarry, x, y, x2, n_valid, limit):
            valid = jnp.arange(x.shape[0], dtype=jnp.int32) < n_valid
            final = lax.while_loop(
                lambda s: cond(s, limit),
                lambda s: body(s, x, y, x2, valid),
                carry)
            return final, stats(final)
    else:
        def run(carry: SMOCarry, x, y, x2, limit):
            final = lax.while_loop(
                lambda s: cond(s, limit),
                lambda s: body(s, x, y, x2, None),
                carry)
            return final, stats(final)

    return jax.jit(run, donate_argnums=(0,))


def train_single_device(x: np.ndarray, y: np.ndarray, config: SVMConfig,
                        device: Optional[jax.Device] = None,
                        f_init: Optional[np.ndarray] = None,
                        alpha_init: Optional[np.ndarray] = None,
                        guard_eta: bool = False,
                        nu_selection: bool = False) -> TrainResult:
    """Train on one device. Data arrives as host NumPy, leaves as NumPy.

    ``f_init`` / ``alpha_init`` override the classification
    initialization (f = -y, alpha = 0); the SVR and one-class wrappers
    use them to seed their duals (models/svr.py, models/oneclass.py —
    the caller is responsible for a consistent pair: f must equal the
    dual gradient at alpha). A checkpoint resume takes precedence (the
    saved state continues the identical trajectory).
    """
    config.validate()
    n, d = x.shape
    gamma = float(config.resolve_gamma(d))
    kspec = config.kernel_spec(d)
    use_cache = config.cache_size > 0

    xd = jax.device_put(jnp.asarray(x, jnp.float32), device)
    yd = jax.device_put(jnp.asarray(y, jnp.float32), device)
    x2 = jax.device_put(host_row_stats(x, kspec), device)
    carry = init_carry(np.asarray(y, np.float32), config.cache_size)
    if f_init is not None:
        carry = carry._replace(f=np.asarray(f_init, np.float32))
    if alpha_init is not None:
        carry = carry._replace(alpha=np.asarray(alpha_init, np.float32))

    def carry_from_ckpt(ck):
        # Shared by the initial resume and the driver's divergence
        # rollback (docs/ROBUSTNESS.md): a fresh carry from checkpoint
        # state, cache cold (the checkpoint holds only solver state,
        # like the reference's model file holds no cache).
        c2 = init_carry(np.asarray(y, np.float32),
                        config.cache_size)._replace(
            alpha=np.asarray(ck.alpha, np.float32),
            f=np.asarray(ck.f, np.float32),
            b_hi=np.float32(ck.b_hi), b_lo=np.float32(ck.b_lo),
            n_iter=np.int32(ck.n_iter))
        return jax.device_put(c2, device) if device is not None else c2

    ckpt = resume_state(config, n, d, gamma)
    if ckpt is not None:
        carry = carry._replace(
            alpha=np.asarray(ckpt.alpha), f=np.asarray(ckpt.f),
            b_hi=np.float32(ckpt.b_hi), b_lo=np.float32(ckpt.b_lo),
            n_iter=np.int32(ckpt.n_iter))
    if device is not None:
        carry = jax.device_put(carry, device)

    # Compile accounting (docs/OBSERVABILITY.md): the wrapper watches
    # the jit's tracing cache, so a warm program (lru_cached builder,
    # persistent compile cache) correctly records zero compiles.
    runner = compilewatch.instrument(
        _build_chunk_runner(float(config.c), kspec,
                            float(config.epsilon), use_cache,
                            config.matmul_precision.upper(),
                            config.selection == "second-order",
                            (float(config.weight_pos),
                             float(config.weight_neg)),
                            config.select_impl == "packed",
                            config.clip == "pairwise",
                            guard_eta=guard_eta,
                            nu_selection=nu_selection),
        "smo-chunk")

    return host_training_loop(
        config, gamma, n, d, carry,
        step_chunk=lambda c, lim: runner(c, xd, yd, x2, np.int32(lim)),
        carry_to_host=lambda c: (np.asarray(c.alpha), np.asarray(c.f)),
        it0=int(ckpt.n_iter) if ckpt is not None else 0,
        carry_from_ckpt=carry_from_ckpt,
    )

"""Shrinking (active-set) training: LIBSVM's -h heuristic, TPU-shaped.

LIBSVM shrinks the optimization to the rows that can still move: a
bound variable whose gradient says it will stay at its bound at the
optimum is removed from selection and gradient maintenance, and the
full problem is only revisited to validate convergence (svm.cpp's
be_shrunk / reconstruct_gradient). The reference has nothing like it —
its per-iteration cost is O(n_shard * d) forever.

XLA cannot reshape arrays inside a compiled loop, so shrinking here is a
HOST-level active-set manager around the existing compiled chunk
runners — the 2-violator program (solver/smo.py), the decomposition
program (solver/decomp.py), or their SPMD variants over the device mesh
(parallel/dist_smo.py, parallel/dist_decomp.py; ``config.shards``) —
all of which share the chunk contract:

  * train in chunks on the ACTIVE subproblem (x/y/x2/alpha/f compacted
    to the active rows — SMO on that subproblem is exact because
    inactive alphas are frozen and their contribution is baked into the
    active rows' f);
  * at each chunk poll, apply LIBSVM's rule to the pulled (alpha, f):
    an I_up-only row with f > b_lo, or an I_low-only row with f < b_hi,
    can no longer join a violating pair — shrink it. Compact only when
    the active set at least halves, so at most log2(n) XLA programs are
    ever compiled;
  * when the subproblem converges, scatter alpha back, reconstruct the
    inactive rows' f EXACTLY in one streamed MXU pass over the support
    vectors (f_i = sum_j alpha_j y_j K_ij - y_i; the active rows keep
    their incrementally-maintained f, exactly like LIBSVM's
    reconstruct_gradient), and re-check optimality on the FULL problem
    on the host. Converged => done; otherwise training continues
    unshrunk (and may shrink again).

The final model therefore satisfies the same stopping criterion as the
unshrunk path on the full problem — shrinking changes the trajectory,
never the convergence contract. Quality is held to the LibSVM parity
bar by tests/test_shrink.py.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dpsvm_tpu.config import SENTINEL, SVMConfig, TrainResult
from dpsvm_tpu.observability import compilewatch
from dpsvm_tpu.observability.device import memory_snapshot
from dpsvm_tpu.ops.kernels import KernelSpec, host_row_norms_sq
from dpsvm_tpu.ops.selection import iup_ilow_masks_np
from dpsvm_tpu.solver.driver import begin_trace, drain_compiles, read_stats
from dpsvm_tpu.utils import watchdog
from dpsvm_tpu.utils.logging import log_progress

# Ceiling on iterations between shrink-rule evaluations (each pulls
# alpha+f to the host); the cadence is min(n, this) per run — LIBSVM's
# is min(n, 1000), but a D2H pull is ~ms-scale on a tunneled
# accelerator where LIBSVM's is a pointer read.
SHRINK_CHECK_ITERS = 4096


def _bucket_cap(n_act: int, n: int, floor: int = 512) -> int:
    """Power-of-two program capacity for an active subproblem.

    Every distinct array size is its own XLA program, and on the
    tunneled TPU a program costs ~0.5-3 s of client compile plus ~3 s of
    server-side load per process (docs/PERF.md reconciliation table) —
    paid at every compaction and again at every re-shrink cycle that
    lands on a new exact size. Quantizing capacities to powers of two
    (capped at n, floored to keep tiny programs from churning) makes all
    cycles — and all runs at the same shape, via the persistent compile
    cache — share one program per bucket, at most log2(n) in total.
    Padding rows are masked out of every selection rule (the runners'
    ``masked=True`` variant), so the trajectory is identical to an
    exact-size subproblem's.
    """
    cap = floor
    while cap < n_act:
        cap *= 2
    return min(cap, n)


def _host_extrema(alpha, y, f, c_box):
    """(b_hi, b_lo) from host arrays — the full-problem optimality check
    at unshrink time, no device program needed. Membership comes from
    the ONE shared rule (ops/selection.iup_ilow_masks_np)."""
    in_up, in_low = iup_ilow_masks_np(alpha, y, c_box)
    b_hi = float(f[in_up].min()) if in_up.any() else np.inf
    b_lo = float(f[in_low].max()) if in_low.any() else -np.inf
    return b_hi, b_lo


def _shrinkable(alpha, y, f, c_box, b_hi, b_lo):
    """LIBSVM's be_shrunk on our f convention: a row that can no longer
    be either side of a violating pair (I_up-only with f >= b_lo can
    never beat the current max-violator as argmin side, and vice
    versa)."""
    in_up, in_low = iup_ilow_masks_np(alpha, y, c_box)
    up_only = in_up & ~in_low
    low_only = in_low & ~in_up
    return (up_only & (f > b_lo)) | (low_only & (f < b_hi))


def _reconstruct_inactive_f(x, y, alpha, f, alpha0, f0, active_mask,
                            spec: KernelSpec,
                            block: int = 8192) -> np.ndarray:
    """Exact f for the inactive rows (one streamed kernel pass); active
    rows keep their maintained values — LIBSVM's reconstruct_gradient
    split.

    Reconstructed RELATIVE to the run's initial state:
    f_i = f0_i + sum_j (alpha_j - alpha0_j) y_j K_ij. For plain
    classification (f0 = -y, alpha0 = 0) this is the textbook
    K(alpha*y) - y; for seeded duals (SVR's tube-offset f_init,
    one-class's K alpha0 seed — models/svr.py, models/oneclass.py) the
    absolute formula would silently rebuild the WRONG gradient and
    corrupt the model at unshrink (caught by
    tests/test_combinations.py::test_svr_with_shrinking)."""
    inactive = ~active_mask
    if not inactive.any():
        return f
    coef = ((alpha - alpha0) * y).astype(np.float32)
    sv = coef != 0.0
    xi = x[inactive]
    if not sv.any():
        kv = np.zeros(int(inactive.sum()), np.float32)
    else:
        kv = _stream_kv_against(xi, x[sv], coef[sv], spec, block)
    f = f.copy()
    f[inactive] = f0[inactive] + kv
    return f


def _stream_kv_against(x_rows: np.ndarray, x_sv: np.ndarray,
                       coef_sv: np.ndarray, spec: KernelSpec,
                       block: int) -> np.ndarray:
    """K(x_rows, x_sv) @ coef_sv in row blocks on device."""
    from dpsvm_tpu.ops.diagnostics import _block_kv
    from dpsvm_tpu.ops.kernels import row_norms_sq

    xs = jnp.asarray(x_sv)
    s2 = row_norms_sq(xs)
    cf = jnp.asarray(coef_sv)
    m = x_rows.shape[0]
    out = np.empty((m,), np.float32)
    for lo in range(0, m, block):
        hi = min(lo + block, m)
        xb = jnp.asarray(x_rows[lo:hi])
        out[lo:hi] = np.asarray(
            _block_kv(xb, row_norms_sq(xb), xs, s2, cf, spec))
    return out


def train_shrinking(x: np.ndarray, y: np.ndarray,
                    config: SVMConfig,
                    device: Optional[jax.Device] = None,
                    f_init: Optional[np.ndarray] = None,
                    alpha_init: Optional[np.ndarray] = None,
                    guard_eta: bool = False,
                    mesh=None) -> TrainResult:
    """Active-set training loop — single device or SPMD over the mesh
    (``config.shards``). Same NumPy-in/NumPy-out contract as the other
    solvers."""
    config.validate()
    t0 = time.perf_counter()
    n, d = x.shape
    gamma = float(config.resolve_gamma(d))
    kspec = config.kernel_spec(d)
    eps = float(config.epsilon)
    chunk = int(config.chunk_iters)

    x = np.ascontiguousarray(np.asarray(x, np.float32))
    y_np = np.asarray(y, np.float32)
    x2_np = np.asarray(host_row_norms_sq(x))
    c_box = np.broadcast_to(
        np.asarray(config.box_bound(y_np), np.float32), y_np.shape)

    alpha = (np.zeros(n, np.float32) if alpha_init is None
             else np.asarray(alpha_init, np.float32).copy())
    f = (-y_np.copy() if f_init is None
         else np.asarray(f_init, np.float32).copy())
    alpha0 = alpha.copy()       # the initial state anchors the exact
    f0 = f.copy()               # relative f reconstruction at unshrink

    decomp = config.working_set > 2
    dist = config.shards > 1
    min_active = 1
    q = 0
    if decomp:
        q = 2 * min(int(config.working_set) // 2, n)
        # The decomp runner's top_k needs q//2 <= len(active); never
        # compact below the block size (review finding: a few-SV
        # problem could otherwise shrink the active set under q and
        # crash the re-trace).
        min_active = q
    inner_cap = int(config.inner_iters) or max(32, q // 4)
    weights = (float(config.weight_pos), float(config.weight_neg))
    pairwise = config.clip == "pairwise"
    precision_name = config.matmul_precision.upper()

    if dist:
        from dpsvm_tpu.parallel.mesh import make_data_mesh, to_host
        if mesh is None:
            mesh = make_data_mesh(config.shards)
        p = mesh.devices.size
        min_active = max(min_active, p)
    else:
        xd_full = jax.device_put(jnp.asarray(x), device)

    # Compile accounting (docs/OBSERVABILITY.md): the ONE masked runner
    # is reused across capacity buckets, so each new bucket shape shows
    # up as a retrace of the same program in the trace — exactly the
    # ≤ log2(n) program economics _bucket_cap promises, now measurable.
    if not dist and decomp:
        from dpsvm_tpu.solver.decomp import (_build_decomp_runner,
                                             init_carry)
        runner = compilewatch.instrument(
            _build_decomp_runner(
                float(config.c), kspec, eps, q, inner_cap,
                precision_name, weights, pairwise,
                pallas_inner=config.use_pallas == "on", masked=True),
            f"shrink-decomp-chunk/q={q}")
    elif not dist:
        from dpsvm_tpu.solver.smo import _build_chunk_runner, init_carry
        runner = compilewatch.instrument(
            _build_chunk_runner(
                float(config.c), kspec, eps, False, precision_name,
                config.selection == "second-order", weights,
                config.select_impl == "packed", pairwise,
                guard_eta=guard_eta, masked=True),
            "shrink-smo-chunk")

    def make_active(idx: np.ndarray):
        """(step, pull, carry) for the active subproblem.

        ``step(carry, limit) -> (carry, stats)`` runs one chunk;
        ``pull(carry) -> (alpha_act, f_act)`` reads the state back. The
        distributed mode builds a fresh SPMD runner per active size
        (padding/shardings change with it — the same ≤ log2(n) program
        bound as the single-device path)."""
        if dist:
            return _make_active_dist(idx)
        n_act = len(idx)
        cap = _bucket_cap(max(n_act, min_active), n)
        pad = cap - n_act
        if n_act == n:
            xa = xd_full
        else:
            xa = jnp.take(xd_full, jax.device_put(jnp.asarray(idx),
                                                  device), axis=0)
        if pad:
            # Inert capacity padding: zero rows, +1 labels, alpha 0 —
            # the runner's valid mask (rows < n_act) keeps them out of
            # every selection rule, so values only need to be finite.
            xa = jnp.concatenate(
                [xa, jnp.zeros((pad, xa.shape[1]), xa.dtype)])
            ya_np = np.concatenate([y_np[idx], np.ones(pad, np.float32)])
            x2a_np = np.concatenate([x2_np[idx],
                                     np.zeros(pad, np.float32)])
            a_seed = np.concatenate([alpha[idx],
                                     np.zeros(pad, np.float32)])
            f_seed = np.concatenate([f[idx],
                                     np.full(pad, SENTINEL, np.float32)])
        else:
            ya_np, x2a_np = y_np[idx], x2_np[idx]
            a_seed, f_seed = alpha[idx].copy(), f[idx].copy()
        ya = jax.device_put(jnp.asarray(ya_np), device)
        x2a = jax.device_put(jnp.asarray(x2a_np), device)
        carry = init_carry(ya_np) if decomp else init_carry(
            ya_np, cache_lines=0)
        carry = carry._replace(alpha=a_seed, f=f_seed)
        if device is not None:
            carry = jax.device_put(carry, device)
        step = lambda c, lim: runner(c, xa, ya, x2a, np.int32(n_act),
                                     np.int32(lim))
        pull = lambda c: (np.asarray(c.alpha)[:n_act],
                          np.asarray(c.f)[:n_act])
        # New active size => new compile on first step; fresh stall
        # window (same reason as the distributed builder below).
        watchdog.pet()
        return step, pull, carry

    placed_full = []        # cached full-set placement: every unshrink
                            # returns to idx == arange(n), and re-paying
                            # the full n x d H2D there is the exact cost
                            # class the single-device path's xd_full
                            # cache avoids

    def _make_active_dist(idx: np.ndarray):
        """SPMD subproblem over the mesh: the shared pad-and-shard
        protocol (parallel/dist_smo.prepare_distributed_inputs) places
        the active slice; the carry is seeded fresh from the manager's
        host state."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from dpsvm_tpu.parallel.dist_smo import prepare_distributed_inputs
        from dpsvm_tpu.parallel.mesh import SHARD_AXIS

        n_act = len(idx)
        # Same power-of-two capacity policy as the single-device path:
        # the SPMD programs are shape-keyed on n_s = capacity / p, so
        # quantized capacities bound the program count at log2(n)
        # across all shrink cycles; rows in [n_act, cap) are zero
        # padding marked invalid by prepare's valid mask.
        cap = _bucket_cap(max(n_act, min_active), n)
        if n_act == n and placed_full:
            di = placed_full[0]
        else:
            di = prepare_distributed_inputs(x[idx], y_np[idx], config,
                                            mesh, None, None, None,
                                            capacity=cap)
            if n_act == n:
                placed_full.append(di)
        n_s = di.n_s
        n_pad = n_s * p
        pad1 = lambda v: np.concatenate(
            [v, np.zeros(n_pad - n_act, v.dtype)])
        a_seed = jax.device_put(pad1(alpha[idx]), di.shard)
        f_seed = jax.device_put(pad1(f[idx]), di.shard)
        b_hi0 = jax.device_put(np.float32(-SENTINEL), di.repl)
        b_lo0 = jax.device_put(np.float32(SENTINEL), di.repl)
        it0 = jax.device_put(np.int32(0), di.repl)

        if decomp:
            from dpsvm_tpu.parallel.dist_decomp import (
                DistDecompCarry, _build_dist_decomp_runner)
            run = compilewatch.instrument(
                _build_dist_decomp_runner(
                    mesh, float(config.c), kspec, eps, n_s, q,
                    inner_cap, bool(config.shard_x), precision_name,
                    weights, pairwise),
                f"shrink-dist-decomp-chunk/n_s={n_s}")
            carry = DistDecompCarry(alpha=a_seed, f=f_seed, b_hi=b_hi0,
                                    b_lo=b_lo0, n_iter=it0,
                                    rounds=jax.device_put(np.int32(0),
                                                          di.repl))
        else:
            from dpsvm_tpu.parallel.dist_smo import (DistCarry,
                                                     _build_dist_runner)
            run = compilewatch.instrument(
                _build_dist_runner(
                    mesh, float(config.c), kspec, eps, n_s,
                    bool(config.shard_x), precision_name,
                    config.selection == "second-order", weights,
                    use_cache=False,
                    packed_select=config.select_impl == "packed",
                    pairwise_clip=pairwise, guard_eta=guard_eta),
                f"shrink-dist-smo-chunk/n_s={n_s}")
            carry = DistCarry(
                alpha=a_seed, f=f_seed, b_hi=b_hi0, b_lo=b_lo0,
                n_iter=it0,
                ck=jax.device_put(np.full((0,), -1, np.int32), di.shard),
                cs=jax.device_put(np.zeros((0,), np.int32), di.shard),
                cr=jax.device_put(np.zeros((0, n_s), np.float32),
                                  NamedSharding(mesh,
                                                P(SHARD_AXIS, None))),
                ch=jax.device_put(np.int32(0), di.repl),
                cm=jax.device_put(np.int32(0), di.repl))

        def step(c, lim):
            return run(c, di.xd, di.yd, di.x2, di.validd,
                       jax.device_put(np.int32(lim), di.repl))

        pull = lambda c: (to_host(c.alpha)[:n_act], to_host(c.f)[:n_act])
        # Each rebuild means a fresh program (new active size) whose
        # first step pays a full compile; give the stall watchdog a
        # fresh window so a healthy compile is never killed as a stall.
        watchdog.pet()
        return step, pull, carry

    # Run telemetry (docs/OBSERVABILITY.md): the manager emits the same
    # trace schema as the shared driver — chunk records read from the
    # runners' packed stats (n_sv/counters describe the ACTIVE
    # subproblem; n_active rides each record), plus shrink/unshrink
    # events marking every active-set transition.
    trace = begin_trace(config, n, d, gamma, "shrink")

    active = np.arange(n)
    step, pull, carry = make_active(active)
    it = 0
    last_check = 0
    # Setup/H2D done; fresh stall-watchdog window for the first compile.
    watchdog.pet()
    try:
        while True:
            limit = min(it + chunk, config.max_iter)
            prev_polled = it
            carry, stats = step(carry, limit)
            st = read_stats(stats)
            it, b_lo, b_hi = st.n_iter, st.b_lo, st.b_hi
            sub_converged = not (b_lo > b_hi + 2.0 * eps)
            capped = it >= config.max_iter
            if (not capped and config.wall_budget_s
                    and time.perf_counter() - t0 > config.wall_budget_s):
                # Time budget exhausted: same exit path as the iteration
                # cap (scatter back, unshrink-reconstruct if compacted,
                # report the honest full-problem convergence state).
                capped = True
                if trace is not None:
                    trace.event("wall_budget", n_iter=it)
            if not capped:   # the final=True line after the loop reports
                log_progress(config, it, b_lo, b_hi, final=False,
                             prev_iter=prev_polled)
            if trace is not None:
                # Same poll-boundary device facts as the shared driver:
                # pending compile observations (the manager's own
                # rebuilds land here) and the HBM watermark.
                drain_compiles(trace, it)
                trace.chunk(n_iter=it, b_lo=b_lo, b_hi=b_hi,
                            n_sv=st.n_sv, cache_hits=st.cache_hits,
                            cache_misses=st.cache_misses,
                            rounds=st.rounds, n_active=len(active),
                            hbm=memory_snapshot())

            if sub_converged or capped:
                # Scatter the subproblem's state back.
                alpha[active], f[active] = pull(carry)
                if len(active) == n:
                    converged = sub_converged
                    break
                # Unshrink: exact f for the frozen rows, then the REAL
                # optimality check on the full problem.
                mask = np.zeros(n, bool)
                mask[active] = True
                f = _reconstruct_inactive_f(x, y_np, alpha, f, alpha0,
                                            f0, mask, kspec)
                b_hi, b_lo = _host_extrema(alpha, y_np, f, c_box)
                converged = not (b_lo > b_hi + 2.0 * eps)
                if trace is not None:
                    trace.event("unshrink", n_iter=it,
                                n_active_before=len(active),
                                n_active_after=n,
                                full_problem_converged=converged)
                if converged or capped:
                    break
                # Not there yet: continue on the full problem (and allow
                # re-shrinking as the new tail converges). The iteration
                # count must survive the rebuild — a fresh carry's
                # n_iter=0 would grant the loop a whole new max_iter
                # budget. The reconstructed extrema ride along so the
                # next chunk's entry state is the real one.
                active = np.arange(n)
                step, pull, carry = make_active(active)
                carry = carry._replace(n_iter=np.int32(it),
                                       b_hi=np.float32(b_hi),
                                       b_lo=np.float32(b_lo))
                continue

            # Mid-training shrink check (LIBSVM checks every min(n,1000)
            # iterations). Each check pulls (alpha, f) — two D2H
            # transfers whose round-trip costs ~65-100 ms on a tunneled
            # TPU — so it runs at most every SHRINK_CHECK_ITERS
            # iterations, not at every small chunk poll. Compact only
            # when the active set halves — each distinct active size is
            # its own XLA program.
            if it - last_check < min(SHRINK_CHECK_ITERS, n):
                continue
            last_check = it
            a_act, f_act = pull(carry)
            shrink = _shrinkable(a_act, y_np[active], f_act,
                                 c_box[active], b_hi, b_lo)
            keep = int(len(active) - shrink.sum())
            if keep <= len(active) // 2 and keep >= min_active:
                alpha[active] = a_act
                f[active] = f_act
                if trace is not None:
                    trace.event("shrink", n_iter=it,
                                n_active_before=len(active),
                                n_active_after=keep)
                active = active[~shrink]
                step, pull, new_carry = make_active(active)
                # Preserve the loop bookkeeping (n_iter and the stopping
                # state survive the compaction; selection state is
                # recomputed next chunk anyway).
                carry = new_carry._replace(
                    n_iter=np.int32(it),
                    b_hi=np.float32(b_hi), b_lo=np.float32(b_lo))

        log_progress(config, it, b_lo, b_hi, final=True)
        result = TrainResult(
            alpha=alpha,
            b=(b_lo + b_hi) / 2.0,
            n_iter=it,
            converged=converged,
            b_lo=b_lo,
            b_hi=b_hi,
            train_seconds=time.perf_counter() - t0,
            gamma=gamma,
            n_sv=int(np.sum(alpha > 0)),
            kernel=config.kernel,
            coef0=float(config.coef0),
            degree=int(config.degree),
        )
        if trace is not None:
            drain_compiles(trace, result.n_iter)
            trace.summary(converged=result.converged,
                          n_iter=result.n_iter, b=result.b,
                          b_lo=result.b_lo, b_hi=result.b_hi,
                          n_sv=result.n_sv,
                          train_seconds=result.train_seconds)
        return result
    finally:
        drain_compiles(None)        # never leak into the next run
        if trace is not None:
            trace.close()

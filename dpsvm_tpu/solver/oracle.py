"""NumPy golden-reference SMO solver.

Role-equivalent of the reference's sequential trainer ``seq.cpp`` (the
readable single-threaded implementation used to validate the accelerated
path — SURVEY §4.2), with semantics matched to the *distributed* trainer,
which is the canonical one:

* index sets I_up / I_low per Keerthi (``seq.cpp:469-553``; fused GPU form
  ``svmTrain.cu:54-91`` with the +/-1e9 sentinels reproduced here);
* first-order working-set selection: I_hi = argmin_{I_up} f,
  I_lo = argmax_{I_low} f (``svmTrain.cu:476-481``);
* eta = K(hi,hi) + K(lo,lo) - 2 K(hi,lo) (``svmTrainMain.cpp:282``);
* alpha_lo' = alpha_lo + y_lo (b_hi - b_lo)/eta;
  alpha_hi' = alpha_hi + s (alpha_lo - alpha_lo') with s = y_lo y_hi,
  using the UNCLIPPED alpha_lo'; then both independently clipped to [0, C]
  (``svmTrainMain.cpp:289-295`` — deliberately not the textbook pairwise
  box clip; reproduced bit-for-bit for parity);
* f_i += dAlpha_hi y_hi K(hi, i) + dAlpha_lo y_lo K(lo, i) with
  K(a, i) = exp(-gamma (|x_i|^2 + |x_a|^2 - 2 x_a.x_i))
  (``svmTrain.cu:128-135``);
* do-while loop: the update is applied on the iteration that detects
  convergence, and the loop exits when NOT (b_lo > b_hi + 2 eps) or the
  iteration cap is hit (``svmTrainMain.cpp:310``); b = (b_lo + b_hi)/2
  (``svmTrainMain.cpp:329``).

Arithmetic is float32 throughout (the reference is all-float32) so that
the XLA solver can be compared against it tightly. Tie-breaking for
argmin/argmax is first-index-wins — the reference's Thrust reduce order is
nondeterministic on ties (``svmTrain.cu:400-467``), so this framework
standardizes the rule across oracle, single-device, and distributed paths.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

import numpy as np

from dpsvm_tpu.config import SENTINEL, SVMConfig, TrainResult


def _np_rows_from_dots(dots: np.ndarray, w2, x2: np.ndarray,
                       spec) -> np.ndarray:
    """NumPy mirror of ops.kernels.rows_from_dots (float32 throughout).

    The RBF branch keeps the oracle's original expression byte-for-byte;
    the other LIBSVM kernels share the iteration with it.
    """
    if spec.kind == "rbf":
        return np.exp((-np.float32(spec.gamma)
                       * (x2 + w2 - 2.0 * dots)).astype(np.float32))
    if spec.kind == "linear":
        return dots
    if spec.kind == "poly":
        return ((np.float32(spec.gamma) * dots + np.float32(spec.coef0))
                ** spec.degree).astype(np.float32)
    if spec.kind == "sigmoid":
        return np.tanh(np.float32(spec.gamma) * dots
                       + np.float32(spec.coef0)).astype(np.float32)
    raise ValueError(f"unknown kernel kind {spec.kind!r}")


def _np_kdiag(x2: np.ndarray, spec) -> np.ndarray:
    """K(i, i) per example (non-RBF kernels; RBF keeps the literal 2-2K)."""
    if spec.kind == "linear":
        return x2
    if spec.kind == "poly":
        return ((np.float32(spec.gamma) * x2 + np.float32(spec.coef0))
                ** spec.degree).astype(np.float32)
    if spec.kind == "sigmoid":
        return np.tanh(np.float32(spec.gamma) * x2
                       + np.float32(spec.coef0)).astype(np.float32)
    raise ValueError(f"unknown kernel kind {spec.kind!r}")


def iup_ilow_masks(alpha: np.ndarray, y: np.ndarray, c
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Keerthi index-set membership masks (svmTrain.cu:54-91 semantics).

    alpha == 0, y == +1 -> I_up only;  alpha == 0, y == -1 -> I_low only;
    alpha == C, y == -1 -> I_up only;  alpha == C, y == +1 -> I_low only;
    0 < alpha < C        -> both.
    Exact comparisons are safe: clipping writes exactly 0.0 or C.
    c may be a scalar or a per-example array (class-weighted costs).
    """
    at0 = alpha == 0.0
    atc = alpha == np.float32(c) if np.isscalar(c) else alpha == c
    interior = ~at0 & ~atc
    pos = y > 0
    in_up = interior | (at0 & pos) | (atc & ~pos)
    in_low = interior | (at0 & ~pos) | (atc & pos)
    return in_up, in_low


def smo_reference(
    x: np.ndarray,
    y: np.ndarray,
    config: SVMConfig,
    trace: Optional[List] = None,
    f_init: Optional[np.ndarray] = None,
    alpha_init: Optional[np.ndarray] = None,
    guard_eta: bool = False,
) -> TrainResult:
    """Train a binary RBF-SVM with the modified-SMO algorithm in NumPy.

    When ``trace`` is a list, one tuple ``(i_hi, i_lo, b_hi, b_lo)`` is
    appended per iteration for step-by-step parity tests against the XLA
    solvers.
    """
    config.validate()
    t0 = time.perf_counter()
    x = np.ascontiguousarray(x, dtype=np.float32)
    n, d = x.shape
    yf = np.asarray(y, dtype=np.float32)
    # Per-example box bound: C * class weight (scalar stays scalar for
    # exact parity with the unweighted reference path).
    if config.weight_pos == 1.0 and config.weight_neg == 1.0:
        c = np.float32(config.c)
    else:
        c = np.where(np.asarray(y) > 0,
                     np.float32(config.c * config.weight_pos),
                     np.float32(config.c * config.weight_neg))
    gamma = np.float32(config.resolve_gamma(d))
    kspec = config.kernel_spec(d)
    eps = np.float32(config.epsilon)
    sent = np.float32(SENTINEL)

    x2 = np.einsum("ij,ij->i", x, x).astype(np.float32)
    alpha = (np.zeros(n, dtype=np.float32) if alpha_init is None
             else np.asarray(alpha_init, np.float32).copy())
    f = ((-yf) if f_init is None
         else np.asarray(f_init, np.float32)).copy()

    second_order = config.selection == "second-order"
    pairwise_clip = config.clip == "pairwise"

    n_iter = 0
    b_hi = np.float32(-sent)
    b_lo = np.float32(sent)
    while True:
        in_up, in_low = iup_ilow_masks(alpha, yf, c)
        f_up = np.where(in_up, f, sent)
        f_low = np.where(in_low, f, -sent)
        i_hi = int(np.argmin(f_up))
        b_hi = f_up[i_hi]
        # b_lo (the max violator) is always the STOPPING gap and the
        # source of the intercept, regardless of selection rule.
        b_lo = f_low[int(np.argmax(f_low))]

        k_hi = None
        if second_order:
            # WSS2 (Fan/Chen/Lin 2005, the LIBSVM rule): among violators
            # j in I_low with f_j > b_hi, maximize (f_j - b_hi)^2 / a_j
            # with a_j = K_ii + K_jj - 2 K_ij = 2 - 2 K(hi, j) for RBF.
            dots_hi = (x[i_hi] @ x.T).astype(np.float32)
            k_hi = _np_rows_from_dots(dots_hi, x2[i_hi], x2, kspec)
            bb = f_low - b_hi
            if kspec.kind == "rbf":
                a = np.maximum(2.0 - 2.0 * k_hi, np.float32(1e-12))
            else:
                kd = _np_kdiag(x2, kspec)
                a = np.maximum(kd[i_hi] + kd - 2.0 * k_hi,
                               np.float32(1e-12))
            obj = np.where(in_low & (bb > 0), bb * bb / a, np.float32(-1.0))
            i_lo = int(np.argmax(obj))
        else:
            i_lo = int(np.argmax(f_low))
        if trace is not None:
            trace.append((i_hi, i_lo, float(b_hi), float(b_lo)))

        if second_order:
            dots_lo = (x[i_lo] @ x.T).astype(np.float32)
            k_lo = _np_rows_from_dots(dots_lo, x2[i_lo], x2, kspec)
            k = np.stack([k_hi, k_lo])
        else:
            rows = x[(i_hi, i_lo), :]                   # (2, d)
            dots = (rows @ x.T).astype(np.float32)      # (2, n)
            w2 = x2[(i_hi, i_lo),]
            k = _np_rows_from_dots(dots, w2[:, None], x2[None, :], kspec)
        eta = k[0, i_hi] + k[1, i_lo] - 2.0 * k[0, i_lo]
        if second_order or guard_eta:
            # Clamped like the WSS2 selection denominator (and LIBSVM's
            # TAU). ``guard_eta`` (set by the SVR/one-class wrappers)
            # applies the same clamp under first-order: SVR's stacked
            # twin rows make eta == 0 reachable (see solver/smo.py). The
            # plain classification path keeps the reference's raw
            # division.
            eta = np.float32(max(eta, 1e-12))

        y_hi = yf[i_hi]
        y_lo = yf[i_lo]
        a_hi = alpha[i_hi]
        a_lo = alpha[i_lo]
        s = y_lo * y_hi
        # The alpha step uses the SELECTED pair's f values; under
        # first-order selection f_low[i_lo] == b_lo, under second-order
        # the chosen violator may not be the max one.
        b_lo_sel = f_low[i_lo]
        a_lo_u = np.float32(a_lo + y_lo * (b_hi - b_lo_sel) / eta)
        c_lo = np.float32(c if np.isscalar(c) else c[i_lo])
        c_hi = np.float32(c if np.isscalar(c) else c[i_hi])
        if pairwise_clip:
            # textbook/LIBSVM joint box; bound hits set the partner to
            # the LITERAL corner value (exact-comparison masks — see
            # ops/update.py for the full rationale)
            if s > 0:
                ssum = np.float32(a_lo + a_hi)
                lo_b = max(np.float32(0.0), np.float32(ssum - c_hi))
                hi_b = min(c_lo, ssum)
                if a_lo_u <= lo_b:
                    a_lo_n = lo_b
                    a_hi_n = c_hi if lo_b > 0 else ssum
                elif a_lo_u >= hi_b:
                    a_lo_n = hi_b
                    a_hi_n = (np.float32(ssum - c_lo) if hi_b == c_lo
                              else np.float32(0.0))
                else:
                    a_lo_n = a_lo_u
                    a_hi_n = np.float32(a_hi + s * (a_lo - a_lo_u))
            else:
                diff = np.float32(a_hi - a_lo)
                lo_b = max(np.float32(0.0), np.float32(a_lo - a_hi))
                hi_b = min(c_lo, np.float32(a_lo + c_hi - a_hi))
                if a_lo_u <= lo_b:
                    a_lo_n = lo_b
                    a_hi_n = np.float32(0.0) if lo_b > 0 else diff
                elif a_lo_u >= hi_b:
                    a_lo_n = hi_b
                    a_hi_n = (np.float32(diff + c_lo) if hi_b == c_lo
                              else c_hi)
                else:
                    a_lo_n = a_lo_u
                    a_hi_n = np.float32(a_hi + s * (a_lo - a_lo_u))
        else:
            a_hi_u = np.float32(a_hi + s * (a_lo - a_lo_u))
            a_lo_n = np.float32(min(max(a_lo_u, np.float32(0.0)), c_lo))
            a_hi_n = np.float32(min(max(a_hi_u, np.float32(0.0)), c_hi))
        alpha[i_lo] = a_lo_n
        alpha[i_hi] = a_hi_n
        f = (f + (a_hi_n - a_hi) * y_hi * k[0]
               + (a_lo_n - a_lo) * y_lo * k[1]).astype(np.float32)

        n_iter += 1
        if not (b_lo > b_hi + 2.0 * eps) or n_iter >= config.max_iter:
            break

    b = float((b_lo + b_hi) / 2.0)
    converged = bool(b_lo <= b_hi + 2.0 * eps)
    return TrainResult(
        alpha=alpha,
        b=b,
        n_iter=n_iter,
        converged=converged,
        b_lo=float(b_lo),
        b_hi=float(b_hi),
        train_seconds=time.perf_counter() - t0,
        gamma=float(gamma),
        n_sv=int(np.sum(alpha > 0)),
        kernel=config.kernel,
        coef0=float(config.coef0),
        degree=int(config.degree),
    )
